"""PyDataProvider2 equivalent: the @provider decorator + async batch pipeline.

Parity targets:
- `@provider` decorator + input-type system —
  python/paddle/trainer/PyDataProvider2.py:365 and :63-236; the C++ host that
  embeds it (paddle/gserver/dataproviders/PyDataProvider2.cpp:195) becomes a
  plain Python driver since there is no C++/Python boundary to cross here.
- async double-buffering — DataProvider.h:249 `DoubleBuffer` (a background
  thread keeps N batches ahead so host input prep overlaps device steps; on TPU
  this hides feeder/numpy time behind the compiled step's async dispatch).
- `MultiDataProvider` ratio mixing — gserver/dataproviders/MultiDataProvider.cpp.
"""

from __future__ import annotations

import functools
import logging
import random
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu.data.feeder import DataFeeder, InputSpec

log = logging.getLogger("paddle_tpu.provider")


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class Settings:
    """The `settings` object handed to user providers (PyDataProvider2.py's
    DataProviderSettings): carries input_types plus anything init_hook sets."""

    def __init__(self, input_types=None, **kwargs):
        self.input_types = input_types
        self.logger = log
        for k, v in kwargs.items():
            setattr(self, k, v)

    # the reference exposes the same field under both names; init_hooks in the
    # wild assign either `settings.slots = [...]` or `settings.input_types`
    @property
    def slots(self):
        return self.input_types

    @slots.setter
    def slots(self, value):
        self.input_types = value


class DataProviderWrapper:
    """Result of @provider: callable over file list(s), exposing the reader
    protocol (`__call__(obj, *files) -> iterator of samples`) plus metadata."""

    def __init__(
        self,
        generator: Callable,
        input_types=None,
        should_shuffle: Optional[bool] = None,
        pool_size: int = -1,
        min_pool_size: int = -1,
        can_over_batch_size: bool = True,
        calc_batch_size: Optional[Callable] = None,
        cache: int = CacheType.NO_CACHE,
        init_hook: Optional[Callable] = None,
        check: bool = False,
        check_fail_continue: bool = False,
    ):
        self.generator = generator
        self.input_types = input_types
        # None keeps the reference semantics: shuffle during training only
        # (PyDataProvider2.py provider(): should_shuffle=None → train-only)
        self.should_shuffle = should_shuffle
        self.pool_size = pool_size
        self.min_pool_size = min_pool_size
        self.can_over_batch_size = can_over_batch_size
        self.calc_batch_size = calc_batch_size
        self.cache = cache
        self.init_hook = init_hook
        self.check = check
        self.check_fail_continue = check_fail_continue
        # pass cache keyed by file_list so train/test calls don't cross-serve
        self._pass_cache: Dict[tuple, List[Any]] = {}
        self._epoch = 0  # reshuffle differently each pass, like the reference
        functools.wraps(generator)(self)

    # -- settings -----------------------------------------------------------
    def make_settings(self, obj=None, file_list: Sequence[str] = (), **kwargs) -> Settings:
        settings = Settings(input_types=self.input_types)
        if self.init_hook is not None:
            self.init_hook(settings, obj=obj, file_list=list(file_list), **kwargs)
        return settings

    # -- iteration ----------------------------------------------------------
    def __call__(
        self,
        obj=None,
        file_list: Union[str, Sequence[str], None] = None,
        is_train: bool = True,
        **kwargs,
    ):
        """Returns an iterator over samples from all files (shuffle-pooled like
        the reference's pool_size window shuffle). `is_train=False` (test /
        inference readers) disables the default shuffle, matching the
        reference's should_shuffle=None train-only semantics."""
        if isinstance(file_list, str):
            file_list = [file_list]
        file_list = list(file_list or [None])
        settings = self.make_settings(obj=obj, file_list=file_list, **kwargs)
        cache_key = tuple(file_list)

        def iter_all():
            cached = self._pass_cache.get(cache_key)
            if self.cache == CacheType.CACHE_PASS_IN_MEM and cached is not None:
                yield from cached
                return
            collected = [] if self.cache == CacheType.CACHE_PASS_IN_MEM else None
            for fname in file_list:
                gen = (
                    self.generator(settings, fname)
                    if fname is not None
                    else self.generator(settings)
                )
                for sample in gen:
                    if self.check and not _check_sample(settings.input_types, sample):
                        if self.check_fail_continue:
                            continue
                        raise ValueError(f"sample fails input_types check: {sample!r}")
                    if collected is not None:
                        collected.append(sample)
                    yield sample
            if collected is not None:
                # only a fully consumed pass is a valid cache
                self._pass_cache[cache_key] = collected

        it = iter_all()
        shuffle = is_train if self.should_shuffle is None else self.should_shuffle
        if shuffle:
            pool = self.pool_size if self.pool_size > 0 else 1000
            if self.min_pool_size > 0:
                pool = max(pool, self.min_pool_size)
            self._epoch += 1
            return _pool_shuffle(it, pool, seed=self._epoch)
        return it

    # -- reader-creator adapter ---------------------------------------------
    def as_reader(self, obj=None, file_list=None, **kwargs) -> Callable:
        """v2 reader creator: provider ported datasets plug into paddle.batch."""

        def reader():
            return self(obj=obj, file_list=file_list, **kwargs)

        return reader


def provider(input_types=None, **kwargs):
    """The @provider decorator (PyDataProvider2.py:365).

    Usage (verbatim from reference demos)::

        @provider(input_types={'pixel': dense_vector(784),
                               'label': integer_value(10)})
        def process(settings, filename):
            for ...: yield {'pixel': ..., 'label': ...}
    """

    def wrap(fn):
        return DataProviderWrapper(fn, input_types=input_types, **kwargs)

    return wrap


def _pool_shuffle(it: Iterable, pool_size: int, seed: int = 0):
    rnd = random.Random(seed)
    pool: List[Any] = []
    for item in it:
        pool.append(item)
        if len(pool) >= pool_size:
            rnd.shuffle(pool)
            yield from pool
            pool = []
    rnd.shuffle(pool)
    yield from pool


def _check_sample(input_types, sample) -> bool:
    if input_types is None:
        return True
    specs = (
        list(input_types.values()) if isinstance(input_types, dict) else list(input_types)
    )
    try:
        if isinstance(sample, dict):
            if not isinstance(input_types, dict):
                return False
            values = [sample[k] for k in input_types]
        elif isinstance(sample, (list, tuple)):
            values = list(sample)
        else:
            values = [sample]
    except KeyError:
        return False
    if len(values) != len(specs):
        return False
    for v, spec in zip(values, specs):
        if spec.kind == "index" and not (
            np.isscalar(v) or (isinstance(v, np.ndarray) and v.ndim == 0)
        ):
            return False
        if spec.kind == "dense":
            dim = spec.dim if isinstance(spec.dim, tuple) else (spec.dim,)
            if int(np.prod(np.shape(v))) != int(np.prod(dim)):
                return False
    return True


# ---------------------------------------------------------------------------
# MultiDataProvider: ratio-mixed sub-providers
# ---------------------------------------------------------------------------


class MultiDataProvider:
    """Mixes sub-readers by sampling ratio (MultiDataProvider.cpp). Each entry
    is (reader_creator, ratio); one mixed stream is produced per pass."""

    def __init__(self, providers: Sequence, seed: int = 0):
        self.entries = [(r, float(ratio)) for r, ratio in providers]
        total = sum(r for _, r in self.entries)
        self.probs = [r / total for _, r in self.entries]
        self.seed = seed
        self._epoch = 0  # vary the mixing order per pass

    def __call__(self):
        self._epoch += 1
        rnd = random.Random(self.seed * 1000003 + self._epoch)
        iters = [iter(r()) for r, _ in self.entries]
        alive = list(range(len(iters)))
        while alive:
            i = rnd.choices(alive, weights=[self.probs[j] for j in alive])[0]
            try:
                yield next(iters[i])
            except StopIteration:
                alive.remove(i)


# ---------------------------------------------------------------------------
# DoubleBuffer: background prefetch of converted batches
# ---------------------------------------------------------------------------


class DoubleBuffer:
    """Async batch prefetcher (DataProvider.h:249).

    Wraps a batched reader (+ optional feeder) and keeps up to `capacity`
    ready-to-feed batches in a background thread, so numpy conversion overlaps
    device execution. Use as: `for batch in DoubleBuffer(reader, feeder): ...`;
    one iteration = one pass.

    Host-side only: batches still pay sharding + H2D on the consumer.
    `data.pipeline.DevicePrefetcher` subsumes this (feeder AND device
    placement off-thread); a DoubleBuffer also composes as the reader of a
    DevicePrefetcher, which then adds just the device leg."""

    def __init__(self, reader: Callable, feeder: Optional[DataFeeder] = None, capacity: int = 4):
        self.reader = reader
        self.feeder = feeder
        self.capacity = capacity

    def __call__(self):
        return iter(self)

    def __iter__(self):
        from paddle_tpu.data.pipeline import iter_async

        prepare = self.feeder if self.feeder is not None else (lambda raw: raw)
        return iter_async(
            self.reader, prepare, self.capacity,
            name="paddle-tpu-double-buffer",
        )


# ---------------------------------------------------------------------------
# DataProviderConverter (py_paddle/dataprovider_converter.py)
# ---------------------------------------------------------------------------


class DataProviderConverter:
    """input_types (list or dict) + names → DataFeeder; mirrors the SWIG-era
    converter that turned numpy/scipy rows into C++ Arguments."""

    def __init__(self, input_types, names: Optional[Sequence[str]] = None):
        if isinstance(input_types, dict):
            feeding = dict(input_types)
        else:
            names = list(names or [f"slot{i}" for i in range(len(input_types))])
            feeding = dict(zip(names, input_types))
        self.feeder = DataFeeder(feeding)

    def __call__(self, samples) -> Dict[str, np.ndarray]:
        return self.feeder(samples)
