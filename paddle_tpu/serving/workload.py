"""Closed-loop serving workloads: N concurrent streams vs sequential.

Shared by benchmarks/serving_bench.py and the bench.py serving leg so the
acceptance numbers and the tracked metric are the same code path.

A "stream" models one user connection: it keeps exactly one request in
flight, submitting its next request the moment the previous one completes —
so `concurrency=N` holds N requests live and continuous batching gets to
fill up to N slots per decode step. `concurrency=1` IS the sequential
per-request baseline (same executables, same platform, same shapes): the
measured speedup isolates dynamic batching, not kernel differences."""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np


def make_prompts(
    n: int,
    lengths: Sequence[int],
    vocab: int,
    bos_id: int,
    seed: int = 0,
) -> List[List[int]]:
    """Deterministic mixed-length prompts (BOS + random ids; never EOS so
    lengths are workload-controlled, not sampling-controlled)."""
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        ln = int(lengths[i % len(lengths)])
        body = rs.randint(3, vocab, size=ln - 1)
        out.append([bos_id] + [int(t) for t in body])
    return out


def run_closed_loop(
    session,
    prompts: List[List[int]],
    max_new_tokens: int,
    concurrency: int,
    tenant: str = "default",
) -> Dict:
    """Drive `session` single-threaded: keep up to `concurrency` requests in
    flight, stepping the engine until all prompts complete. Returns
    tokens/sec plus p50/p99 request latency."""
    pending = list(enumerate(prompts))
    in_flight = {}  # request_id -> (index, handle)
    latencies_ms: List[float] = []
    tokens_out = 0
    results: List[Optional[List[int]]] = [None] * len(prompts)

    t0 = time.monotonic()
    while pending or in_flight:
        while pending and len(in_flight) < concurrency:
            idx, prompt = pending.pop(0)
            h = session.submit(prompt, max_new_tokens, tenant=tenant)
            in_flight[h.request_id] = (idx, h)
        session.step()
        done = [rid for rid, (_, h) in in_flight.items() if h.done]
        for rid in done:
            idx, h = in_flight.pop(rid)
            results[idx] = h.tokens
            tokens_out += len(h.tokens)
            latencies_ms.append((h.t_done - h.t_submit) * 1e3)
    dt = time.monotonic() - t0

    lat = np.asarray(latencies_ms)
    return {
        "concurrency": concurrency,
        "requests": len(prompts),
        "tokens": tokens_out,
        "wall_s": round(dt, 4),
        "tokens_per_sec": round(tokens_out / dt, 1) if dt > 0 else 0.0,
        "p50_latency_ms": round(float(np.percentile(lat, 50)), 2),
        "p99_latency_ms": round(float(np.percentile(lat, 99)), 2),
        "results": results,
    }
