"""Closed-loop serving workloads: N concurrent streams vs sequential.

Shared by benchmarks/serving_bench.py and the bench.py serving leg so the
acceptance numbers and the tracked metric are the same code path.

A "stream" models one user connection: it keeps exactly one request in
flight, submitting its next request the moment the previous one completes —
so `concurrency=N` holds N requests live and continuous batching gets to
fill up to N slots per decode step. `concurrency=1` IS the sequential
per-request baseline (same executables, same platform, same shapes): the
measured speedup isolates dynamic batching, not kernel differences."""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np


def make_prompts(
    n: int,
    lengths: Sequence[int],
    vocab: int,
    bos_id: int,
    seed: int = 0,
) -> List[List[int]]:
    """Deterministic mixed-length prompts (BOS + random ids; never EOS so
    lengths are workload-controlled, not sampling-controlled)."""
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        ln = int(lengths[i % len(lengths)])
        body = rs.randint(3, vocab, size=ln - 1)
        out.append([bos_id] + [int(t) for t in body])
    return out


def make_repetitive_prompts(
    n: int,
    motif_len: int,
    repeats: int,
    vocab: int,
    bos_id: int,
    seed: int = 0,
) -> List[List[int]]:
    """High-overlap prompts for the speculative-decoding legs (ISSUE 16):
    each prompt is BOS + a short random motif repeated, so the prompt-lookup
    drafter has dense n-gram matches from the first generated token. A
    per-prompt motif keeps the workload shape-diverse across requests while
    every individual request stays self-similar — the regime prompt-lookup
    speculation is built for (extraction, code edits, templated text)."""
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        motif = [int(t) for t in rs.randint(3, vocab, size=motif_len)]
        out.append([bos_id] + motif * repeats)
    return out


def make_shared_prefix_prompts(
    n: int,
    n_prefixes: int,
    prefix_len: int,
    suffix_len: int,
    vocab: int,
    bos_id: int,
    seed: int = 0,
) -> List[List[int]]:
    """The prefix-cache workload (ISSUE 19): `n_prefixes` distinct system
    prompts, each shared by `n // n_prefixes`-ish user turns that differ only
    in a short random suffix — the many-users-one-system-prompt regime the
    shared-prefix KV cache is built for. Prompts cycle round-robin over the
    prefixes so consecutive requests hit DIFFERENT chains (the adversarial
    order for a naive single-tail cache; a radix-over-pages index must not
    care). Every suffix is unique, so past the shared pages each request
    still pays its own prefill — the measured win isolates the prefix."""
    rs = np.random.RandomState(seed)
    prefixes = [
        [bos_id] + [int(t) for t in rs.randint(3, vocab, size=prefix_len - 1)]
        for _ in range(n_prefixes)
    ]
    out = []
    for i in range(n):
        suffix = [int(t) for t in rs.randint(3, vocab, size=suffix_len)]
        out.append(prefixes[i % n_prefixes] + suffix)
    return out


def make_mixed_prompts(
    n: int,
    short_lengths: Sequence[int],
    long_len: int,
    long_every: int,
    vocab: int,
    bos_id: int,
    seed: int = 0,
    burst: int = 3,
) -> List[List[int]]:
    """The chunked-prefill workload (ISSUE 11): a steady short-prompt stream
    with a BURST of `burst` long prompts joining every `long_every` requests
    mid-stream. Bursts are the adversarial arrival pattern for whole-prompt
    prefill: every long prompt admitted at one step boundary runs its full
    forward serially inside that single engine step, so the running streams'
    inter-token gap is burst_size × prefill — exactly the stall chunked
    prefill bounds to one chunk per step."""
    base = make_prompts(n, lengths=short_lengths, vocab=vocab, bos_id=bos_id,
                        seed=seed)
    rs = np.random.RandomState(seed + 1)
    for i in range(long_every // 2, n, long_every):
        for j in range(i, min(i + burst, n)):
            body = rs.randint(3, vocab, size=long_len - 1)
            base[j] = [bos_id] + [int(t) for t in body]
    return base


def run_closed_loop(
    session,
    prompts: List[List[int]],
    max_new_tokens,  # int, or a per-prompt list (staggers retirements)
    concurrency: int,
    tenant: str = "default",
    deadline_s: Optional[float] = None,
    ttft_deadline_s: Optional[float] = None,
) -> Dict:
    """Drive `session` single-threaded: keep up to `concurrency` requests in
    flight, stepping the engine until all prompts complete. Returns
    tokens/sec plus p50/p99/p999 request latency, the INTER-TOKEN latency
    percentiles (gap between consecutive tokens of one stream, observed at
    engine-step boundaries — the number a whole-prompt prefill stall shows
    up in and chunked prefill must keep flat, ISSUE 11), and (when deadlines
    are armed) the deadline-miss and shed columns — present either way, so
    bench rounds stay comparable. Throughput and the percentiles count only
    requests that COMPLETED: a deadline-cancelled request's partial tokens
    and truncated latency would otherwise flatter the overloaded run
    (higher tok/s, lower p99) exactly when it is failing."""
    from paddle_tpu.serving.quota import QuotaExceeded

    budgets = (
        list(max_new_tokens) if isinstance(max_new_tokens, (list, tuple))
        else [max_new_tokens] * len(prompts)
    )
    pending = list(enumerate(prompts))
    in_flight = {}  # request_id -> (index, handle)
    latencies_ms: List[float] = []
    itl_ms: List[float] = []  # inter-token gaps across ALL streams
    token_seen = {}  # request_id -> (token_count, t_last_token)
    tokens_out = 0
    shed = 0
    deadline_missed = 0
    results: List[Optional[List[int]]] = [None] * len(prompts)

    t0 = time.monotonic()
    while pending or in_flight:
        while pending and len(in_flight) < concurrency:
            idx, prompt = pending.pop(0)
            try:
                h = session.submit(
                    prompt, budgets[idx], tenant=tenant,
                    deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
                )
            except QuotaExceeded:
                shed += 1
                continue
            in_flight[h.request_id] = (idx, h)
            token_seen[h.request_id] = (0, None)
        session.step()
        now = time.monotonic()
        # inter-token latency: a stream's gap between consecutive tokens,
        # measured from this driver's step boundary (first token = TTFT,
        # excluded — ITL isolates the steady-stream stall a co-scheduled
        # prefill causes). A step may deliver SEVERAL tokens to one stream
        # (a speculative verify round, ISSUE 16): the gap amortizes over
        # them and each delivered token contributes ONE sample, so the
        # percentiles stay per-token — a multi-token step must pull p50
        # down in proportion to the tokens it delivered, not count once
        # alongside the single-token steps
        for rid, (_, h) in in_flight.items():
            n_prev, t_prev = token_seen[rid]
            n_now = len(h.tokens)
            if n_now > n_prev:
                if t_prev is not None:
                    gap = (now - t_prev) * 1e3 / (n_now - n_prev)
                    itl_ms.extend([gap] * (n_now - n_prev))
                token_seen[rid] = (n_now, now)
        done = [rid for rid, (_, h) in in_flight.items() if h.done]
        for rid in done:
            idx, h = in_flight.pop(rid)
            token_seen.pop(rid, None)
            if h.status == h.DONE:
                results[idx] = h.tokens
                tokens_out += len(h.tokens)
                latencies_ms.append((h.t_done - h.t_submit) * 1e3)
            elif h.finish_reason == "deadline":
                deadline_missed += 1
    dt = time.monotonic() - t0

    lat = np.asarray(latencies_ms) if latencies_ms else np.asarray([0.0])
    itl = np.asarray(itl_ms) if itl_ms else np.asarray([0.0])
    accepted = len(latencies_ms) + deadline_missed
    return {
        "concurrency": concurrency,
        "requests": len(prompts),
        "tokens": tokens_out,
        "wall_s": round(dt, 4),
        "tokens_per_sec": round(tokens_out / dt, 1) if dt > 0 else 0.0,
        "p50_latency_ms": round(float(np.percentile(lat, 50)), 2),
        "p99_latency_ms": round(float(np.percentile(lat, 99)), 2),
        "p999_latency_ms": round(float(np.percentile(lat, 99.9)), 2),
        "p50_inter_token_ms": round(float(np.percentile(itl, 50)), 3),
        "p99_inter_token_ms": round(float(np.percentile(itl, 99)), 3),
        "shed": shed,
        "deadline_misses": deadline_missed,
        "deadline_miss_ratio": round(deadline_missed / accepted, 4)
        if accepted else 0.0,
        "results": results,
    }


def expand_schedule(
    n: int,
    schedule: Sequence,  # [(duration_s, rate_rps), ...]
) -> List:
    """Flatten a time-varying load schedule into absolute arrival offsets.

    Each `(duration_s, rate_rps)` phase contributes evenly spaced arrivals
    for its duration (rate 0 = an idle phase: time passes, nothing arrives).
    Returns `[(offset_s, phase_idx), ...]`, at most `n` entries — shared by
    `run_open_loop` and the chaos bench's autoscale drill (which replays the
    same offsets against a ROUTER instead of an engine), so "the burst" is
    the identical arrival pattern in both."""
    arrivals = []
    t = 0.0
    for p, (dur, rate) in enumerate(schedule):
        dur = float(dur)
        rate = float(rate)
        if rate > 0:
            interval = 1.0 / rate
            k = 0
            while k * interval < dur and len(arrivals) < n:
                arrivals.append((t + k * interval, p))
                k += 1
        t += dur
    return arrivals


def run_open_loop(
    session,
    prompts: List[List[int]],
    max_new_tokens: int,
    rate_rps: Optional[float] = None,
    tenants: Sequence[str] = ("default",),
    deadline_s: Optional[float] = None,
    ttft_deadline_s: Optional[float] = None,
    schedule: Optional[Sequence] = None,
) -> Dict:
    """Open-loop (offered-load) driver — the overload model: arrivals land
    on a fixed offered schedule REGARDLESS of completions, so offered load
    above capacity builds a queue instead of throttling itself (the closed
    loop can never overload a server; this is what exercises shedding). The
    engine is driven inline on this thread, one step per iteration, arrivals
    replayed from the precomputed schedule, so a run is reproducible modulo
    host timing.

    Offered load is either a constant `rate_rps`, or a time-varying
    `schedule` of `(duration_s, rate_rps)` phases (ISSUE 17: the autoscale
    gate's idle → burst → idle shape). With a schedule, the report gains a
    `phases` list — per-phase offered/shed/goodput — because a burst phase's
    collapse would otherwise be averaged away by its idle neighbours.

    Goodput = requests that completed WITHIN their deadline per second of
    wall clock — the number the chaos bench's 2× overload gate compares
    against the at-capacity run."""
    from paddle_tpu.serving.quota import QuotaExceeded

    n = len(prompts)
    if schedule is not None:
        arrivals = expand_schedule(n, schedule)
        phase_specs = [(float(d), float(r)) for d, r in schedule]
    else:
        if rate_rps is None:
            raise ValueError("run_open_loop needs rate_rps or schedule")
        interval = 1.0 / float(rate_rps)
        arrivals = [(i * interval, 0) for i in range(n)]
        phase_specs = None
    handles = []
    handle_phase = []  # parallel to handles: arrival phase index
    shed = 0
    shed_by_phase: Dict[int, int] = {}
    offered_by_phase: Dict[int, int] = {}
    i = 0
    t0 = time.monotonic()
    while i < len(arrivals) or session.scheduler.has_work():
        now = time.monotonic()
        while i < len(arrivals) and t0 + arrivals[i][0] <= now:
            phase = arrivals[i][1]
            offered_by_phase[phase] = offered_by_phase.get(phase, 0) + 1
            try:
                handles.append(session.submit(
                    prompts[i], max_new_tokens,
                    tenant=tenants[i % len(tenants)],
                    deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
                ))
                handle_phase.append(phase)
            except QuotaExceeded:
                shed += 1
                shed_by_phase[phase] = shed_by_phase.get(phase, 0) + 1
            i += 1
        if session.scheduler.has_work():
            session.step(now)
        elif i < len(arrivals):
            time.sleep(max(0.0, min(0.002, t0 + arrivals[i][0] - now)))
    dt = time.monotonic() - t0

    completed_ok = sum(1 for h in handles if h.status == h.DONE)
    missed = sum(1 for h in handles if h.finish_reason == "deadline")
    offered_rps = (
        rate_rps if schedule is None
        else n / sum(d for d, _ in phase_specs)
        if phase_specs and sum(d for d, _ in phase_specs) > 0 else 0.0
    )
    report = {
        "offered_rps": round(float(offered_rps), 2),
        "requests_offered": len(arrivals),
        "accepted": len(handles),
        "shed": shed,
        "completed_ok": completed_ok,
        "deadline_misses": missed,
        "deadline_miss_ratio": round(missed / len(handles), 4)
        if handles else 0.0,
        "goodput_rps": round(completed_ok / dt, 2) if dt > 0 else 0.0,
        "wall_s": round(dt, 4),
    }
    if phase_specs is not None:
        phases = []
        for p, (dur, rate) in enumerate(phase_specs):
            ok = sum(
                1 for h, hp in zip(handles, handle_phase)
                if hp == p and h.status == h.DONE
            )
            phases.append({
                "phase": p,
                "duration_s": dur,
                "rate_rps": rate,
                "offered": offered_by_phase.get(p, 0),
                "shed": shed_by_phase.get(p, 0),
                "completed_ok": ok,
                "goodput_rps": round(ok / dur, 2) if dur > 0 else 0.0,
            })
        report["phases"] = phases
    return report
