"""Continuous-batching scheduler: requests, slots, and step-boundary joins.

The host-side half of the serving runtime. A request's life:

    submit -> admission control (queue bound + tenant quota) -> waiting
    -> [step boundary] slot + KV pages reserved, prefill -> decoding
    -> EOS / token budget -> retired (pages recycled, handle completed)

The defining property of continuous batching is that admissions and
retirements happen at *decode step boundaries*, never inside one: a new
request joins the very next step after a slot frees up, and a finished
sequence stops occupying its slot immediately — the batch never stalls
waiting for its longest member (the per-request RPC round-trip model this
replaces is the fleet-size cap named in "RPC Considered Harmful", PAPERS.md).

This module is pure host bookkeeping (deterministic, unit-testable); the
device work lives in session.ServingSession."""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Deque, List, Optional, Sequence, Tuple

from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.serving.kv_cache import PagedKVCache
from paddle_tpu.serving.quota import QuotaExceeded, TenantQuotas

# end-to-end request latency (submit → done), observed at retirement —
# unconditional telemetry, exported via the `metrics` RPC / obs export CLI
REQUEST_HISTOGRAM = obs_metrics.REGISTRY.histogram(
    "paddle_tpu_serving_request_seconds",
    "submit → completion, per retired request",
)


class FinishReason:
    EOS = "eos"
    LENGTH = "length"
    CANCELLED = "cancelled"


class RequestHandle:
    """Caller-facing future for one generation request.

    `result()` blocks until the request finishes and returns the generated
    token ids; a cancelled request raises. Timing fields feed the latency
    bench (t_submit/t_first_token/t_done, all time.monotonic)."""

    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"

    def __init__(self, request_id: int, tenant: str, prompt_len: int,
                 max_new_tokens: int):
        self.request_id = request_id
        self.tenant = tenant
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.status = self.QUEUED
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        # trace context ({"t": trace_id, "s": span_id}) captured at submit
        # time (ServingSession.submit) so engine-thread spans — queue-wait,
        # prefill, ttft — stitch under the submitting RPC's trace id
        self.trace_ctx: Optional[dict] = None
        self._event = threading.Event()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not done after {timeout}s"
            )
        if self.status == self.CANCELLED:
            raise RuntimeError(
                f"request {self.request_id} cancelled ({self.finish_reason})"
            )
        return self.tokens

    def _complete(self, status: str, reason: str) -> None:
        self.status = status
        self.finish_reason = reason
        self.t_done = time.monotonic()
        self._event.set()


class _Waiting:
    __slots__ = ("handle", "prompt")

    def __init__(self, handle: RequestHandle, prompt: List[int]):
        self.handle = handle
        self.prompt = prompt


class ActiveSeq:
    """One occupied decode slot: the sequence's last token + position ride
    into every decode step; everything else is retained host-side."""

    __slots__ = ("handle", "prompt", "last_token", "next_pos", "generated")

    def __init__(self, handle: RequestHandle, prompt: List[int]):
        self.handle = handle
        self.prompt = prompt
        self.last_token: int = -1  # set by prefill
        self.next_pos: int = len(prompt)  # position the last token occupies
        self.generated: int = 0

    def append(self, token: int) -> None:
        self.handle.tokens.append(int(token))
        self.generated += 1
        if self.generated == 1:
            self.handle.t_first_token = time.monotonic()
        else:
            self.next_pos += 1
        self.last_token = int(token)

    def finished(self, eos_id: int) -> Optional[str]:
        if self.generated and self.last_token == eos_id:
            return FinishReason.EOS
        if self.generated >= self.handle.max_new_tokens:
            return FinishReason.LENGTH
        return None


class Scheduler:
    """Slot + queue management; thread-safe against concurrent submits."""

    def __init__(
        self,
        cache: PagedKVCache,
        max_queue: int = 256,
        quotas: Optional[TenantQuotas] = None,
    ):
        self.cache = cache
        self.max_queue = max_queue
        self.quotas = quotas
        self.lock = threading.Lock()
        self.waiting: Deque[_Waiting] = collections.deque()
        self.slots: List[Optional[ActiveSeq]] = [None] * cache.max_slots
        self._ids = itertools.count()
        # counters surfaced through session.stats()
        self.completed = 0
        self.rejected = 0
        self.cancelled = 0

    # -- intake -------------------------------------------------------------
    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        tenant: str,
        trace_ctx: Optional[dict] = None,
    ) -> RequestHandle:
        """Admission control happens HERE, synchronously: the caller learns
        'no' at the front door, not by timing out in a silent queue.
        trace_ctx must ride in (not be set on the returned handle after):
        the engine thread can pop the request the instant it is queued, so
        the context has to be on the handle BEFORE it becomes visible."""
        prompt = [int(t) for t in prompt]
        with self.lock:
            if len(self.waiting) >= self.max_queue:
                self.rejected += 1
                raise QuotaExceeded(
                    f"request queue full ({self.max_queue})", "queue"
                )
            if self.quotas is not None:
                try:
                    self.quotas.admit(tenant, len(prompt) + max_new_tokens)
                except QuotaExceeded:
                    self.rejected += 1
                    raise
            handle = RequestHandle(
                next(self._ids), tenant, len(prompt), max_new_tokens
            )
            handle.trace_ctx = trace_ctx
            self.waiting.append(_Waiting(handle, prompt))
            return handle

    # -- step-boundary transitions ------------------------------------------
    def pop_admissions(self) -> List[Tuple[int, ActiveSeq]]:
        """Move waiting requests into free slots while KV pages allow —
        called once per engine step, so joins land exactly at step
        boundaries. Returns [(slot, ActiveSeq)] needing prefill."""
        admitted: List[Tuple[int, ActiveSeq]] = []
        with self.lock:
            for slot in range(len(self.slots)):
                if not self.waiting:
                    break
                if self.slots[slot] is not None:
                    continue
                w = self.waiting[0]
                total = w.handle.prompt_len + w.handle.max_new_tokens
                if not self.cache.can_reserve(total):
                    break  # FIFO: do not starve the head by skipping it
                self.waiting.popleft()
                self.cache.reserve(slot, total)
                act = ActiveSeq(w.handle, w.prompt)
                act.handle.status = RequestHandle.RUNNING
                self.slots[slot] = act
                admitted.append((slot, act))
        return admitted

    def retire(self, slot: int, reason: str) -> None:
        act = self.slots[slot]
        assert act is not None
        with self.lock:
            self.slots[slot] = None
            self.cache.release(slot)
            self.completed += 1
        if self.quotas is not None:
            unused = act.handle.max_new_tokens - act.generated
            self.quotas.release(act.handle.tenant, max(0, unused))
        act.handle._complete(RequestHandle.DONE, reason)
        REQUEST_HISTOGRAM.observe(act.handle.t_done - act.handle.t_submit)

    def cancel_tenant(self, tenant: str) -> int:
        """Drop a (evicted/deregistered) tenant's QUEUED requests; running
        sequences finish — their pages are already committed and retiring
        them early would waste the work. Returns how many were cancelled."""
        n = 0
        with self.lock:
            keep: Deque[_Waiting] = collections.deque()
            for w in self.waiting:
                if w.handle.tenant == tenant:
                    n += 1
                    if self.quotas is not None:
                        self.quotas.release(
                            tenant,
                            w.handle.prompt_len + w.handle.max_new_tokens,
                        )
                    w.handle._complete(
                        RequestHandle.CANCELLED, FinishReason.CANCELLED
                    )
                else:
                    keep.append(w)
            self.waiting = keep
            self.cancelled += n
        return n

    # -- views --------------------------------------------------------------
    def active_slots(self) -> List[Tuple[int, ActiveSeq]]:
        return [(i, a) for i, a in enumerate(self.slots) if a is not None]

    def has_work(self) -> bool:
        with self.lock:
            return bool(self.waiting) or any(
                a is not None for a in self.slots
            )

    def queue_depth(self) -> int:
        with self.lock:
            return len(self.waiting)
