"""Continuous-batching scheduler: requests, slots, and step-boundary joins.

The host-side half of the serving runtime. A request's life:

    submit -> admission control (queue bound + load-aware shed + tenant
    quota) -> waiting -> [step boundary] slot + KV pages reserved, prefill
    -> decoding -> EOS / token budget -> retired (pages recycled, handle
    completed)

and since ISSUE 10 every exit from that pipeline is *named*: a request that
cannot make its deadline is shed at the front door (`overload`, with a
`retry_after_ms` hint), expires in the queue or at a decode-step boundary
(`deadline`), is cancelled by its abandoning client (`client_timeout`), or
is failed by a dead engine (`engine_error`) — never silently dropped, and
its KV pages are recycled the moment it leaves.

The defining property of continuous batching is that admissions and
retirements happen at *decode step boundaries*, never inside one: a new
request joins the very next step after a slot frees up, and a finished
sequence stops occupying its slot immediately — the batch never stalls
waiting for its longest member (the per-request RPC round-trip model this
replaces is the fleet-size cap named in "RPC Considered Harmful", PAPERS.md).
Deadline checks obey the same discipline: ONE wall-clock read per engine
step (taken by the session) feeds expiry for every queued and running
request — enforced by tests/test_lint_hotloop.py's clock lint.

This module is pure host bookkeeping (deterministic, unit-testable); the
device work lives in session.ServingSession."""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.serving.kv_cache import PagedKVCache
from paddle_tpu.serving.quota import QuotaExceeded, TenantQuotas

# end-to-end request latency (submit → done), observed at retirement —
# unconditional telemetry, exported via the `metrics` RPC / obs export CLI
REQUEST_HISTOGRAM = obs_metrics.REGISTRY.histogram(
    "paddle_tpu_serving_request_seconds",
    "submit → completion, per retired request",
)


class FinishReason:
    EOS = "eos"
    LENGTH = "length"
    CANCELLED = "cancelled"
    DEADLINE = "deadline"          # total-latency deadline expired
    CLIENT_TIMEOUT = "client_timeout"  # result(timeout=) abandoned the work
    ENGINE_ERROR = "engine_error"  # engine died past its restart budget
    # router tier (ISSUE 15): the assigned replica was lost and no live
    # survivor could take the request before the router gave up
    REPLICA_LOST = "replica_lost"


class RequestHandle:
    """Caller-facing future for one generation request.

    `result()` blocks until the request finishes and returns the generated
    token ids; a cancelled request raises. By default a `result(timeout=)`
    expiry also CANCELS the request server-side — the pre-ISSUE-10 behavior
    (client times out, request keeps decoding and holding KV pages) leaked
    work nobody would collect. Timing fields feed the latency bench
    (t_submit/t_first_token/t_done, all time.monotonic); t_deadline /
    t_ttft_deadline are absolute monotonic deadlines (None = none)."""

    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"

    def __init__(self, request_id: int, tenant: str, prompt_len: int,
                 max_new_tokens: int,
                 deadline_s: Optional[float] = None,
                 ttft_deadline_s: Optional[float] = None,
                 seed: Optional[int] = None,
                 temperature: float = 0.0,
                 top_k: int = 0):
        self.request_id = request_id
        self.tenant = tenant
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        # sampling identity (ISSUE 11): the per-request seed is part of the
        # REQUEST, not the engine — a crash-replayed request reuses it (with
        # the token's step index) so restart recovery regenerates bitwise-
        # identical tokens even at temperature > 0. Default: the request id,
        # stable across replay and across same-order submission streams.
        self.seed = int(request_id if seed is None else seed) & 0xFFFFFFFF
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.status = self.QUEUED
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.t_deadline = (
            None if deadline_s is None else self.t_submit + float(deadline_s)
        )
        self.t_ttft_deadline = (
            None if ttft_deadline_s is None
            else self.t_submit + float(ttft_deadline_s)
        )
        # trace context ({"t": trace_id, "s": span_id}) captured at submit
        # time (ServingSession.submit) so engine-thread spans — queue-wait,
        # prefill, ttft — stitch under the submitting RPC's trace id
        self.trace_ctx: Optional[dict] = None
        # back-reference for cancel(); set by Scheduler.submit
        self._scheduler: Optional["Scheduler"] = None
        # TTFT histogram/miss-counter latch: a crash-replayed request gets a
        # fresh t_first_token but must be OBSERVED exactly once (session._admit)
        self.ttft_observed = False
        # prefix-cache admission-pricing hint (ISSUE 19): leading prompt
        # tokens the cache held at SUBMIT time (read-only peek). Load
        # estimates price this request's prefill by its uncached suffix;
        # the authoritative hit is re-measured at reservation (ActiveSeq
        # .prefix_hit) — the cache may have warmed or evicted meanwhile.
        self.prefix_hint = 0
        self._event = threading.Event()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self, reason: str = FinishReason.CANCELLED) -> bool:
        """Cancel this request: a queued request completes CANCELLED
        immediately; a running one is retired (pages recycled) at the next
        decode-step boundary. False when already finished."""
        if self._scheduler is None or self.done:
            return False
        return self._scheduler.cancel(self.request_id, reason)

    def result(self, timeout: Optional[float] = None,
               cancel_on_timeout: bool = True) -> List[int]:
        if not self._event.wait(timeout):
            if cancel_on_timeout:
                # the fix for the classic leak: an abandoning client must not
                # leave its request decoding into the void while holding KV
                # pages — cancel it so the slot + pages recycle at the next
                # step boundary (serving/scheduler.py reap)
                self.cancel(FinishReason.CLIENT_TIMEOUT)
            raise TimeoutError(
                f"request {self.request_id} not done after {timeout}s"
                + ("; cancelled server-side" if cancel_on_timeout else "")
            )
        if self.status == self.CANCELLED:
            raise RuntimeError(
                f"request {self.request_id} cancelled ({self.finish_reason})"
            )
        return self.tokens

    def _complete(self, status: str, reason: str) -> None:
        self.status = status
        self.finish_reason = reason
        self.t_done = time.monotonic()
        self._event.set()


class _Waiting:
    __slots__ = ("handle", "prompt")

    def __init__(self, handle: RequestHandle, prompt: List[int]):
        self.handle = handle
        self.prompt = prompt


class ActiveSeq:
    """One occupied decode slot: the sequence's last token + position ride
    into every decode step; everything else is retained host-side.

    Chunked prefill (ISSUE 11): `prefill_pos` counts the prompt tokens whose
    K/V is committed so far. The session's chunked path admits long prompts
    with prefill_pos=0 and advances one chunk per engine step; a slot is
    `prefilling` until the whole prompt is committed and joins decode steps
    only after — so a long prompt never steals a decode step from the
    already-decoding slots."""

    __slots__ = ("handle", "prompt", "last_token", "next_pos", "generated",
                 "t_started", "prefill_pos", "engine_steps", "prefix_hit")

    def __init__(self, handle: RequestHandle, prompt: List[int]):
        self.handle = handle
        self.prompt = prompt
        self.last_token: int = -1  # set by prefill
        self.next_pos: int = len(prompt)  # position the last token occupies
        self.generated: int = 0
        self.t_started: Optional[float] = None  # set at admission
        self.prefill_pos: int = len(prompt)  # chunked path resets to 0
        # prompt tokens aliased from the prefix cache at reservation
        # (ISSUE 19): the session starts this slot's chunked prefill HERE —
        # the aliased pages' KV is already committed — and the retire-time
        # EWMA prices the prefill by the remaining suffix only
        self.prefix_hit: int = 0
        # engine steps this sequence actually consumed (one per decode step
        # it rode, one per verify round): with speculative decoding emitting
        # >1 token per step, `generated` stops being a step count — the
        # retire-time EWMA prices steps off THIS when speculation is on
        self.engine_steps: int = 0

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < len(self.prompt)

    def append(self, token: int) -> None:
        self.handle.tokens.append(int(token))
        self.generated += 1
        if self.generated == 1:
            # clock-ok: once per REQUEST (not per token) — the TTFT stamp
            self.handle.t_first_token = time.monotonic()
        else:
            self.next_pos += 1
        self.last_token = int(token)

    def finished(self, eos_id: int) -> Optional[str]:
        if self.generated and self.last_token == eos_id:
            return FinishReason.EOS
        if self.generated >= self.handle.max_new_tokens:
            return FinishReason.LENGTH
        return None


class Scheduler:
    """Slot + queue management; thread-safe against concurrent submits."""

    # EWMA smoothing for the observed per-request service time that feeds
    # the queue-wait estimate (load-aware shedding)
    SERVICE_EWMA_ALPHA = 0.3

    def __init__(
        self,
        cache: PagedKVCache,
        max_queue: int = 256,
        quotas: Optional[TenantQuotas] = None,
        prefill_chunk: Optional[int] = None,
        largest_bucket: Optional[int] = None,
        speculate_k: int = 0,
    ):
        self.cache = cache
        self.max_queue = max_queue
        self.quotas = quotas
        # speculative decoding (ISSUE 16): admission reserves K extra
        # tokens of page headroom per request so a verify chunk's K+1
        # scatter always has pages behind it; the session trims the surplus
        # back to the free list once a request's remaining budget can no
        # longer use it (kv_cache.trim). 0 = today's exact reservation.
        self.speculate_k = max(0, int(speculate_k))
        # chunked-prefill geometry (None = whole-prompt prefill): the load
        # estimator charges each chunk one engine step, so a flood of long
        # prompts raises the wait estimate the way it raises real TTFT;
        # largest_bucket mirrors the session's routing (a prompt beyond
        # every bucket chunks even when it fits one chunk)
        self.prefill_chunk = prefill_chunk
        self.largest_bucket = largest_bucket
        self.lock = threading.Lock()
        self.waiting: Deque[_Waiting] = collections.deque()
        self.slots: List[Optional[ActiveSeq]] = [None] * cache.max_slots
        self._ids = itertools.count()
        # cancellations requested for RUNNING sequences; honored at the next
        # decode-step boundary (reap) so they never interrupt a step
        self._cancel_req: Dict[int, str] = {}
        # EWMA of admission→done wall time, the basis of estimate_wait_s,
        # plus an EWMA of per-ENGINE-STEP time (service / steps observed at
        # retirement) that prices prefill chunks into the estimates
        self._ewma_service_s: Optional[float] = None
        self._ewma_step_s: Optional[float] = None
        # counters surfaced through session.stats()
        self.completed = 0
        self.rejected = 0
        self.cancelled = 0
        self.shed = 0
        self.deadline_misses = 0
        self.pages_recycled_on_cancel = 0

    # -- intake -------------------------------------------------------------
    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        tenant: str,
        trace_ctx: Optional[dict] = None,
        deadline_s: Optional[float] = None,
        ttft_deadline_s: Optional[float] = None,
        seed: Optional[int] = None,
        temperature: float = 0.0,
        top_k: int = 0,
    ) -> RequestHandle:
        """Admission control happens HERE, synchronously: the caller learns
        'no' at the front door, not by timing out in a silent queue. Three
        gates, in order: the queue bound, the load-aware deadline check (a
        request whose estimated queue wait already exceeds its deadline
        budget is doomed — admitting it would burn a slot on work nobody can
        use; shed it with `retry_after_ms` instead), then the tenant quota.
        trace_ctx must ride in (not be set on the returned handle after):
        the engine thread can pop the request the instant it is queued, so
        the context has to be on the handle BEFORE it becomes visible."""
        prompt = [int(t) for t in prompt]
        total = len(prompt) + max_new_tokens
        # prefix-cache pricing peek (ISSUE 19): how much of this prompt's
        # prefill is already cached RIGHT NOW. Read-only (no recency bump) —
        # the ONE sanctioned admission-path hash computation (lint-pinned);
        # 0 with the cache off, so estimates are bitwise the old ones.
        cached = self.cache.peek_hit_tokens(tenant, prompt)
        with self.lock:
            if len(self.waiting) >= self.max_queue:
                self.rejected += 1
                self.shed += 1
                obs_metrics.observe_shed("queue")
                raise QuotaExceeded(
                    f"request queue full ({self.max_queue})", "queue",
                    retry_after_ms=self._retry_hint_ms(total, len(prompt)),
                )
            if deadline_s is not None:
                if deadline_s <= 0:
                    self.rejected += 1
                    self.shed += 1
                    obs_metrics.observe_shed("deadline")
                    raise QuotaExceeded(
                        f"deadline of {deadline_s}s already expired at "
                        f"admission", "deadline",
                        retry_after_ms=self._retry_hint_ms(total, len(prompt)),
                    )
                est = self._estimate_wait_s(total, len(prompt), cached)
                if est > deadline_s:
                    self.rejected += 1
                    self.shed += 1
                    obs_metrics.observe_shed("overload")
                    raise QuotaExceeded(
                        f"overloaded: estimated completion {est:.2f}s exceeds "
                        f"the request's {deadline_s:.2f}s deadline budget",
                        "overload",
                        retry_after_ms=self._retry_hint_ms(total, len(prompt)),
                    )
            # the TTFT budget is compared against the QUEUE-WAIT estimate,
            # never the completion estimate: a TTFT deadline shorter than one
            # service time must not shed requests on an idle server (TTFT ≈
            # queue wait + prefill, and the contract is "counted, not fatal"
            # — an already-expired TTFT budget just counts a miss later)
            if ttft_deadline_s is not None and ttft_deadline_s > 0:
                est_ttft = self._estimate_ttft_wait_s(total, len(prompt),
                                                      cached)
                if est_ttft > ttft_deadline_s:
                    self.rejected += 1
                    self.shed += 1
                    obs_metrics.observe_shed("overload")
                    raise QuotaExceeded(
                        f"overloaded: estimated queue wait {est_ttft:.2f}s "
                        f"exceeds the request's {ttft_deadline_s:.2f}s TTFT "
                        f"budget", "overload",
                        retry_after_ms=self._retry_hint_ms(total, len(prompt)),
                    )
            if self.quotas is not None:
                try:
                    self.quotas.admit(tenant, total)
                except QuotaExceeded:
                    self.rejected += 1
                    raise
            handle = RequestHandle(
                next(self._ids), tenant, len(prompt), max_new_tokens,
                deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
                seed=seed, temperature=temperature, top_k=top_k,
            )
            handle.trace_ctx = trace_ctx
            handle._scheduler = self
            handle.prefix_hint = cached
            self.waiting.append(_Waiting(handle, prompt))
            return handle

    # -- load estimate ------------------------------------------------------
    def _chunk_steps(self, prompt_len: int, cached: int = 0) -> int:
        """Chunk-budget engine steps a prompt's prefill costs: ceil(len/C)
        when it routes to the chunked path (longer than one chunk, or longer
        than every bucket — ServingSession._chunked_prompt's rule), else 0
        (whole-prompt prefill rides its admission boundary). The SAME count
        prices a queued prompt and, via remaining-token ceil, one already
        mid-prefill — so the estimate never jumps across admission.

        `cached` is the prompt's prefix-cache hit (ISSUE 19): a hit routes
        through the chunked path starting at the first un-cached token, so
        the request is priced by its SUFFIX — ceil((len - cached)/C) — which
        is exactly the engine steps its prefill will actually occupy. The
        floor of one step keeps a fully-page-matched prompt priced at its
        final (always recomputed) chunk."""
        c = self.prefill_chunk
        if c is None:
            return 0
        cached = min(max(0, int(cached)), max(0, prompt_len - 1))
        routed_chunked = cached > 0 or prompt_len > c or (
            self.largest_bucket is not None and prompt_len > self.largest_bucket
        )
        if not routed_chunked:
            return 0
        return -(-int(prompt_len - cached) // c)

    def _estimate_wait_s(self, total_len: int, prompt_len: int = 0,
                         cached: int = 0) -> float:
        """Expected time for a request of `total_len` tokens to COMPLETE
        (queue wait + its own service), under self.lock — what a deadline
        budget must cover. The queue drains in waves of up to max_slots
        requests, each taking ~one EWMA service time; the request's own
        decode is one more wave, and free-page pressure (pool cannot host it
        right now) adds another. Chunked prefill is priced per chunk: every
        extra chunk — the queue's and this request's own — occupies one
        whole engine step (per-step EWMA observed at retirement), which is
        exactly how long prompts actually delay everyone's wall clock.
        Optimistic (0) until the first retirement seeds the EWMA — cold
        starts admit."""
        svc = self._ewma_service_s
        if svc is None:
            return 0.0
        free_slot = any(a is None for a in self.slots)
        fits_now = free_slot and self.cache.can_reserve(
            total_len + self.speculate_k
        )
        depth = len(self.waiting)
        step_s = self._ewma_step_s or 0.0
        c = self.prefill_chunk
        # chunks still to commit for prompts ALREADY mid-prefill in slots:
        # each one is a whole engine step everybody waits behind, same as
        # the queued and own chunks below
        in_flight_chunks = 0 if c is None else sum(
            -(-(len(a.prompt) - a.prefill_pos) // c)
            for a in self.slots if a is not None and a.prefilling
        )
        # queued prompts price by their uncached suffix (the submit-time
        # peek on the handle); mid-prefill slots auto-correct below — a
        # prefix hit started prefill_pos at the hit, so the remaining-token
        # ceil already charges only the suffix
        chunk_cost = step_s * (
            self._chunk_steps(prompt_len, cached)
            + sum(
                self._chunk_steps(w.handle.prompt_len, w.handle.prefix_hint)
                for w in self.waiting
            )
            + in_flight_chunks
        )
        if depth == 0 and fits_now:
            return svc + chunk_cost  # empty queue: its own decode + chunks
        waves = depth / max(1, self.cache.max_slots) + 1.0
        if not fits_now:
            waves += 1.0
        return waves * svc + chunk_cost

    def _estimate_ttft_wait_s(self, total_len: int, prompt_len: int = 0,
                              cached: int = 0) -> float:
        """Expected wait until the FIRST token (under self.lock): the
        completion estimate minus the request's own decode wave — the
        queue-drain time ahead of it plus its OWN prefill chunks (a chunked
        long prompt's first token only lands after its last chunk). 0 on an
        idle server with room."""
        svc = self._ewma_service_s
        if svc is None:
            return 0.0
        return max(
            0.0, self._estimate_wait_s(total_len, prompt_len, cached) - svc
        )

    def _retry_hint_ms(self, total_len: int, prompt_len: int = 0) -> int:
        # under self.lock; the hint is "when could this plausibly fit":
        # the estimated wait, floored at one service time (or 10ms cold)
        est = self._estimate_wait_s(total_len, prompt_len)
        floor = self._ewma_service_s or 0.01
        return max(1, int(1000 * max(est, floor)))

    def estimate_wait_s(self, total_len: int = 0, prompt_len: int = 0) -> float:
        with self.lock:
            return self._estimate_wait_s(total_len, prompt_len)

    def reset_load_estimate(self) -> None:
        """Forget the observed service-time EWMAs. Benches and warmup paths
        need this: a compile-heavy first round observes second-scale
        'service times' that would make the load-aware admission check shed
        everything against a millisecond-scale deadline budget until enough
        steady-state retirements wash the EWMA out."""
        with self.lock:
            self._ewma_service_s = None
            self._ewma_step_s = None

    # -- cancellation + deadline reaping ------------------------------------
    def _finalize(self, handle: RequestHandle, reason: str,
                  refund_tokens: int, freed_pages: int) -> None:
        """The ONE completion path for every cancellation exit (queued
        cancel, reap expiry, doomed-at-admission, crash requeue): refund the
        tenant quota, emit the page-recycle / deadline-miss metrics, wake
        the caller. Must run OUTSIDE self.lock (quota has its own lock and
        _complete wakes waiters)."""
        if self.quotas is not None:
            self.quotas.release(handle.tenant, refund_tokens)
        if freed_pages:
            obs_metrics.observe_pages_recycled(freed_pages)
        if reason == FinishReason.DEADLINE:
            obs_metrics.observe_deadline_miss("total")
        handle._complete(RequestHandle.CANCELLED, reason)

    def cancel(self, request_id: int,
               reason: str = FinishReason.CANCELLED) -> bool:
        """Cancel one request by id. Queued → completed CANCELLED now (quota
        refunded, nothing was reserved); running → marked, retired with its
        pages recycled at the next decode-step boundary (reap). False when
        unknown or already finished."""
        victim: Optional[_Waiting] = None
        with self.lock:
            for w in self.waiting:
                if w.handle.request_id == request_id:
                    victim = w
                    break
            if victim is not None:
                self.waiting.remove(victim)
                self.cancelled += 1
            else:
                for act in self.slots:
                    if act is not None and act.handle.request_id == request_id:
                        self._cancel_req[request_id] = reason
                        return True
                return False
        h = victim.handle
        self._finalize(h, reason, h.prompt_len + h.max_new_tokens, 0)
        return True

    def reap(self, now: Optional[float] = None) -> int:
        """Step-boundary sweep, called once per engine step with that step's
        single timestamp: expire queued + running requests past their total
        deadline and honor pending cancellations, recycling KV pages
        immediately. Returns how many requests were removed."""
        # clock-ok: fallback for direct (test) calls — the engine passes its
        # single per-step timestamp, so expiry never reads per request
        now = time.monotonic() if now is None else now
        removed: List[Tuple[RequestHandle, str, int, int]] = []
        with self.lock:
            if self.waiting and any(
                w.handle.t_deadline is not None for w in self.waiting
            ):
                keep: Deque[_Waiting] = collections.deque()
                for w in self.waiting:
                    h = w.handle
                    if h.t_deadline is not None and now >= h.t_deadline:
                        self.cancelled += 1
                        self.deadline_misses += 1
                        removed.append(
                            (h, FinishReason.DEADLINE,
                             h.prompt_len + h.max_new_tokens, 0)
                        )
                    else:
                        keep.append(w)
                self.waiting = keep
            for slot, act in enumerate(self.slots):
                if act is None:
                    continue
                h = act.handle
                reason = self._cancel_req.pop(h.request_id, None)
                if (reason is None and h.t_deadline is not None
                        and now >= h.t_deadline):
                    reason = FinishReason.DEADLINE
                if reason is None:
                    continue
                self.slots[slot] = None
                freed = self.cache.release(slot)
                self.pages_recycled_on_cancel += freed
                self.cancelled += 1
                if reason == FinishReason.DEADLINE:
                    self.deadline_misses += 1
                removed.append(
                    (h, reason,
                     max(0, h.max_new_tokens - act.generated), freed)
                )
        for h, reason, refund, freed in removed:
            self._finalize(h, reason, refund, freed)
        return len(removed)

    # -- step-boundary transitions ------------------------------------------
    def pop_admissions(
        self, now: Optional[float] = None
    ) -> List[Tuple[int, ActiveSeq]]:
        """Move waiting requests into free slots while KV pages allow —
        called once per engine step, so joins land exactly at step
        boundaries. A queued request whose remaining deadline budget no
        longer covers one service time is DOOMED: it is failed here
        ('deadline') instead of being handed a slot it would die holding —
        under overload that one check is most of what keeps goodput flat
        (slot time only goes to requests that can still finish). Returns
        [(slot, ActiveSeq)] needing prefill."""
        # clock-ok: fallback for direct (test) calls — the engine passes its
        # single per-step timestamp
        now = time.monotonic() if now is None else now
        admitted: List[Tuple[int, ActiveSeq]] = []
        doomed: List[RequestHandle] = []
        with self.lock:
            svc = self._ewma_service_s
            for slot in range(len(self.slots)):
                while self.waiting:
                    w = self.waiting[0]
                    h = w.handle
                    if (h.t_deadline is not None and svc is not None
                            and h.t_deadline - now < svc):
                        self.waiting.popleft()
                        self.cancelled += 1
                        self.deadline_misses += 1
                        doomed.append(h)
                        continue
                    break
                if not self.waiting:
                    break
                if self.slots[slot] is not None:
                    continue
                w = self.waiting[0]
                # +K speculative headroom (0 when speculation is off, so
                # the reservation is bitwise today's)
                total = (w.handle.prompt_len + w.handle.max_new_tokens
                         + self.speculate_k)
                if not self.cache.can_reserve(total):
                    break  # FIFO: do not starve the head by skipping it
                self.waiting.popleft()
                # tenant+prompt let the cache alias this prompt's cached
                # prefix pages into the slot (no-op with the cache off);
                # the AUTHORITATIVE hit lands on the ActiveSeq — the session
                # starts chunked prefill at exactly this offset
                self.cache.reserve(
                    slot, total, tenant=w.handle.tenant, prompt=w.prompt
                )
                act = ActiveSeq(w.handle, w.prompt)
                act.prefix_hit = self.cache.hit_tokens(slot)
                act.t_started = now
                act.handle.status = RequestHandle.RUNNING
                self.slots[slot] = act
                admitted.append((slot, act))
        for h in doomed:
            self._finalize(h, FinishReason.DEADLINE,
                           h.prompt_len + h.max_new_tokens, 0)
        return admitted

    def retire(self, slot: int, reason: str) -> None:
        act = self.slots[slot]
        assert act is not None
        with self.lock:
            self.slots[slot] = None
            self.cache.release(slot)
            self.completed += 1
            self._cancel_req.pop(act.handle.request_id, None)
        if self.quotas is not None:
            unused = act.handle.max_new_tokens - act.generated
            self.quotas.release(act.handle.tenant, max(0, unused))
        act.handle._complete(RequestHandle.DONE, reason)
        REQUEST_HISTOGRAM.observe(act.handle.t_done - act.handle.t_submit)
        svc = act.handle.t_done - (act.t_started or act.handle.t_submit)
        # engine steps this request actually occupied: its decode steps plus
        # its extra prefill chunks — prices one chunk for the load estimate.
        # With speculation on, `generated` over-counts steps (a verify round
        # commits several accepted tokens in ONE step), so the EWMA prices
        # off the sequence's real step count instead (+1 for the prefill
        # step that emitted the first token, matching generated's accounting)
        if self.speculate_k:
            occupied = act.engine_steps + 1
        else:
            occupied = act.generated
        # suffix pricing (ISSUE 19): the chunks this request ACTUALLY ran —
        # a prefix hit skipped the cached pages entirely, so the EWMA must
        # not learn phantom whole-prompt steps off cache-hit retirements
        steps = max(1, occupied + self._chunk_steps(act.handle.prompt_len,
                                                    act.prefix_hit))
        with self.lock:
            a = self.SERVICE_EWMA_ALPHA
            self._ewma_service_s = (
                svc if self._ewma_service_s is None
                else (1 - a) * self._ewma_service_s + a * svc
            )
            per_step = svc / steps
            self._ewma_step_s = (
                per_step if self._ewma_step_s is None
                else (1 - a) * self._ewma_step_s + a * per_step
            )

    # -- engine crash recovery ----------------------------------------------
    def requeue_active(self, now: Optional[float] = None) -> Tuple[int, int]:
        """Engine recovery (ISSUE 10): push every RUNNING sequence back to
        the FRONT of the queue in original submit order with its progress
        reset — decode is deterministic (greedy trivially; sampled requests
        replay through the SAME per-request seed and token step indices,
        ISSUE 11), so the replay regenerates bitwise-identical tokens and
        the restart is result-transparent. Requests
        already past their total deadline fail now with the named reason
        instead of wasting the fresh engine's steps. Slots are emptied but
        the page free-list is NOT touched: the caller re-initializes the
        whole pool (cache.reset()) because the dead engine's donated buffers
        are gone regardless. Returns (requeued, expired)."""
        # clock-ok: once per engine restart (the supervisor's recovery stamp)
        now = time.monotonic() if now is None else now
        requeued = 0
        expired: List[Tuple[RequestHandle, str, int]] = []
        with self.lock:
            active = [(i, a) for i, a in enumerate(self.slots)
                      if a is not None]
            for i, _ in active:
                self.slots[i] = None
            # appendleft in descending id order -> queue head ends up in
            # ascending (original) order, ahead of not-yet-admitted work
            for _, act in sorted(
                active, key=lambda t: t[1].handle.request_id, reverse=True,
            ):
                h = act.handle
                reason = self._cancel_req.pop(h.request_id, None)
                if reason is None and h.t_deadline is not None \
                        and now >= h.t_deadline:
                    reason = FinishReason.DEADLINE
                if reason is not None:
                    self.cancelled += 1
                    if reason == FinishReason.DEADLINE:
                        self.deadline_misses += 1
                    expired.append(
                        (h, reason, max(0, h.max_new_tokens - act.generated))
                    )
                    continue
                h.tokens = []
                h.t_first_token = None
                h.status = RequestHandle.QUEUED
                self.waiting.appendleft(_Waiting(h, act.prompt))
                requeued += 1
        for h, reason, refund in expired:
            self._finalize(h, reason, refund, 0)
        return requeued, len(expired)

    def cancel_tenant(self, tenant: str) -> int:
        """Drop a (evicted/deregistered) tenant's QUEUED requests; running
        sequences finish — their pages are already committed and retiring
        them early would waste the work. Returns how many were cancelled."""
        n = 0
        with self.lock:
            keep: Deque[_Waiting] = collections.deque()
            for w in self.waiting:
                if w.handle.tenant == tenant:
                    n += 1
                    if self.quotas is not None:
                        self.quotas.release(
                            tenant,
                            w.handle.prompt_len + w.handle.max_new_tokens,
                        )
                    w.handle._complete(
                        RequestHandle.CANCELLED, FinishReason.CANCELLED
                    )
                else:
                    keep.append(w)
            self.waiting = keep
            self.cancelled += n
        return n

    # -- views --------------------------------------------------------------
    def active_slots(self) -> List[Tuple[int, ActiveSeq]]:
        return [(i, a) for i, a in enumerate(self.slots) if a is not None]

    def has_work(self) -> bool:
        with self.lock:
            return bool(self.waiting) or any(
                a is not None for a in self.slots
            )

    def queue_depth(self) -> int:
        with self.lock:
            return len(self.waiting)
