"""Admission control: per-tenant token quotas + concurrency caps.

The front door says no *before* any device work is queued ("admission
control" in ISSUE 6): a request is charged its worst case
(prompt + max_new_tokens) against its tenant's token bucket at submit time,
and rejected — never silently queued forever — when the tenant is over
budget, over its concurrency cap, or the global queue is full. The token
bucket refills continuously (tokens_per_s up to a burst capacity), the
standard shape for "heavy traffic from millions of users" fairness; the
clock is injectable so tests are deterministic.

Since ISSUE 10 this is also where request *deadlines* get their defaults:
a tenant may carry a default total-latency and/or TTFT deadline
(`set_quota(deadline_s=, ttft_deadline_s=)`), applied to any request that
does not name its own — the scheduler enforces them at admission, in the
queue, and at decode-step boundaries."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class QuotaExceeded(Exception):
    """Rejected by admission control; `reason` is machine-readable
    ('tokens' | 'concurrency' | 'queue' | 'overload' | 'deadline' |
    'unregistered'). `retry_after_ms` — set on load sheds — is the server's
    estimate of when retrying could succeed, derived from the current queue
    wait and free-page pressure; a client that honors it converts a goodput
    collapse into bounded backoff."""

    def __init__(self, msg: str, reason: str,
                 retry_after_ms: Optional[int] = None):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_ms = retry_after_ms


class _Bucket:
    __slots__ = ("capacity", "rate", "level", "last", "in_flight")

    def __init__(self, capacity: float, rate: float, now: float):
        self.capacity = capacity
        self.rate = rate
        self.level = capacity
        self.last = now
        self.in_flight = 0


class TenantQuotas:
    """Per-tenant token buckets + concurrency caps.

    `token_capacity` is the burst size and `tokens_per_s` the refill rate;
    either may be None (unlimited). Unknown tenants get the defaults, so a
    fleet-wide cap needs no per-tenant config."""

    def __init__(
        self,
        token_capacity: Optional[float] = None,
        tokens_per_s: float = 0.0,
        max_concurrent: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        default_deadline_s: Optional[float] = None,
        default_ttft_deadline_s: Optional[float] = None,
    ):
        self._default = (token_capacity, float(tokens_per_s), max_concurrent)
        # tenant-configurable request deadlines (ISSUE 10): requests that do
        # not name their own total-latency / time-to-first-token deadline
        # inherit the tenant's, falling back to these fleet-wide defaults
        self._default_deadlines = (default_deadline_s, default_ttft_deadline_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, _Bucket] = {}
        self._caps: Dict[str, Optional[int]] = {}
        self._deadlines: Dict[str, tuple] = {}
        # concurrency holds for tenants with no token bucket
        self._hold_counts: Dict[str, int] = {}

    def set_quota(
        self,
        tenant: str,
        token_capacity: Optional[float] = None,
        tokens_per_s: float = 0.0,
        max_concurrent: Optional[int] = None,
        deadline_s: Optional[float] = None,
        ttft_deadline_s: Optional[float] = None,
    ) -> None:
        with self._lock:
            if token_capacity is not None:
                b = _Bucket(token_capacity, float(tokens_per_s), self._clock())
                self._buckets[tenant] = b
            self._caps[tenant] = max_concurrent
            if deadline_s is not None or ttft_deadline_s is not None:
                self._deadlines[tenant] = (deadline_s, ttft_deadline_s)

    def deadlines_for(self, tenant: str) -> tuple:
        """(total_deadline_s, ttft_deadline_s) this tenant's requests default
        to — per-tenant override where set, else the fleet-wide defaults;
        either element may be None (no deadline)."""
        with self._lock:
            d, td = self._deadlines.get(tenant, (None, None))
            dd, dtd = self._default_deadlines
            return (d if d is not None else dd, td if td is not None else dtd)

    def _bucket(self, tenant: str) -> Optional[_Bucket]:
        b = self._buckets.get(tenant)
        if b is None and self._default[0] is not None:
            b = _Bucket(self._default[0], self._default[1], self._clock())
            self._buckets[tenant] = b
        return b

    def _cap(self, tenant: str) -> Optional[int]:
        return self._caps.get(tenant, self._default[2])

    def admit(self, tenant: str, tokens: int) -> None:
        """Charge `tokens` against the tenant or raise QuotaExceeded. The
        concurrency hold is released by release(); the tokens are consumed."""
        with self._lock:
            b = self._bucket(tenant)
            cap = self._cap(tenant)
            # concurrency first: a capped tenant must not drain its bucket
            # with requests that would be refused anyway
            holds = b.in_flight if b is not None else self._holds(tenant)
            if cap is not None and holds >= cap:
                raise QuotaExceeded(
                    f"tenant {tenant!r} at max_concurrent={cap}", "concurrency"
                )
            if b is not None:
                now = self._clock()
                b.level = min(b.capacity, b.level + (now - b.last) * b.rate)
                b.last = now
                if tokens > b.level:
                    raise QuotaExceeded(
                        f"tenant {tenant!r} over token quota: wanted {tokens}, "
                        f"{b.level:.0f} of {b.capacity:.0f} available",
                        "tokens",
                    )
                b.level -= tokens
                b.in_flight += 1
            else:
                self._hold_counts[tenant] = self._holds(tenant) + 1

    def _holds(self, tenant: str) -> int:
        return self._hold_counts.get(tenant, 0)

    def release(self, tenant: str, unused_tokens: int = 0) -> None:
        """Drop the concurrency hold; refund tokens the request reserved but
        never generated (a request that stops at EOS early should not keep
        paying for its worst case)."""
        with self._lock:
            b = self._buckets.get(tenant)
            if b is not None:
                b.in_flight = max(0, b.in_flight - 1)
                if unused_tokens:
                    b.level = min(b.capacity, b.level + unused_tokens)
            elif self._holds(tenant):
                self._hold_counts[tenant] -= 1

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                t: {
                    "level": round(b.level, 1),
                    "capacity": b.capacity,
                    "in_flight": b.in_flight,
                }
                for t, b in self._buckets.items()
            }
