"""Shared-prefix index: per-tenant chains over committed full KV pages.

ISSUE 19: real traffic is a handful of system prompts × millions of user
turns, so the dominant wasted prefill FLOPs are re-computing KV for tokens
some earlier request already committed. The paged KV design makes reuse a
pure block-table aliasing trick — and this module is the *host-side lookup
structure only*: it never touches a device array, a socket or a clock
(tests/test_lint_hotloop.py pins all three bans), and it never owns a page.
Refcounts and the free list stay in PagedKVCache; the index merely says
"these physical pages already hold the KV for this token prefix".

Structure: a radix-style chain of nodes, one node per FULL page of prompt
tokens. A node's identity is ``(parent_node_id, tuple(page_tokens))`` — an
exact-match dict key, so "hashing" is Python's tuple hash with equality
collision resolution: two different token chunks can never alias the same
node, and the chain id encodes the ENTIRE prefix up to that page. Chains
are rooted per tenant (the root node id namespaces every key), so two
tenants submitting identical text walk disjoint chains and can never see
each other's pages — the cache-hygiene contract ROADMAP item 1b names.

Copy-on-write is implicit in the page-granularity design: only full,
immutable prompt pages enter the index, a matching request aliases the
matched prefix READ-ONLY and allocates a private page at the first
divergent page (its own chunked prefill recomputes any partial overlap
there — identical math, no device-side page copy). The `cow_events`
counter records lookups that stopped at a genuine divergence (the parent
node had cached continuations, just not ours).

Recency is a LOGICAL tick (a counter bumped per lookup/insert), not a wall
clock: eviction order only needs relative recency, and the admission path
must not grow a second clock source (the clock-ok lint discipline)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixIndex"]


class _Node:
    """One cached full page of some tenant's prompt stream."""

    __slots__ = ("node_id", "parent_id", "chunk", "page", "children", "tick")

    def __init__(self, node_id: int, parent_id: int,
                 chunk: Tuple[int, ...], page: int, tick: int):
        self.node_id = node_id
        self.parent_id = parent_id
        self.chunk = chunk
        self.page = page
        self.children = 0
        self.tick = tick


class PrefixIndex:
    """Per-tenant chain index mapping page-aligned token prefixes to the
    physical pages that already hold their committed KV.

    Pure host bookkeeping: the caller (PagedKVCache) owns refcounts and
    takes one reference per node registered here, released when the node is
    evicted — the index itself only stores ids and counters."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        # node id 0 is never used; per-tenant roots are synthetic nodes that
        # exist only as parent ids (no page, never evicted)
        self._next_id = 1
        self._roots: Dict[str, int] = {}
        # (parent_node_id, page_tokens_tuple) -> _Node; the dict IS the hash
        # index — exact-match keys, so distinct prefixes can never collide
        self._nodes: Dict[Tuple[int, Tuple[int, ...]], _Node] = {}
        self._by_id: Dict[int, _Node] = {}
        self._tick = 0
        # telemetry (cumulative across resets — reset drops the INDEX, not
        # the counters, so a crash-recovered engine keeps its history)
        self.hits = 0              # lookups that matched >= 1 page
        self.lookups = 0
        self.hit_tokens = 0        # prompt tokens served from cached pages
        self.lookup_tokens = 0     # prompt tokens examined across lookups
        self.pages_shared = 0      # aliases handed out (page x request)
        self.pages_inserted = 0
        self.evictions = 0
        self.cow_events = 0        # lookups that stopped at a divergent page
        self.hit_tokens_by_tenant: Dict[str, int] = {}
        self.lookup_tokens_by_tenant: Dict[str, int] = {}

    # -- views ---------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._nodes)

    def pages(self) -> List[int]:
        """Every physical page the index holds a reference on."""
        return [n.page for n in self._nodes.values()]

    def holds(self, page: int) -> bool:
        return any(n.page == page for n in self._nodes.values())

    def _root_for(self, tenant: str, create: bool) -> Optional[int]:
        root = self._roots.get(tenant)
        if root is None and create:
            root = self._next_id
            self._next_id += 1
            self._roots[tenant] = root
        return root

    @staticmethod
    def max_match_pages(prompt_len: int, page_size: int) -> int:
        """How many leading pages of a prompt a request may ALIAS: full
        pages only, and never the whole prompt — the last prompt token is
        always recomputed so the final prefill chunk has >= 1 token to
        forward (its logits sample the request's first token)."""
        return max(0, (int(prompt_len) - 1) // int(page_size))

    # -- lookup --------------------------------------------------------------
    def match(self, tenant: str, prompt: Sequence[int],
              peek: bool = False) -> Tuple[List[int], int]:
        """Walk the tenant's chain along `prompt` and return
        ``(matched_pages, last_node_id)`` — the physical pages whose KV this
        prompt can alias read-only, capped at `max_match_pages`, and the
        node id registration should continue from. `peek=True` is the
        admission-pricing probe: it bumps no recency ticks and no counters
        (Scheduler.submit estimates the uncached suffix without perturbing
        eviction order)."""
        ps = self.page_size
        limit = self.max_match_pages(len(prompt), ps)
        root = self._root_for(tenant, create=not peek)
        if not peek:
            self._tick += 1
            self.lookups += 1
            self.lookup_tokens += len(prompt)
            self.lookup_tokens_by_tenant[tenant] = (
                self.lookup_tokens_by_tenant.get(tenant, 0) + len(prompt)
            )
        if root is None:
            return [], 0
        pages: List[int] = []
        parent = root
        for i in range(limit):
            chunk = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            node = self._nodes.get((parent, chunk))
            if node is None:
                # the COW boundary: cached continuations exist under this
                # parent but none matches OUR tokens — the caller allocates
                # a private page here and recomputes from this position
                if not peek:
                    pnode = self._by_id.get(parent)
                    siblings = (pnode.children if pnode is not None
                                else self._root_children(parent))
                    if siblings > 0:
                        self.cow_events += 1
                break
            if not peek:
                node.tick = self._tick
            pages.append(node.page)
            parent = node.node_id
        if not peek and pages:
            self.hits += 1
            self.hit_tokens += len(pages) * ps
            self.hit_tokens_by_tenant[tenant] = (
                self.hit_tokens_by_tenant.get(tenant, 0) + len(pages) * ps
            )
            self.pages_shared += len(pages)
        return pages, parent

    def _root_children(self, root: int) -> int:
        return sum(1 for (pid, _), _n in self._nodes.items() if pid == root)

    def peek_hit_tokens(self, tenant: str, prompt: Sequence[int]) -> int:
        """Admission-pricing probe: how many leading prompt tokens are
        cached RIGHT NOW (no recency bump, no counters)."""
        pages, _ = self.match(tenant, prompt, peek=True)
        return len(pages) * self.page_size

    # -- registration --------------------------------------------------------
    def extend(self, tenant: str, parent: int, prompt: Sequence[int],
               from_page: int, upto_page: int,
               slot_pages: Sequence[int]) -> Tuple[int, List[int]]:
        """Register pages ``[from_page, upto_page)`` of `prompt` (committed
        by the slot that owns `slot_pages`) as cached, continuing the chain
        from node `parent`. Returns ``(new_parent, registered_pages)`` —
        only pages for which a NEW node was created (the caller takes one
        index reference each). A level whose node already exists (another
        slot registered the same prefix first) keeps the existing node and
        page: chains may interleave physical pages from different
        originators, which is sound because a page's KV content is a pure
        function of its token prefix."""
        ps = self.page_size
        if parent == 0:
            parent = self._root_for(tenant, create=True)
        self._tick += 1
        registered: List[int] = []
        for i in range(from_page, upto_page):
            chunk = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            key = (parent, chunk)
            node = self._nodes.get(key)
            if node is None:
                node = _Node(self._next_id, parent, chunk,
                             int(slot_pages[i]), self._tick)
                self._next_id += 1
                self._nodes[key] = node
                self._by_id[node.node_id] = node
                pnode = self._by_id.get(parent)
                if pnode is not None:
                    pnode.children += 1
                registered.append(node.page)
                self.pages_inserted += 1
            else:
                node.tick = self._tick
            parent = node.node_id
        return parent, registered

    # -- eviction ------------------------------------------------------------
    def evictable(self, refcount: Sequence[int]) -> int:
        """Pages reclaimable under pool pressure: index-held pages no slot
        references (refcount 1 = the index's own reference). Every such
        page is reachable by cascading leaf evictions — a slot aliasing a
        DEEPER node would hold references on every ancestor too."""
        return sum(1 for n in self._nodes.values() if refcount[n.page] == 1)

    def evict_lru(self, refcount: Sequence[int]) -> Optional[int]:
        """Drop the least-recently-used LEAF node whose page only the index
        references; returns the freed page id (caller releases the index's
        reference) or None when nothing is evictable."""
        victim_key = None
        victim = None
        for key, n in self._nodes.items():
            if n.children == 0 and refcount[n.page] == 1:
                if victim is None or n.tick < victim.tick:
                    victim_key, victim = key, n
        if victim is None:
            return None
        del self._nodes[victim_key]
        del self._by_id[victim.node_id]
        pnode = self._by_id.get(victim.parent_id)
        if pnode is not None:
            pnode.children -= 1
        self.evictions += 1
        return victim.page

    def drop_all(self) -> List[int]:
        """Empty the index (flush / crash invalidation), returning every
        page it held a reference on so the caller can release them. Unlike
        evict_lru this drops nodes regardless of slot references — a page a
        slot still uses simply loses its INDEX reference and recycles when
        the slot releases it."""
        pages = [n.page for n in self._nodes.values()]
        self._nodes.clear()
        self._by_id.clear()
        self._roots.clear()
        return pages

    # -- telemetry -----------------------------------------------------------
    def stats(self) -> Dict:
        rate = (self.hit_tokens / self.lookup_tokens
                if self.lookup_tokens else 0.0)
        by_tenant = {
            t: round(self.hit_tokens_by_tenant.get(t, 0) / lt, 4)
            for t, lt in self.lookup_tokens_by_tenant.items() if lt
        }
        return {
            "prefix_hit_rate": round(rate, 4),
            "prefix_lookups": self.lookups,
            "prefix_hits": self.hits,
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_lookup_tokens": self.lookup_tokens,
            "prefix_pages_shared": self.pages_shared,
            "prefix_pages_inserted": self.pages_inserted,
            "prefix_pages_cached": len(self._nodes),
            "prefix_pages_cow": self.cow_events,
            "prefix_evictions": self.evictions,
            "prefix_hit_rate_by_tenant": by_tenant,
            "prefix_hit_tokens_by_tenant": dict(self.hit_tokens_by_tenant),
        }
