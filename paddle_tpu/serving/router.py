"""Fault-tolerant multi-replica serving router (ISSUE 15 tentpole).

The tier that lets serving go WIDE: N `ServingServer` replicas (each
possibly tensor-parallel) behind one router that stays correct while
replicas crash, wedge, join and drain.

  * membership — replicas hold leases in a `FleetView` (serving/fleet.py),
    renewed by heartbeats whose REQUEST carries the replica's load snapshot
    and whose REPLY carries the router's control signals (drain orders,
    re-register hints) — the master plane's piggyback discipline, so the
    dispatch path never pays a health round-trip;
  * dispatch — each submit routes to the least-loaded LIVE replica, scored
    purely from piggybacked state + the router's own assignment books (no
    RPC per decision; the ONE blocking call in the path is the forward of
    the submit itself, lint-pinned in tests/test_lint_hotloop.py). When
    every replica sheds, the router sheds too — with the TIGHTEST
    `retry_after_ms` any replica offered — never a hang;
  * in-flight failover — when a replica's lease lapses (it died, or its
    agent self-fenced a wedge) or its connection drops, the router
    re-submits that replica's outstanding requests to a survivor under the
    SAME idempotency key and the SAME pinned per-request seed, so
    re-execution is token-identical for greedy AND sampled streams (PR 11's
    seeded sampling). The fleet-level (tenant, client_req_id) dedup map
    guarantees exactly-one delivered result: the pump keeps polling a
    partitioned replica after eviction, and a LATE answer from it is
    dropped and counted, never double-delivered;
  * planned drain — `drain(replica_id)` stops new assignments, lets
    in-flight streams finish against a deadline (stragglers fail over),
    then deregisters: the lever ROADMAP item 2's autoscaling controller
    pulls;
  * hedging — PR 10's client-side TTFT hedge, promoted into the router:
    a token-less request past `hedge_ttft_s` is duplicated onto a second
    replica under the same key+seed; the first replica to produce a token
    wins and the loser is cancelled server-side.

Results flow back through per-REPLICA pump threads batch-polling
`poll_many` — one round-trip per pump cycle per replica regardless of how
many requests are in flight there (the "RPC Considered Harmful" shape, and
the direction ROADMAP item 4's batched control plane generalizes).

`RouterServer` wraps the router in the same line-JSON TCP surface a
`ServingServer` exposes, so `ServingClient` talks to a router unchanged.
Gate: `benchmarks/chaos_bench.py --mode router`."""

from __future__ import annotations

import collections
import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.core import stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace
from paddle_tpu.runtime.election import mint_instance_token, watch_primary
from paddle_tpu.runtime.master import EndpointsLike, MasterClient, _Membership
from paddle_tpu.serving.fleet import FleetView, Replica, ReplicaState
from paddle_tpu.serving.quota import QuotaExceeded
from paddle_tpu.serving.scheduler import FinishReason

log = logging.getLogger("paddle_tpu.serving.router")


class _BadRequest(RuntimeError):
    """A replica refused the forward for a non-load reason (bad prompt,
    over max_len, ...): the client's problem, not the fleet's — never
    retried on another replica."""


# prompt tokens hashed into the affinity key: long enough to distinguish
# system prompts, short enough that appending user turns to a shared head
# still lands on the same replica (ROADMAP 2a's multi-turn shape)
AFFINITY_HEAD = 16


def affinity_key(prompt: Sequence[int]) -> Optional[int]:
    """Prefix-affinity key (ISSUE 20 / ROADMAP 2a): a hash of the prompt
    HEAD, so requests sharing a system prompt / conversation prefix map to
    the same key and the dispatch score can prefer the replica whose
    prefix cache (runtime/kv_share.py shapes) is already warm for it.
    Int-tuple hashing is deterministic within a process — this key never
    crosses the wire."""
    if not prompt:
        return None
    return hash(tuple(prompt[:AFFINITY_HEAD]))


class RouterHandle:
    """Client-facing future for one fleet request: the router's mirror of
    the replica-side RequestHandle (tokens so far, completion, timing), plus
    the fleet bookkeeping (assignments, failovers, hedges, late drops) the
    chaos drill asserts on. Thread-safe via the owning Router's lock."""

    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"

    def __init__(self, request_id: int, tenant: str, prompt: List[int],
                 max_new_tokens: Optional[int], key: str, seed: int,
                 now: float,
                 deadline_s: Optional[float] = None,
                 ttft_deadline_s: Optional[float] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 hedge_ttft_s: Optional[float] = None):
        self.request_id = request_id
        self.tenant = tenant
        self.prompt = prompt
        self.prompt_len = len(prompt)
        self.affinity = affinity_key(prompt)
        self.max_new_tokens = max_new_tokens
        self.key = key  # the fleet-wide idempotency key (client_req_id)
        # the pinned sampling identity: forwarded EXPLICITLY on every
        # (re-)submit so failover/hedge re-execution draws the same tokens
        # on any replica — replica-local seed defaults would diverge
        self.seed = seed
        self.temperature = temperature
        self.top_k = top_k
        self.hedge_ttft_s = hedge_ttft_s
        self.status = self.QUEUED
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.t_submit = now
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.t_deadline = None if deadline_s is None else now + float(deadline_s)
        self.t_ttft_deadline = (
            None if ttft_deadline_s is None else now + float(ttft_deadline_s)
        )
        # live assignments: replica_id -> replica-side request id (two
        # entries only while a hedge is in flight)
        self.assignments: Dict[str, int] = {}
        self.delivered_by: Optional[str] = None
        self.failovers = 0
        self.hedged = False
        self.late_drops = 0
        self.t_parked: Optional[float] = None
        self._router: Optional["Router"] = None
        # terminal-state latch, written ONLY under the owning Router's lock
        # (first writer wins): delivery, cancel, park-expiry and shed-discard
        # all race here, and `_event.is_set()` alone leaves a window between
        # deciding and waking where a second writer could overwrite the
        # status a waiter already observed
        self._finished = False
        self._event = threading.Event()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> bool:
        if self._router is None or self.done:
            return False
        return self._router.cancel(self.request_id)

    def result(self, timeout: Optional[float] = None,
               cancel_on_timeout: bool = True) -> List[int]:
        if not self._event.wait(timeout):
            if cancel_on_timeout:
                self.cancel()
            raise TimeoutError(
                f"fleet request {self.request_id} not done after {timeout}s"
            )
        if self.status == self.CANCELLED:
            raise RuntimeError(
                f"fleet request {self.request_id} cancelled "
                f"({self.finish_reason})"
            )
        return self.tokens

    def _finish_locked(self, status: str, reason: Optional[str],
                       now: float) -> bool:
        """Write the terminal state (caller holds the Router lock); False
        when another writer already finished this handle. The caller fires
        `_event` OUTSIDE the lock after a True return."""
        if self._finished:
            return False
        self._finished = True
        self.status = status
        self.finish_reason = reason
        self.t_done = now
        return True


class Router:
    """The routing core: usable in-process (benches, drills) or wrapped by
    `RouterServer` for the TCP surface. start()/stop() manage the reaper;
    replica pumps start at registration."""

    # consecutive pump/submit connection failures before a LIVE replica is
    # declared dead (lease expiry is the other, slower detector)
    CONN_FAILURE_EVICT = 3

    def __init__(
        self,
        lease_s: float = 5.0,
        poll_interval_s: float = 0.02,
        hedge_ttft_s: Optional[float] = None,
        late_grace_s: Optional[float] = None,
        drain_deadline_s: float = 30.0,
        park_give_up_s: Optional[float] = None,
        handle_ttl_s: float = 600.0,
        replica_client_kw: Optional[dict] = None,
    ):
        self.fleet = FleetView(lease_s)
        self.poll_interval_s = float(poll_interval_s)
        # router-level TTFT hedge default; per-request submit() wins
        self.hedge_ttft_s = hedge_ttft_s
        # how long an evicted replica's pump keeps polling for LATE winners
        # (the partitioned-then-healed case the dedup map exists for)
        self.late_grace_s = (
            float(late_grace_s) if late_grace_s is not None
            else max(4.0 * lease_s, 10.0)
        )
        self.drain_deadline_s = float(drain_deadline_s)
        # an unplaceable request (every replica gone) parks this long before
        # failing with the named reason 'replica_lost'
        self.park_give_up_s = (
            float(park_give_up_s) if park_give_up_s is not None
            else max(2.0 * lease_s, 5.0)
        )
        self.handle_ttl_s = float(handle_ttl_s)
        # per-incarnation identity (ISSUE 18): minted fresh for every Router
        # object and echoed on replica register/heartbeat replies, so agents
        # can fence control hints by WHICH router incarnation issued them —
        # a healed old primary's stale replies are recognizably not ours.
        # A RouterStandby overwrites this with its election token.
        self.instance = mint_instance_token()
        self._replica_client_kw = dict(
            replica_client_kw or {"timeout": 5.0, "retries": 2}
        )
        self._lock = threading.Lock()
        self._handles: Dict[int, RouterHandle] = {}
        self._by_key: Dict[Tuple[str, str], int] = {}
        self._unassigned: Set[int] = set()
        self._ids = itertools.count()
        # per-replica submit-path clients (shared, serialized by a lock —
        # MasterClient is one socket); pumps own a separate connection
        self._submit_clients: Dict[str, Tuple[threading.Lock, MasterClient]] = {}
        self._pumps: List[threading.Thread] = []
        self._stop = threading.Event()
        self._reaper: Optional[threading.Thread] = None
        # push-streaming seam (ISSUE 16): pumps bump this sequence whenever
        # a mirror grows or finishes; RouterServer pusher threads diff the
        # handle's token mirror and write frames on their own time — the
        # pump threads never touch a client socket
        self._stream_cv = threading.Condition()
        self._stream_seq = 0
        # prefix-affinity books (ISSUE 20 / ROADMAP 2a): affinity key ->
        # replica_id of the LAST successful assignment with that prompt
        # head, bounded LRU (guarded by self._lock). Dispatch prefers the
        # mapped replica within FleetView.AFFINITY_SLACK; a failover simply
        # re-points the key at the surviving replica it lands on.
        self._affinity: "collections.OrderedDict[int, str]" = (
            collections.OrderedDict()
        )
        self.affinity_cap = 4096
        self.affinity_hits = 0     # assignments landed on the affine replica
        self.affinity_misses = 0   # keyed assignments that landed elsewhere
        # fleet counters (also exported via obs metrics)
        self.submitted = 0
        self.completed = 0
        self.failovers = 0
        self.hedges = 0
        self.late_results_dropped = 0
        self.shed = 0
        self.replica_evictions = 0
        self.drains_completed = 0
        # requests this incarnation ADOPTED from replica state via the
        # takeover sweep (it never saw their submit — a dead predecessor did)
        self.adopted = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Router":
        if self._reaper is None:
            self._reaper = threading.Thread(
                target=self._reap_loop, name="router-reaper", daemon=True
            )
            self._reaper.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._reaper is not None:
            self._reaper.join(timeout=5.0)
        for t in list(self._pumps):
            t.join(timeout=5.0)
        with self._lock:
            clients = list(self._submit_clients.values())
            self._submit_clients.clear()
        for _lk, c in clients:
            c.close()

    # -- replica membership (RouterServer RPC surface) -----------------------
    def register_replica(self, endpoint: Sequence,
                         load: Optional[dict] = None) -> dict:
        rep = self.fleet.register((endpoint[0], int(endpoint[1])))
        if load:
            rep.load = dict(load)
        # takeover sweep (ISSUE 18) — BEFORE the pump starts, so the first
        # pump cycle already polls every adopted request. For a fresh
        # replica this is one cheap empty-reply RPC; for a replica
        # re-registering after a router takeover (or an eviction it
        # outlived) it rebuilds this incarnation's in-flight/dedup books
        # from the data plane. Cold path: once per registration EVENT.
        self._sweep_replica(rep)
        pump = threading.Thread(
            target=self._pump_loop, args=(rep,),
            name=f"router-pump-{rep.replica_id}", daemon=True,
        )
        self._pumps.append(pump)
        pump.start()
        stats.FT_EVENTS.incr("router_replica_joined")
        log.info("replica %s joined at %s:%d", rep.replica_id, *rep.endpoint)
        return {"replica_id": rep.replica_id, "lease_s": self.fleet.lease_s,
                "instance": self.instance}

    def replica_heartbeat(self, replica_id: Optional[str],
                          load: Optional[dict] = None) -> dict:
        # every reply names this incarnation: the agent's fencing compares
        # it against the incarnation it registered with (ISSUE 18)
        rep = self.fleet.heartbeat(replica_id, load)
        if rep is None:
            return {"ok": False, "reregister": True,
                    "instance": self.instance}
        if rep.drained:
            return {"ok": True, "drained": True, "instance": self.instance}
        if rep.state == ReplicaState.DRAINING:
            return {"ok": True, "drain": True, "instance": self.instance}
        if rep.state not in (ReplicaState.LIVE,):
            # evicted lease the replica outlived (wedge healed, partition
            # closed): rejoin fresh; the old pump still catches late results
            return {"ok": False, "reregister": True,
                    "instance": self.instance}
        return {"ok": True, "instance": self.instance}

    # -- takeover sweep (ISSUE 18) -------------------------------------------
    def _sweep_replica(self, rep: Replica) -> None:
        """Stateless-reconciling takeover: ask a just-registered replica for
        every keyed request it still holds (in flight AND server-held
        results) and rebuild the fleet books — handles, the (tenant, key)
        dedup map, rid mappings, seeds. After a router death the data plane
        is the only copy of this state; one sweep per registration event
        recovers it without a journal. Connection/err failures degrade to
        an empty sweep: the replica is simply treated as fresh."""
        lock, client = self._submit_client(rep)
        try:
            with lock:
                # rpc-ok: ONE sweep call per replica registration event
                # (cold path — never in the pump/dispatch/reap loops)
                resp = client.call("outstanding")
        except (ConnectionError, OSError):
            return
        items = resp.get("requests") or []
        if not items:
            return
        # clock-ok: one admission stamp for the whole adopted batch
        now = time.monotonic()
        adopted = 0
        with self._lock:
            for item in items:
                try:
                    adopted += self._adopt_locked(rep, item, now)
                except (KeyError, TypeError, ValueError):
                    continue  # one malformed item must not void the sweep
        if adopted:
            stats.FT_EVENTS.incr("router_requests_adopted", adopted)
            log.warning(
                "takeover sweep: adopted %d request(s) from replica %s",
                adopted, rep.replica_id,
            )
        self._notify_streams()

    def _adopt_locked(self, rep: Replica, item: dict, now: float) -> int:
        """Fold one `outstanding` item into the books (caller holds the
        lock). Returns 1 when a NEW handle was minted (this incarnation
        never saw the request), 0 for a key we already track — in which
        case the replica's copy is mapped as an additional assignment and
        the dedup latch arbitrates: first terminal answer wins, the other
        is dropped-and-counted exactly like a hedge loser or late winner."""
        tenant = str(item.get("tenant_id") or "default")
        key = str(item["client_req_id"])
        rrid = int(item["request_id"])
        rid = self._by_key.get((tenant, key))
        h = self._handles.get(rid) if rid is not None else None
        if h is None:
            rid = next(self._ids)
            h = RouterHandle(
                rid, tenant, [int(t) for t in item.get("prompt") or []],
                item.get("max_new_tokens"), key,
                # re-pin the seed from replica state: a later failover of
                # this request re-submits under the SAME sampling identity,
                # so re-execution is token-identical, greedy AND sampled
                seed=int(item.get("seed") or 0) & 0xFFFFFFFF,
                now=now,
                temperature=item.get("temperature"),
                top_k=item.get("top_k"),
            )
            h._router = self
            h.status = RouterHandle.RUNNING
            self._handles[rid] = h
            self._by_key[(tenant, key)] = rid
            self.adopted += 1
            fresh = 1
        else:
            fresh = 0
        if h._finished:
            # already delivered by a survivor: map the replica's copy for
            # polling only, so its eventual answer lands in the dedup latch
            # (dropped + counted), never re-delivered
            rep.rids[h.request_id] = rrid
            return fresh
        rep.rids[h.request_id] = rrid
        rep.outstanding.add(h.request_id)
        h.assignments[rep.replica_id] = rrid
        self._unassigned.discard(h.request_id)
        h.t_parked = None
        return fresh

    def get_by_key(self, tenant: str, key: str) -> Optional[RouterHandle]:
        """Resolve a request by its (tenant, client_req_id) identity — what
        a client reattaching across a takeover presents when its request_id
        names a dead incarnation's books."""
        with self._lock:
            rid = self._by_key.get((str(tenant), str(key)))
            return self._handles.get(rid) if rid is not None else None

    def deregister_replica(self, replica_id: Optional[str]) -> bool:
        rep = self.fleet.get(replica_id) if replica_id else None
        if rep is None:
            return False
        self._evict(rep, "deregister")
        return True

    def drain(self, replica_id: str,
              deadline_s: Optional[float] = None) -> dict:
        """Planned drain: stop new assignments now; in-flight streams get
        until the deadline (then fail over); the lease drops when empty."""
        rep = self.fleet.get(replica_id)
        if rep is None or rep.state not in (
            ReplicaState.LIVE, ReplicaState.DRAINING
        ):
            return {"err": f"no live replica {replica_id!r}"}
        # clock-ok: once per drain ORDER (an operator/controller action)
        now = time.monotonic()
        with self._lock:
            rep.state = ReplicaState.DRAINING
            rep.drain_deadline = now + float(
                deadline_s if deadline_s is not None else self.drain_deadline_s
            )
            outstanding = len(rep.outstanding)
        stats.FT_EVENTS.incr("router_drain_ordered")
        log.warning(
            "drain ordered for replica %s: %d stream(s) in flight, "
            "deadline %.1fs", replica_id, outstanding,
            rep.drain_deadline - now,
        )
        return {"ok": True, "replica_id": replica_id,
                "outstanding": outstanding}

    # -- client surface ------------------------------------------------------
    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        ttft_deadline_s: Optional[float] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        seed: Optional[int] = None,
        client_req_id: Optional[str] = None,
        hedge_ttft_s: Optional[float] = None,
    ) -> RouterHandle:
        """Dispatch one request to the least-loaded live replica. Raises
        QuotaExceeded (reason 'overload', tightest retry_after_ms across the
        fleet) when no replica will take it — the fleet-wide shed; a shed
        submit leaves no state behind, so the client's retry is a fresh
        request. A repeated (tenant, client_req_id) reattaches to the
        original handle (the fleet-level dedup map)."""
        # clock-ok: once per SUBMIT (admission stamp; deadlines, hedge and
        # park timing all derive from it) — never per replica tried
        now = time.monotonic()
        prompt = [int(t) for t in prompt]
        with self._lock:
            if client_req_id is not None:
                rid = self._by_key.get((tenant, str(client_req_id)))
                if rid is not None and rid in self._handles:
                    return self._handles[rid]  # idempotent reattach
            rid = next(self._ids)
            key = client_req_id if client_req_id is not None else f"fleet-{rid}"
            h = RouterHandle(
                rid, tenant, prompt, max_new_tokens, str(key),
                seed=(int(seed) if seed is not None else rid) & 0xFFFFFFFF,
                now=now, deadline_s=deadline_s,
                ttft_deadline_s=ttft_deadline_s,
                temperature=temperature, top_k=top_k,
                hedge_ttft_s=(
                    hedge_ttft_s if hedge_ttft_s is not None
                    else self.hedge_ttft_s
                ),
            )
            h._router = self
            self._handles[rid] = h
            self._by_key[(tenant, str(key))] = rid
            self.submitted += 1
        live = self.fleet.live()
        if not live:
            self._discard(h, now)
            self.shed += 1
            obs_metrics.observe_router_shed("no_replicas")
            raise QuotaExceeded(
                "no live replicas behind the router", "overload",
                retry_after_ms=int(self.fleet.lease_s * 1000),
            )
        if deadline_s is not None and deadline_s > 0:
            # fleet-wide proactive shed, pure piggybacked state: when EVERY
            # live replica's own queue-wait estimate already exceeds the
            # request's budget, forwarding would only collect N shed replies
            waits = [
                float(r.load.get("estimated_queue_wait_s", 0.0) or 0.0)
                for r in live
            ]
            if waits and min(waits) > float(deadline_s):
                self._discard(h, now)
                self.shed += 1
                obs_metrics.observe_router_shed("overload")
                raise QuotaExceeded(
                    f"fleet saturated: best replica queue-wait estimate "
                    f"{min(waits):.2f}s exceeds the {deadline_s:.2f}s "
                    f"deadline budget", "overload",
                    retry_after_ms=max(1, int(min(waits) * 1000)),
                )
        try:
            ok, hints = self._try_assign(h, now=now, park_on_fail=False)
        except _BadRequest:
            # the replica refused for a non-load reason (bad prompt, over
            # max_len): the client's error — leave no fleet state behind
            self._discard(h, now)
            raise
        if not ok:
            self._discard(h, now)
            self.shed += 1
            obs_metrics.observe_router_shed("overload")
            hint = min([x for x in hints if x is not None], default=None)
            raise QuotaExceeded(
                "every live replica shed this request", "overload",
                retry_after_ms=(
                    hint if hint is not None
                    else int(self.fleet.lease_s * 1000)
                ),
            )
        return h

    def get_handle(self, request_id: int) -> Optional[RouterHandle]:
        with self._lock:
            return self._handles.get(int(request_id))

    def _notify_streams(self) -> None:
        """Wake RouterServer frame pushers: a mirror advanced or a handle
        reached a terminal state (same contract as the session's engine-step
        bump — no socket writes happen here)."""
        with self._stream_cv:
            self._stream_seq += 1
            self._stream_cv.notify_all()

    def stream_wait(self, seq: int, timeout: float = 0.25) -> int:
        """Block (pusher side) until the mirrors advance past `seq` or the
        timeout elapses; returns the current sequence."""
        with self._stream_cv:
            if self._stream_seq == seq:
                self._stream_cv.wait(timeout)
            return self._stream_seq

    def cancel(self, request_id: int) -> bool:
        # clock-ok: once per client CANCEL order, not on any per-step path
        now = time.monotonic()
        with self._lock:
            h = self._handles.get(int(request_id))
            if h is None or not h._finish_locked(
                RouterHandle.CANCELLED, FinishReason.CANCELLED, now
            ):
                # unknown, or a pump delivery won the race — the delivered
                # result stands, this cancel is a no-op
                return False
            cancels = self._strip_assignments_locked(h)
            self._unassigned.discard(h.request_id)
        self._send_cancels(cancels)
        h._event.set()
        self._notify_streams()
        return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            outstanding = sum(
                1 for h in self._handles.values() if not h.done
            )
            parked = len(self._unassigned)
        reps = [r.view() for r in self.fleet.replicas()]
        return {
            "replicas": reps,
            "live_replicas": sum(1 for r in reps if r["state"] == "live"),
            "submitted": self.submitted,
            "completed": self.completed,
            "outstanding": outstanding,
            "parked": parked,
            "failovers": self.failovers,
            "hedges": self.hedges,
            "late_results_dropped": self.late_results_dropped,
            "shed": self.shed,
            "replica_evictions": self.replica_evictions,
            "drains_completed": self.drains_completed,
            "adopted_requests": self.adopted,
            "affinity_hits": self.affinity_hits,
            "affinity_misses": self.affinity_misses,
            "instance": self.instance,
            # the tightest current queue-wait estimate across live replicas:
            # what a load balancer above THIS tier would piggyback on
            "estimated_queue_wait_s": min(
                [
                    float(r["load"].get("estimated_queue_wait_s", 0.0) or 0.0)
                    for r in reps if r["state"] == "live"
                ],
                default=0.0,
            ),
            # fleet-wide cumulative pressure counters, summed from the
            # piggybacked per-replica snapshots (fleet.LOAD_KEYS) — the
            # autoscaler's shed/deadline-miss signal, zero extra RPCs
            "fleet_shed": sum(
                int(r["load"].get("shed", 0) or 0)
                for r in reps if r["state"] == "live"
            ),
            "fleet_deadline_misses": sum(
                int(r["load"].get("deadline_misses", 0) or 0)
                for r in reps if r["state"] == "live"
            ),
        }

    # -- assignment path -----------------------------------------------------
    def _fail_parked(self, h: RouterHandle, reason: str, now: float) -> None:
        with self._lock:
            self._unassigned.discard(h.request_id)
            finished = h._finish_locked(RouterHandle.CANCELLED, reason, now)
        if finished:
            h._event.set()
            self._notify_streams()

    def _discard(self, h: RouterHandle, now: Optional[float] = None) -> None:
        """Remove a front-door-shed (or bad) request from the fleet books —
        and COMPLETE it cancelled first: a concurrent retry with the same
        idempotency key may have reattached to this handle between its
        registration and this shed, and that caller must get a prompt
        raise from result(), not a hang on a handle nobody owns anymore."""
        with self._lock:
            self._handles.pop(h.request_id, None)
            self._by_key.pop((h.tenant, h.key), None)
            finished = h._finish_locked(
                RouterHandle.CANCELLED, FinishReason.CANCELLED,
                now if now is not None else h.t_submit,
            )
        if finished:
            h._event.set()
            self._notify_streams()

    def _submit_client(self, rep: Replica) -> Tuple[threading.Lock, MasterClient]:
        with self._lock:
            got = self._submit_clients.get(rep.replica_id)
            if got is None:
                got = (
                    threading.Lock(),
                    MasterClient(rep.endpoint, **self._replica_client_kw),
                )
                self._submit_clients[rep.replica_id] = got
            return got

    def _choose_replica(self, exclude: Set[str],
                        affinity: Optional[int] = None) -> Optional[Replica]:
        """Pure piggybacked-state choice — no RPC lives here (lint-pinned).
        With an affinity key, the replica that last served this prompt head
        is preferred (within the fleet's load slack); a dead or excluded
        affine replica degrades to plain least-loaded."""
        prefer = None
        if affinity is not None:
            with self._lock:
                prefer = self._affinity.get(affinity)
        return self.fleet.choose(exclude=exclude, prefer=prefer)

    def _try_assign(self, h: RouterHandle, now: float,
                    exclude: Optional[Set[str]] = None,
                    park_on_fail: bool = True) -> Tuple[bool, List[Optional[int]]]:
        """Walk replicas least-loaded-first until one accepts; collect shed
        hints. On total failure either park the request for the reaper's
        retry (failover path) or report back (front-door path)."""
        tried: Set[str] = set(exclude or ())
        hints: List[Optional[int]] = []
        while not h._finished:
            rep = self._choose_replica(tried, affinity=h.affinity)
            if rep is None:
                break
            try:
                self._forward(rep, h, now)
                return True, hints
            except QuotaExceeded as e:
                hints.append(e.retry_after_ms)
                tried.add(rep.replica_id)
            except _BadRequest:
                raise
            except (ConnectionError, OSError):
                tried.add(rep.replica_id)
                self._note_conn_failure(rep)
        if park_on_fail and not h._finished:
            with self._lock:
                if h.t_parked is None:
                    h.t_parked = now
                self._unassigned.add(h.request_id)
        return False, hints

    def _forward(self, rep: Replica, h: RouterHandle, now: float) -> None:
        """The ONE blocking RPC in the assignment path: forward the submit
        to the chosen replica under the fleet idempotency key + pinned seed,
        then record the assignment. Raises QuotaExceeded on a replica shed
        (hint attached), _BadRequest on a non-load refusal, ConnectionError
        when the replica is unreachable."""
        kw: Dict[str, Any] = dict(
            prompt=h.prompt, max_new_tokens=h.max_new_tokens,
            tenant_id=h.tenant, client_req_id=h.key, seed=h.seed,
            temperature=h.temperature, top_k=h.top_k,
        )
        if h.t_deadline is not None:
            kw["deadline_s"] = max(1e-3, h.t_deadline - now)
        if h.t_ttft_deadline is not None:
            kw["ttft_deadline_s"] = max(1e-3, h.t_ttft_deadline - now)
        lock, client = self._submit_client(rep)
        # span-ok: one ring write per ASSIGNMENT (submit/failover/hedge),
        # never per decode step or per poll cycle
        with trace.span("router.assign", request_id=h.request_id):
            with lock:
                # rpc-ok: the sanctioned submit forward — the single
                # blocking replica RPC the assignment path is allowed
                resp = client.call("submit", **kw)
        if "err" in resp:
            if resp.get("rejected"):
                raise QuotaExceeded(
                    str(resp["err"]), str(resp["rejected"]),
                    retry_after_ms=resp.get("retry_after_ms"),
                )
            raise _BadRequest(str(resp["err"]))
        rrid = int(resp["request_id"])
        with self._lock:
            if h.affinity is not None:
                # record (and LRU-refresh) the prompt-head -> replica map;
                # a failover landing elsewhere re-points the key so the
                # NEXT request with this head follows the warm cache
                if self._affinity.get(h.affinity) == rep.replica_id:
                    self.affinity_hits += 1
                else:
                    if h.affinity in self._affinity:
                        self.affinity_misses += 1
                    self._affinity[h.affinity] = rep.replica_id
                self._affinity.move_to_end(h.affinity)
                while len(self._affinity) > self.affinity_cap:
                    self._affinity.popitem(last=False)
            rep.rids[h.request_id] = rrid
            rep.outstanding.add(h.request_id)
            rep.assigned_total += 1
            h.assignments[rep.replica_id] = rrid
            if h.status == RouterHandle.QUEUED:
                h.status = RouterHandle.RUNNING
            self._unassigned.discard(h.request_id)
            h.t_parked = None
            evicted_meanwhile = rep.state not in (
                ReplicaState.LIVE, ReplicaState.DRAINING
            )
        if evicted_meanwhile:
            # the replica died between choose and record: hand the request
            # straight back to the failover path instead of stranding it
            self._failover_requests(rep, [h.request_id], "evicted_mid_assign")

    def _note_conn_failure(self, rep: Replica) -> None:
        with self._lock:
            rep.conn_failures += 1
            dead = (
                rep.state in (ReplicaState.LIVE, ReplicaState.DRAINING)
                and rep.conn_failures >= self.CONN_FAILURE_EVICT
            )
        if dead:
            self._evict(rep, "conn")

    # -- failover ------------------------------------------------------------
    def _strip_assignments_locked(self, h: RouterHandle) -> List[Tuple[str, int, str]]:
        """Drop every live assignment of `h` (caller holds self._lock);
        returns (replica_id, replica_rid, tenant) triples to cancel."""
        cancels = []
        for rep_id, rrid in list(h.assignments.items()):
            rep = self.fleet.get(rep_id)
            if rep is not None:
                rep.outstanding.discard(h.request_id)
                rep.rids.pop(h.request_id, None)
                rep.poll_cursors.pop(h.request_id, None)
            cancels.append((rep_id, rrid, h.tenant))
            del h.assignments[rep_id]
        return cancels

    def _send_cancels(self, cancels: List[Tuple[str, int, str]]) -> None:
        # pipelined (ISSUE 20): group per replica and ship each group as
        # ONE batch on the shared socket — a drain-timeout or multi-hedge
        # teardown stops paying a round trip per cancelled request
        by_rep: Dict[str, List[Tuple[int, str]]] = {}
        for rep_id, rrid, tenant in cancels:
            by_rep.setdefault(rep_id, []).append((rrid, tenant))
        for rep_id, batch in by_rep.items():
            rep = self.fleet.get(rep_id)
            if rep is None:
                continue
            lock, client = self._submit_client(rep)
            try:
                with lock:
                    # rpc-ok: per cancel/hedge-loser order, never per step
                    client.call_many([
                        ("cancel", {"request_id": rrid, "tenant_id": tenant})
                        for rrid, tenant in batch
                    ])
            except (ConnectionError, OSError):
                pass  # dead replica: nothing to cancel anymore

    def _evict(self, rep: Replica, cause: str) -> None:
        """A replica stopped being assignable (lease lapsed, connection
        dead, deregistered): fail its outstanding requests over to
        survivors. The pump keeps polling it for `late_grace_s` so a
        partitioned-not-dead replica's late answers land in the dedup map
        (dropped + counted) instead of vanishing unobserved."""
        # clock-ok: once per EVICTION event, not per request or per poll
        now = time.monotonic()
        with self._lock:
            if rep.state not in (ReplicaState.LIVE, ReplicaState.DRAINING):
                return
            rep.state = ReplicaState.EVICTED
            rep.evicted_at = now
            victims = sorted(rep.outstanding)
            rep.outstanding.clear()
        self.replica_evictions += 1
        self.fleet.evicted_total += 1
        stats.FT_EVENTS.incr("router_replica_evicted")
        obs_metrics.observe_replica_evicted(cause)
        log.warning(
            "replica %s evicted (%s); failing %d in-flight request(s) over",
            rep.replica_id, cause, len(victims),
        )
        self._failover_requests(rep, victims, cause, now=now)

    def _failover_requests(self, rep: Replica, rids: List[int], cause: str,
                           now: Optional[float] = None) -> None:
        if now is None:
            # clock-ok: once per failover BATCH (an eviction/drain event)
            now = time.monotonic()
        for rid in rids:
            with self._lock:
                h = self._handles.get(rid)
                if h is None:
                    continue
                h.assignments.pop(rep.replica_id, None)
                if h._finished or h.assignments:
                    continue  # delivered, or a hedge partner still lives
            h.failovers += 1
            self.failovers += 1
            obs_metrics.observe_replica_failover(cause)
            # span-ok: one ring write per FAILED-OVER request (rare path)
            with trace.span("router.failover", request_id=rid):
                self._try_assign(
                    h, now=now, exclude={rep.replica_id}, park_on_fail=True
                )

    def _finish_drain(self, rep: Replica) -> None:
        with self._lock:
            if rep.state != ReplicaState.DRAINING:
                return
            rep.state = ReplicaState.DRAINED
            rep.drained = True
        self.drains_completed += 1
        stats.FT_EVENTS.incr("router_drain_complete")
        log.warning("replica %s drained and deregistered", rep.replica_id)

    # -- result pump (one thread per replica) --------------------------------
    def _pump_loop(self, rep: Replica) -> None:
        client = MasterClient(
            rep.endpoint,
            timeout=self._replica_client_kw.get("timeout", 5.0),
            retries=1,
        )
        try:
            while not self._stop.is_set():
                ok = self._pump_once(rep, client)
                with self._lock:
                    if ok is True:
                        # only a SUCCESSFUL round trip resets the failure
                        # count: the no-op case (ok is None, nothing to
                        # poll) must not keep absolving an asymmetrically
                        # partitioned replica whose submit forwards fail —
                        # an idle replica scores least-loaded, so every
                        # submit would eat its connect timeout forever
                        rep.conn_failures = 0
                    state = rep.state
                    idle = not rep.rids
                    evicted_at = rep.evicted_at
                if ok is False:
                    self._note_conn_failure(rep)
                if state == ReplicaState.EVICTED:
                    # grace window: keep polling a possibly-partitioned
                    # replica so late winners reach the dedup map
                    if (idle or time.monotonic()  # clock-ok: grace check,
                            # once per pump cycle while evicted
                            > (evicted_at or 0.0) + self.late_grace_s):
                        break
                if state == ReplicaState.DRAINED and idle:
                    break
                if self._stop.wait(self.poll_interval_s):
                    break
        finally:
            with self._lock:
                rep.state = ReplicaState.CLOSED
                sc = self._submit_clients.pop(rep.replica_id, None)
            if sc is not None:
                sc[1].close()
            client.close()

    def _pump_once(self, rep: Replica,
                   client: MasterClient) -> Optional[bool]:
        """One batch poll of every request still mapped on this replica —
        ONE round trip regardless of in-flight count. Returns True on a
        successful round trip, False on a connection failure (the loop
        counts those toward eviction), None when there was nothing to poll
        (no RPC happened — proves nothing about the connection)."""
        with self._lock:
            pairs = [
                (rid, rrid, self._handles[rid].tenant,
                 rep.poll_cursors.get(rid, 0))
                for rid, rrid in rep.rids.items()
                if rid in self._handles
            ]
        if not pairs:
            return None
        # delta poll (ISSUE 16): each item names the cursor this pump
        # already folded, so steady-state cycles move O(new tokens) per
        # request instead of O(all tokens) — the replica clamps a stale
        # cursor back to a full reply, so this is never a correctness seam
        items = [
            {"request_id": rrid, "tenant_id": tenant, "from": cur}
            for _, rrid, tenant, cur in pairs
        ]
        try:
            # rpc-ok: the sanctioned batch poll — per pump CYCLE per
            # replica, never per request
            resp = client.call("poll_many", items=items)
        except (ConnectionError, OSError):
            return False
        # clock-ok: ONE wall-clock read per pump cycle stamps every result
        # processed from this batch (TTFT mirrors, completion times)
        now = time.monotonic()
        by_rrid = {}
        for entry in resp.get("results", []):
            if isinstance(entry, dict) and "request_id" in entry:
                by_rrid[int(entry["request_id"])] = entry
        for rid, rrid, _tenant, _cur in pairs:
            entry = by_rrid.get(rrid)
            if entry is not None:
                self._on_result(rep, rid, entry, now)
        return True

    def _on_result(self, rep: Replica, rid: int, entry: dict,
                   now: float) -> None:
        """Fold one poll_many entry into the fleet books. The dedup latch
        lives here: the FIRST terminal result for a fleet request wins; a
        later one (the failed-over original finally answering) is dropped
        and counted."""
        delivered = False
        grew = False
        cancels: List[Tuple[str, int, str]] = []
        late = False
        with self._lock:
            h = self._handles.get(rid)
            if h is None:
                rep.rids.pop(rid, None)
                rep.poll_cursors.pop(rid, None)
                rep.outstanding.discard(rid)
                return
            if entry.get("err"):
                # the replica no longer knows this id (process restart,
                # handle GC): that assignment is void — re-place unless a
                # partner still runs it
                rep.rids.pop(rid, None)
                rep.poll_cursors.pop(rid, None)
                rep.outstanding.discard(rid)
                h.assignments.pop(rep.replica_id, None)
                if not h._finished and not h.assignments:
                    if h.t_parked is None:
                        h.t_parked = now
                    self._unassigned.add(rid)
                return
            toks = [int(t) for t in (entry.get("tokens") or [])]
            if not entry.get("done"):
                base = entry.get("from")
                # advance this pump's cursor to what the replica now holds
                # (a delta reply echoes tokens_so_far; a legacy full reply
                # just counts its tokens)
                rep.poll_cursors[rid] = (
                    int(entry.get("tokens_so_far", len(toks)))
                    if base is not None else len(toks)
                )
                if toks and not h._finished:
                    if base is None:
                        merged = toks  # legacy full-list reply
                    elif int(base) > len(h.tokens):
                        # cursor ran ahead of the mirror (stale books):
                        # drop the gapped suffix and refetch full next cycle
                        merged = None
                        rep.poll_cursors[rid] = 0
                    else:
                        merged = h.tokens[: int(base)] + toks
                    # grow-only: the mirror is a prefix-consistent record —
                    # a slower replica's shorter view never rolls it back
                    if merged is not None and len(merged) > len(h.tokens):
                        h.tokens = merged
                        grew = True
                        if h.t_first_token is None:
                            h.t_first_token = now
                    if grew and len(h.assignments) > 1:
                        # first token wins: cancel the hedge loser(s)
                        winner = rep.replica_id
                        for rep_id, rrid in list(h.assignments.items()):
                            if rep_id == winner:
                                continue
                            other = self.fleet.get(rep_id)
                            if other is not None:
                                other.outstanding.discard(rid)
                                other.rids.pop(rid, None)
                                other.poll_cursors.pop(rid, None)
                            cancels.append((rep_id, rrid, h.tenant))
                            del h.assignments[rep_id]
            else:
                rep.rids.pop(rid, None)
                rep.poll_cursors.pop(rid, None)
                rep.outstanding.discard(rid)
                h.assignments.pop(rep.replica_id, None)
                status = (
                    RouterHandle.CANCELLED if entry.get("cancelled")
                    else RouterHandle.DONE
                )
                if not h._finish_locked(status, entry.get("finish_reason"),
                                        now):
                    # the late winner: already delivered from a survivor —
                    # drop, count, and leave the delivered result untouched
                    h.late_drops += 1
                    rep.late_results_dropped += 1
                    self.late_results_dropped += 1
                    late = True
                else:
                    h.delivered_by = rep.replica_id
                    if toks:
                        h.tokens = [int(t) for t in toks]
                        if h.t_first_token is None:
                            h.t_first_token = now
                    if status == RouterHandle.DONE:
                        self.completed += 1
                    delivered = True
                    cancels = self._strip_assignments_locked(h)
        if late:
            stats.FT_EVENTS.incr("router_late_result_dropped")
            obs_metrics.observe_late_result_dropped()
            return
        if cancels:
            self._send_cancels(cancels)
        if delivered:
            h._event.set()
        if delivered or grew:
            self._notify_streams()

    # -- reaper --------------------------------------------------------------
    def _reap_loop(self) -> None:
        period = max(0.05, min(0.5, self.fleet.lease_s / 4.0))
        while not self._stop.wait(period):
            try:
                self._reap_once()
            except Exception:
                log.exception("router reaper tick failed")

    def _reap_once(self) -> None:
        """One maintenance tick: lease evictions, drain completion, parked
        re-assignment, hedge launches, handle GC — every decision off ONE
        timestamp."""
        # clock-ok: the single per-tick read every reaper decision batches on
        now = time.monotonic()
        for rep in self.fleet.expired(now):
            self._evict(rep, "lease")
        for rep in self.fleet.replicas():
            if rep.state != ReplicaState.DRAINING:
                continue
            with self._lock:
                empty = not rep.outstanding
                past = rep.drain_deadline is not None and now > rep.drain_deadline
                stragglers = sorted(rep.outstanding) if past else []
                cancels = []
                if past:
                    rep.outstanding.clear()
                    for rid in stragglers:
                        # unlike an eviction (replica presumed dead), a
                        # drain-timeout replica is ALIVE: cancel its copy so
                        # it stops decoding and releases slots + KV pages —
                        # otherwise the straggler runs twice and its
                        # eventual completion miscounts as a late winner
                        rrid = rep.rids.pop(rid, None)
                        rep.poll_cursors.pop(rid, None)
                        h = self._handles.get(rid)
                        if rrid is not None and h is not None:
                            cancels.append((rep.replica_id, rrid, h.tenant))
            if stragglers:
                log.warning(
                    "drain deadline passed on %s with %d stream(s) in "
                    "flight; cancelling there and failing them over",
                    rep.replica_id, len(stragglers),
                )
                self._send_cancels(cancels)
                self._failover_requests(rep, stragglers, "drain_timeout",
                                        now=now)
                empty = True
            if empty:
                self._finish_drain(rep)
        # parked (unplaceable) requests: retry, expire, or give up named
        with self._lock:
            parked = [
                self._handles[rid] for rid in list(self._unassigned)
                if rid in self._handles
            ]
        for h in parked:
            if h.done:
                with self._lock:
                    self._unassigned.discard(h.request_id)
                continue
            if h.t_deadline is not None and now >= h.t_deadline:
                self._fail_parked(h, FinishReason.DEADLINE, now)
                continue
            ok, _hints = self._try_assign(h, now=now, park_on_fail=True)
            if ok:
                continue
            if (not self.fleet.live()
                    and h.t_parked is not None
                    and now - h.t_parked > self.park_give_up_s):
                self._fail_parked(h, FinishReason.REPLICA_LOST, now)
        # hedging: duplicate token-less requests past their TTFT hedge onto
        # a second replica (same key + seed; first token wins)
        with self._lock:
            hedgeable = [
                h for h in self._handles.values()
                if (not h.done and h.hedge_ttft_s is not None
                    and not h.hedged and not h.tokens
                    and len(h.assignments) == 1
                    and now - h.t_submit >= h.hedge_ttft_s)
            ]
        for h in hedgeable:
            exclude = set(h.assignments)
            # span-ok: one ring write per HEDGE launch (TTFT-miss path)
            with trace.span("router.hedge", request_id=h.request_id):
                ok, _hints = self._try_assign(
                    h, now=now, exclude=exclude, park_on_fail=False
                )
            if ok:
                h.hedged = True
                self.hedges += 1
                stats.FT_EVENTS.incr("router_hedge")
                obs_metrics.observe_router_hedge()
        # GC finished handles past the TTL (submit-and-vanish clients)
        cutoff = now - self.handle_ttl_s
        with self._lock:
            stale = [
                rid for rid, h in self._handles.items()
                if h.done and (h.t_done or 0) < cutoff
            ]
            for rid in stale:
                h = self._handles.pop(rid)
                self._by_key.pop((h.tenant, h.key), None)
                self._unassigned.discard(rid)


class RouterServer:
    """The router behind the same line-JSON TCP surface a ServingServer
    exposes (reusing its request handler), so a `ServingClient` — and every
    retry/idempotency/hedging behavior it already has — talks to a router
    unchanged. Adds the replica-facing methods (replica_register /
    replica_heartbeat / replica_deregister) and the ops methods (drain /
    replicas)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        lease_s: float = 5.0,
        tenant_lease_s: float = 30.0,
        **router_kw,
    ):
        import socketserver

        from paddle_tpu.serving.server import _Handler

        self.router = Router(lease_s=lease_s, **router_kw)
        self.membership = _Membership(tenant_lease_s)
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.ctx = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._killed = False
        self.stream_frames = 0
        self.stream_bytes = 0
        self.stream_tokens = 0
        self.stream_coalesced = 0
        self.stream_active = 0  # pushers currently attached (fan-out gauge)
        self._stream_lock = threading.Lock()

    @property
    def address(self) -> tuple:
        return self._srv.server_address

    @property
    def fleet(self) -> FleetView:
        return self.router.fleet

    def dispatch(self, method: str, req: dict,
                 tenant_id: Optional[str]) -> dict:
        r = self.router
        if method == "register":
            tid = self.membership.register(role="tenant")
            return {"tenant_id": tid, "lease_s": self.membership.lease_s}
        if method == "heartbeat":
            return {"ok": bool(tenant_id)}
        if method == "deregister":
            if tenant_id:
                self.membership.drop(tenant_id)
            return {"ok": bool(tenant_id)}
        if method == "replica_register":
            ep = req.get("endpoint")
            if (not isinstance(ep, (list, tuple)) or len(ep) != 2):
                return {"err": f"replica_register needs endpoint [host, "
                               f"port], got {ep!r}"}
            return r.register_replica(ep, req.get("load"))
        if method == "replica_heartbeat":
            return r.replica_heartbeat(req.get("replica_id"), req.get("load"))
        if method == "replica_deregister":
            return {"ok": r.deregister_replica(req.get("replica_id"))}
        if method == "drain":
            return r.drain(str(req.get("replica_id")), req.get("deadline_s"))
        if method == "replicas":
            return {"replicas": [x.view() for x in r.fleet.replicas()]}
        if method == "stats":
            out = r.stats()
            out["live_tenants"] = self.membership.live
            out["stream_frames_pushed"] = self.stream_frames
            out["stream_bytes_pushed"] = self.stream_bytes
            out["stream_tokens_pushed"] = self.stream_tokens
            out["stream_frames_coalesced"] = self.stream_coalesced
            return out
        if method == "metrics":
            return {"text": obs_metrics.to_prometheus_text()}
        if method == "trace_export":
            return {"chrome_trace": trace.export_chrome()}
        if method in ("submit", "generate"):
            tenant = tenant_id or "default"
            try:
                h = r.submit(
                    req["prompt"], req.get("max_new_tokens"), tenant=tenant,
                    deadline_s=req.get("deadline_s"),
                    ttft_deadline_s=req.get("ttft_deadline_s"),
                    temperature=req.get("temperature"),
                    top_k=req.get("top_k"),
                    seed=req.get("seed"),
                    client_req_id=req.get("client_req_id"),
                    hedge_ttft_s=req.get("hedge_ttft_s"),
                )
            except _BadRequest as e:
                # the replica's own error text, unwrapped: a client talking
                # to the router must see the same err shape it would get
                # from one server ("ValueError: empty prompt"), not the
                # router's internal exception class
                return {"err": str(e)}
            if method == "submit":
                out = {"request_id": h.request_id}
                if req.get("stream"):
                    # push streaming THROUGH the router (ISSUE 16): frames
                    # follow on this connection as the pump advances the
                    # mirror; the pump's poll stays authoritative
                    out["stream"] = True
                    out["_stream"] = (h, 0)
                return out
            try:
                h.result(timeout=float(req.get("timeout_s", 120.0)),
                         cancel_on_timeout=False)
            except TimeoutError:
                return {
                    "err": "generate timed out router-side; still running",
                    "request_id": h.request_id, "done": False,
                }
            except RuntimeError:
                pass  # cancelled: _completion names the reason
            return dict(self._completion(h), request_id=h.request_id)
        if method in ("poll", "cancel", "stream"):
            from paddle_tpu.serving.server import clamp_cursor

            if req.get("client_req_id"):
                # identity is the (tenant, client_req_id) key, NOT the rid:
                # after a takeover this incarnation's rid counter restarted,
                # so the client's stale rid may name a DIFFERENT request —
                # resolving by rid would hand it someone else's tokens. The
                # takeover sweep rebuilt the key map from replica state;
                # a key miss means the request is not in these books.
                h = r.get_by_key(tenant_id or "default",
                                 str(req["client_req_id"]))
            else:
                h = r.get_handle(int(req["request_id"]))
            if h is None:
                return {"err": f"unknown request_id {req['request_id']}"}
            if h.tenant != (tenant_id or "default"):
                return {"err": "request belongs to another tenant"}
            if method == "cancel":
                return {"cancelled": r.cancel(h.request_id), "done": h.done}
            if method == "stream":
                cur = clamp_cursor(req.get("from"), len(h.tokens))
                return {
                    "request_id": h.request_id, "stream": True,
                    "from": cur, "_stream": (h, cur),
                }
            if not h.done:
                toks = list(h.tokens)
                cur = clamp_cursor(req.get("from"), len(toks))
                return {"done": False, "tokens_so_far": len(toks),
                        "tokens": toks[cur:], "from": cur}
            return self._completion(h)
        return {"err": f"unknown method {method!r}"}

    @staticmethod
    def _completion(h: RouterHandle) -> dict:
        return {
            "done": True,
            "tokens": list(h.tokens),
            "finish_reason": h.finish_reason,
            "cancelled": h.status == RouterHandle.CANCELLED,
        }

    # -- push-stream plumbing (shared with server._Handler._push_frames) ----
    def stream_wait(self, seq: int, timeout: float = 0.25) -> int:
        return self.router.stream_wait(seq, timeout)

    @staticmethod
    def _stream_final(h: RouterHandle) -> dict:
        return {
            "done": True,
            "finish_reason": h.finish_reason,
            "cancelled": h.status == RouterHandle.CANCELLED,
        }

    def note_frames(self, n: int, nbytes: int = 0, ntokens: int = 0,
                    coalesced: int = 0) -> None:
        with self._stream_lock:
            self.stream_frames += n
            self.stream_bytes += nbytes
            self.stream_tokens += ntokens
            self.stream_coalesced += coalesced
        stats.FT_EVENTS.incr("router_stream_frames", n)

    def note_stream(self, delta: int) -> None:
        with self._stream_lock:
            self.stream_active += delta

    def start(self) -> "RouterServer":
        self.router.start()
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._killed:
            return
        if self._thread is not None:
            self._srv.shutdown()
        self._srv.server_close()
        self.router.stop()

    def kill(self) -> None:
        """Fault injection (chaos drills, HA tests): die abruptly — stop
        accepting, drop the port, answer nothing. No drain, no goodbye to
        replicas or clients; the standby's probe loop and the replicas'
        heartbeat rotation are what must notice. Mirrors ServingServer.kill."""
        self._killed = True
        self.router._stop.set()

        def _die():
            try:
                self._srv.shutdown()
                self._srv.server_close()
            except OSError:
                pass

        threading.Thread(target=_die, name="router-kill", daemon=True).start()


class RouterStandby:
    """Warm standby for the serving router (ISSUE 18), on the shared
    election primitive (`runtime/election.py`). Watches the primary's TCP
    port; after N strikes plus one patient confirmation probe it binds its
    OWN port and becomes the fleet's router — *stateless-reconciling*
    takeover, no journal, no replicated log:

      - replicas carry both endpoints; their heartbeat rotation finds the
        standby, the unknown-id `reregister` hint heals leases, and
        `register_replica`'s takeover sweep rebuilds the in-flight/dedup
        books from each replica's `outstanding` reply (prompt, seed,
        temperature, tokens so far, server-held results);
      - clients carry both endpoints too; their retry/reattach path
        presents the (tenant, client_req_id) key, which the rebuilt key
        map resolves even though request ids restarted;
      - the election token becomes this incarnation's `Router.instance`,
        fencing replica agents against a healed old primary.

    The standby binds at TAKEOVER, not at construction: two live routers
    must never answer the same fleet, and an un-elected standby holding a
    bound port would look alive to the other standby's probes."""

    def __init__(self, primary: EndpointsLike, host: str = "127.0.0.1",
                 port: int = 0, poll_s: float = 0.2,
                 confirm_failures: int = 2,
                 max_wait_s: Optional[float] = None,
                 stop_evt: Optional[threading.Event] = None,
                 lease_s: float = 5.0, tenant_lease_s: float = 30.0,
                 **router_kw):
        self.primary = primary
        self.host, self.port = host, int(port)
        self.poll_s = float(poll_s)
        self.confirm_failures = int(confirm_failures)
        self.max_wait_s = max_wait_s
        self.stop_evt = stop_evt
        self.lease_s = float(lease_s)
        self.tenant_lease_s = float(tenant_lease_s)
        self.router_kw = router_kw

    def run(self) -> Optional["RouterServer"]:
        """Block watching the primary; on confirmed death return a STARTED
        RouterServer whose `Router.instance` is the election token. None
        when stopped or timed out with the primary still alive."""
        token = watch_primary(
            self.primary, plane="router", poll_s=self.poll_s,
            confirm_failures=self.confirm_failures,
            max_wait_s=self.max_wait_s, stop_evt=self.stop_evt,
        )
        if token is None:
            return None
        srv = RouterServer(
            host=self.host, port=self.port, lease_s=self.lease_s,
            tenant_lease_s=self.tenant_lease_s, **self.router_kw,
        )
        srv.router.instance = token
        log.warning(
            "router standby (incarnation %s) taking over on %s:%d",
            token, *srv.address,
        )
        return srv.start()


def _main(argv: Optional[List[str]] = None) -> int:
    """`python -m paddle_tpu.serving.router serve|standby|drain|status` —
    the router as its own process, plus the ops levers (`drain` is the hook
    ROADMAP item 2's autoscaling controller pulls) and the warm-standby
    role (ISSUE 18)."""
    import argparse
    import json
    import signal as _signal

    ap = argparse.ArgumentParser(prog="paddle_tpu.serving.router")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sv = sub.add_parser("serve", help="run a router in front of N replicas")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=0)
    sv.add_argument("--lease_s", type=float, default=5.0,
                    help="replica lease: silence past this is eviction + "
                         "in-flight failover")
    sv.add_argument("--hedge_ttft_s", type=float, default=0.0,
                    help="fleet default TTFT hedge (0 = off): a token-less "
                         "request past this is duplicated onto a second "
                         "replica, first token wins")
    sv.add_argument("--drain_deadline_s", type=float, default=30.0)
    # autoscaler co-process (ISSUE 17): run the goodput-driven controller
    # beside this router — it watches the stats this process already
    # aggregates from heartbeats and pulls the spawn/drain (and, with
    # --autoscale_master, training resize) levers. The router never
    # depends on it: kill the controller and the fleet is simply static.
    sv.add_argument("--autoscale", action="store_true",
                    help="run an autoscaler controller for this router's "
                         "fleet (see paddle_tpu/runtime/autoscaler.py)")
    sv.add_argument("--autoscale_master", default=None,
                    help="master host:port — arms the training resize "
                         "lever so training borrows idle serving chips")
    sv.add_argument("--autoscale_tick_s", type=float, default=1.0)
    sv.add_argument("--autoscale_chips", type=int, default=8,
                    help="total chip budget arbitrated across both fleets")
    sv.add_argument("--autoscale_min_replicas", type=int, default=1)
    sv.add_argument("--autoscale_max_replicas", type=int, default=8)
    sv.add_argument("--autoscale_spawn_arg", action="append", default=None,
                    help="repeatable: extra argv for spawned replicas "
                         "(default: --demo)")
    sb = sub.add_parser(
        "standby",
        help="watch a primary router; take over its fleet when it dies "
             "(replicas and clients must carry this standby's endpoint in "
             "their --router_endpoints list)",
    )
    sb.add_argument("--primary", required=True, help="primary host:port")
    sb.add_argument("--host", default="127.0.0.1")
    sb.add_argument("--port", type=int, default=0)
    sb.add_argument("--lease_s", type=float, default=5.0)
    sb.add_argument("--hedge_ttft_s", type=float, default=0.0)
    sb.add_argument("--drain_deadline_s", type=float, default=30.0)
    sb.add_argument("--poll_s", type=float, default=0.2)
    sb.add_argument("--max_wait_s", type=float, default=None,
                    help="give up after this long with the primary healthy")
    for name in ("drain", "status"):
        p = sub.add_parser(name)
        p.add_argument("--endpoint", required=True, help="router host:port")
        if name == "drain":
            p.add_argument("--replica", required=True,
                           help="replica id (see `status`)")
            p.add_argument("--deadline_s", type=float, default=None)
    args = ap.parse_args(argv)

    if args.cmd == "serve":
        srv = RouterServer(
            host=args.host, port=args.port, lease_s=args.lease_s,
            hedge_ttft_s=args.hedge_ttft_s or None,
            drain_deadline_s=args.drain_deadline_s,
        ).start()
        ctl = None
        if args.autoscale:
            from paddle_tpu.runtime.autoscaler import (
                AutoscalerController, ReplicaSpawner, ScaleConfig,
            )

            ctl = AutoscalerController(
                router_endpoints=srv.address,
                master_endpoints=args.autoscale_master,
                config=ScaleConfig(
                    chips_total=args.autoscale_chips,
                    min_replicas=args.autoscale_min_replicas,
                    max_replicas=args.autoscale_max_replicas,
                ),
                spawner=ReplicaSpawner(
                    srv.address,
                    extra_args=(args.autoscale_spawn_arg
                                if args.autoscale_spawn_arg is not None
                                else ["--demo"]),
                ),
                tick_s=args.autoscale_tick_s,
            ).start()

        def _shutdown(*_):
            if ctl is not None:
                ctl.stop()
            srv.stop()

        _signal.signal(_signal.SIGTERM, _shutdown)
        _signal.signal(_signal.SIGINT, _shutdown)
        print(json.dumps({"role": "router", "address": list(srv.address),
                          "autoscale": bool(args.autoscale)}),
              flush=True)
        while srv._thread is not None and srv._thread.is_alive():
            time.sleep(0.05)
        if ctl is not None:
            ctl.stop()
            if ctl.spawner is not None:
                ctl.spawner.stop_all()
        return 0
    if args.cmd == "standby":
        stop_evt = threading.Event()
        _signal.signal(_signal.SIGTERM, lambda *_: stop_evt.set())
        _signal.signal(_signal.SIGINT, lambda *_: stop_evt.set())
        srv = RouterStandby(
            args.primary, host=args.host, port=args.port,
            poll_s=args.poll_s, max_wait_s=args.max_wait_s,
            stop_evt=stop_evt, lease_s=args.lease_s,
            hedge_ttft_s=args.hedge_ttft_s or None,
            drain_deadline_s=args.drain_deadline_s,
        ).run()
        if srv is None:
            print(json.dumps({"role": "router_standby", "takeover": False}),
                  flush=True)
            return 3
        print(json.dumps({"role": "router_standby", "takeover": True,
                          "address": list(srv.address)}), flush=True)
        while srv._thread is not None and srv._thread.is_alive():
            time.sleep(0.05)
        srv.stop()
        return 0
    client = MasterClient(args.endpoint)
    try:
        if args.cmd == "drain":
            out = client.call("drain", replica_id=args.replica,
                              deadline_s=args.deadline_s)
        else:
            out = client.call("stats")
        print(json.dumps(out))
        return 0 if "err" not in out else 1
    finally:
        client.close()


if __name__ == "__main__":
    import sys

    sys.exit(_main())
