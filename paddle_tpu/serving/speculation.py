"""Prompt-lookup speculative drafting (ISSUE 16): no second model.

The drafter is an n-gram index over ONE request's committed tokens (prompt
+ everything generated so far). To draft, it looks up the sequence's last
`n` tokens; if that n-gram occurred earlier, the K tokens that FOLLOWED the
earlier occurrence become the draft — the "prompt lookup" trick: templated
and repetitive text (code, structured prompts, self-repeating generations)
re-walks its own n-grams constantly, so the continuation after the last
match is a strong guess at the continuation now.

Correctness never depends on draft quality: the verify chunk samples the
TARGET model's token at every draft position through the request's own
(seed, emitted-token-index) key, and the host only accepts drafts that
exactly match those samples — a bad draft costs a wasted lane, never a
wrong token. That is what lets the drafter be this simple.

Determinism is load-bearing (the replay contract): a drafter's output is a
pure function of the committed token sequence — no clocks, no RNG, no
engine-step state — so a crash replay or router failover that regrows the
sequence from the prompt reproduces the exact same draft at every round.

Host-side, pure Python, O(1) dict ops per committed token; one instance per
active request (the serving session keys them by slot + request id and
drops them at retirement)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


def next_draft_k(k_eff: int, k_max: int, drafted: int, accepted: int) -> int:
    """Adaptive draft length (ROADMAP item 1a): the effective K for a
    request's NEXT verify round, given what just happened. A PURE rule —
    no clocks, no RNG, no engine state — so crash replay / router failover
    regrow the same K sequence from the same acceptance history and every
    round stays bitwise.

    Additive-increase / fall-to-observed:
      * full acceptance (every drafted token matched) -> grow by 1 toward
        `k_max` — the stream is in a predictable stretch, draft deeper;
      * partial/zero acceptance -> fall to `accepted + 1` — the draft
        diverged after `accepted` tokens, so drafting further than one past
        the observed match depth just burns verify lanes.

    The [1, K_max+1] verify program zero-pads short drafts, so the shape —
    and therefore the executable — never changes with K (signature stays 1);
    only HOW MANY lanes carry real draft tokens does."""
    k_eff = max(1, min(int(k_eff), int(k_max)))
    if drafted <= 0:
        return k_eff  # no draft existed: no evidence, keep the current K
    if accepted >= drafted:
        return min(int(k_max), k_eff + 1)
    return max(1, int(accepted) + 1)


class PromptLookupDrafter:
    """Incremental n-gram index + drafts for one request.

    `feed()` consumes newly committed tokens (prompt first, then each
    emitted token, in order); `draft(k)` proposes up to `k` continuation
    tokens after the most recent earlier occurrence of the current
    `ngram`-token suffix, or [] when the suffix never occurred before
    (the caller then falls back to plain decode for that slot)."""

    def __init__(self, ngram: int = 2):
        if ngram < 1:
            raise ValueError("ngram must be >= 1")
        self.ngram = int(ngram)
        # n-gram -> (latest, previous) continuation-start indices (the
        # position right AFTER the gram). Two generations are kept because
        # the LATEST occurrence of the sequence's own suffix is the suffix
        # itself — drafting needs the one before it (think a period-1
        # repetition: the previous occurrence is what predicts the next
        # token); most-recent-wins keeps drafts tracking the live text
        self._index: Dict[Tuple[int, ...], Tuple[int, Optional[int]]] = {}
        self._ctx: List[int] = []

    def __len__(self) -> int:
        return len(self._ctx)

    def feed(self, tokens: Sequence[int]) -> None:
        """Append committed tokens and index every complete n-gram they
        close (latest occurrence, keeping the one it displaces)."""
        n = self.ngram
        ctx = self._ctx
        for t in tokens:
            ctx.append(int(t))
            if len(ctx) >= n:
                g = tuple(ctx[-n:])
                old = self._index.get(g)
                self._index[g] = (len(ctx), old[0] if old else None)

    def sync(self, prompt: Sequence[int], generated: Sequence[int]) -> None:
        """Catch the index up to `prompt + generated` (the request's
        committed sequence): feeds only the unseen tail, so callers can
        re-sync every round without re-walking the whole history."""
        total = len(prompt) + len(generated)
        have = len(self._ctx)
        if have >= total:
            return
        if have < len(prompt):
            self.feed(prompt[have:])
            have = len(self._ctx)
        self.feed(generated[have - len(prompt):])

    def draft(self, k: int) -> List[int]:
        """Up to `k` proposed continuation tokens; [] when the current
        suffix never occurred before. Tokens are drafted one at a time
        against the committed context — each drafted token slides the
        lookup window, so a cyclic tail (the common case for repetitive
        text) drafts the whole cycle forward, not just to the end of the
        match. Stops early at the first window with no earlier occurrence;
        the verify chunk's acceptance test makes any draft safe."""
        ctx, n = self._ctx, self.ngram
        total = len(ctx)
        if k <= 0 or total < n:
            return []
        out: List[int] = []
        window = list(ctx[-n:])  # committed suffix, slid over drafted tokens
        p: Optional[int] = None  # next source position in the committed ctx
        while len(out) < k:
            if p is None or p >= total:
                e = self._index.get(tuple(window))
                if e is None:
                    break
                latest, prev = e
                # a continuation start at the very end has nothing after
                # it (it IS the current suffix / the just-slid window):
                # fall back to the occurrence it displaced
                p = latest if latest < total else prev
                if p is None:
                    break
            out.append(ctx[p])
            window = window[1:] + [ctx[p]]
            p += 1
        return out
