"""Continuous-batching inference serving runtime (ISSUE 6 / ROADMAP item 1).

The long-lived serving layer over the generation stack: a `ServingSession`
owns device state across requests (params loaded once, one compiled decode
program shared by every mixed-length request via a paged KV cache), a
scheduler forms dynamic batches at decode-step boundaries, admission control
and per-tenant quotas guard the front door, and a TCP front-end reuses the
master's line-JSON request-routing plane.

    from paddle_tpu.serving import make_demo_session
    s = make_demo_session(max_slots=8)
    h = s.submit([1, 5, 9], max_new_tokens=16)
    s.run_until_idle()
    print(h.result())

CLI: `python -m paddle_tpu serve` (README "Serving")."""

from paddle_tpu.serving.kv_cache import PagedKVCache
from paddle_tpu.serving.model import LMConfig, ServableLM
from paddle_tpu.serving.quota import QuotaExceeded, TenantQuotas
from paddle_tpu.serving.scheduler import (
    FinishReason,
    RequestHandle,
    Scheduler,
)
from paddle_tpu.serving.session import (
    SERVING_EVENTS,
    ServingSession,
    make_demo_session,
)
from paddle_tpu.serving.fleet import FleetView, Replica, ReplicaAgent
from paddle_tpu.serving.router import Router, RouterHandle, RouterServer

__all__ = [
    "PagedKVCache",
    "LMConfig",
    "ServableLM",
    "QuotaExceeded",
    "TenantQuotas",
    "FinishReason",
    "RequestHandle",
    "Scheduler",
    "SERVING_EVENTS",
    "ServingSession",
    "make_demo_session",
    "FleetView",
    "Replica",
    "ReplicaAgent",
    "Router",
    "RouterHandle",
    "RouterServer",
]
