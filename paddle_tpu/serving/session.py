"""ServingSession: a long-lived serving engine that owns device state.

The anti-pattern this replaces: `run_generation` rebuilt the Network,
re-initialized params and reloaded the checkpoint on EVERY call, and
`InferenceMachine.forward` compiled per batch shape and blocked the host per
request. Here the session loads parameters ONCE, compiles THREE kinds of
executable ONCE, and then serves any number of requests of any mixed lengths
against them:

  * decode  — the single fixed-[max_slots] continuous-batching step
              (pages donated in/out; the only executable in the hot loop;
              on TPU its attention runs the Pallas ragged paged-attention
              kernel, the jnp gather path staying the CPU oracle)
  * prefill — one per length bucket (a handful: `prefill_buckets`)
  * commit  — one per bucket + one chunk shape (scatter prompt KV into pages)
  * chunk   — ONE [1, prefill_chunk] program serving every long prompt:
              chunked prefill (ISSUE 11) commits a long prompt C tokens per
              engine step interleaved with decode, so a long prompt joining
              mid-stream never stalls the running streams' inter-token
              latency the way a whole-prompt prefill does

Sampling (ISSUE 11) is on-device and rides the SAME decode executable:
per-request (seed, temperature, top_k) are [max_slots] data lanes, the key
is fold_in(PRNGKey(seed), token_index), so greedy and sampled requests mix
freely with zero recompiles and the PR 10 crash replay stays bitwise even
at temperature > 0.

Shape discipline is *asserted*, not hoped for: every decode step's input
signature is recorded into a serving-local stats.RecompileStats (the PR-1
telemetry) and `decode_shape_signatures()` must stay at 1 over any request
mix — the zero-recompile gate in tests/test_serving.py and
benchmarks/serving_bench.py.

Hot-loop discipline matches the trainer's (README "Async execution"): the
decode loop performs exactly ONE device->host fetch per step — the sampled
token ids, which the autoregressive loop inherently needs to detect EOS and
stream results — and, since ISSUE 10, exactly ONE wall-clock read per step
(the step-boundary timestamp that batches every deadline/cancellation
check). tests/test_lint_hotloop.py lints this loop body the same way it
lints the train loop.

Resilience (ISSUE 10): in server mode the engine thread runs under a
SUPERVISOR. When the engine faults (seeded sites `decode_raise` /
`page_exhaust`) or stalls past `engine_stall_timeout_s` without a step
(seeded site `engine_stall`), the supervisor supersedes it, re-initializes
the page pool (a failed donated step consumed the old buffers anyway), and
replays every in-flight request from its prompt — greedy decode is
deterministic, so completed requests are unaffected and replayed ones are
result-transparent; requests past their deadline fail with the named reason
`deadline`. Past `engine_restart_max` restarts the engine gives up and every
outstanding request fails `engine_error` (the pre-supervisor behavior)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional, Sequence

import numpy as np

from paddle_tpu.core import faults as _faults
from paddle_tpu.core import stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace
from paddle_tpu.serving.kv_cache import PagedKVCache
from paddle_tpu.serving.model import LMConfig, ServableLM
from paddle_tpu.serving.quota import TenantQuotas
from paddle_tpu.serving.scheduler import RequestHandle, Scheduler

# serving-side counters (sibling of stats.FT_EVENTS/DATA_EVENTS): admissions,
# retirements, quota rejections, decode steps — unconditional telemetry;
# the "serving" name registers the group with the obs metrics exporter
SERVING_EVENTS = stats.EventCounter("serving")

# time-to-first-token distribution (PADDLE_TPU_TRACE not required: histograms
# are unconditional telemetry like the event counters above)
TTFT_HISTOGRAM = obs_metrics.REGISTRY.histogram(
    "paddle_tpu_serving_ttft_seconds",
    "submit → first sampled token, per request",
)


def _bucket_for(buckets: Sequence[int], n: int) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt of {n} tokens exceeds largest bucket {buckets[-1]}")


class ServingSession:
    def __init__(
        self,
        model: ServableLM,
        params: Dict,
        *,
        max_slots: int = 8,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        prefill_buckets: Sequence[int] = (16, 32, 64),
        max_new_limit: int = 64,
        max_queue: int = 256,
        quotas: Optional[TenantQuotas] = None,
        default_deadline_s: Optional[float] = None,
        default_ttft_deadline_s: Optional[float] = None,
        engine_restart_max: int = 3,
        engine_stall_timeout_s: float = 10.0,
        prefill_chunk: Optional[int] = None,
        default_temperature: float = 0.0,
        default_top_k: int = 0,
        speculate_k: int = 0,
        prefix_cache: bool = False,
        prefix_cache_pages: Optional[int] = None,
    ):
        import jax

        self.model = model
        self.cfg = model.cfg
        # TP (ISSUE 12): params resolve through the model's logical-axes
        # table + sharding rules — heads/mlp/vocab split over the mesh
        # 'model' axis, per-chip param bytes ~1/TP. Identity on one chip.
        self.params = model.shard_params(params)
        self.buckets = tuple(sorted(set(int(b) for b in prefill_buckets)))
        self.max_new_limit = int(max_new_limit)
        max_ctx = self.buckets[-1] + self.max_new_limit
        if max_ctx > self.cfg.max_len:
            raise ValueError(
                f"largest bucket + max_new_limit = {max_ctx} exceeds the "
                f"model's max_len {self.cfg.max_len}"
            )
        # chunked prefill (ISSUE 11) lifts the bucket cap on prompt length:
        # any prompt up to max_len - 1 is admissible (committed one C-token
        # chunk per engine step), so the page pool must cover max_len, not
        # just the largest bucket
        self.prefill_chunk = None if not prefill_chunk else int(prefill_chunk)
        if self.prefill_chunk is not None:
            max_ctx = self.cfg.max_len
        # session-wide sampling defaults; per-request values win (ISSUE 11)
        self.default_temperature = float(default_temperature)
        self.default_top_k = int(default_top_k)
        # speculative decoding (ISSUE 16): K drafted tokens verified per
        # round through ONE [1, K+1] prefill-chunk-shaped executable.
        # 0 (the default) compiles nothing extra and takes exactly today's
        # code path — `--speculate_k 0` bitwise-recovers PR-15 behavior.
        self.speculate_k = max(0, int(speculate_k))
        # shared-prefix cache (ISSUE 19): cached prompt pages alias into new
        # slots read-only and the chunked prefill starts at the first
        # un-cached token — which is why the cache REQUIRES chunked prefill
        # (the whole-prompt executables have no notion of a partial start).
        # Purely host-side block-table state: zero new executables, decode
        # signature stays 1, and it rides TP's replicated-table dispatch.
        self.prefix_cache = bool(prefix_cache)
        if self.prefix_cache and self.prefill_chunk is None:
            raise ValueError(
                "prefix_cache requires prefill_chunk: cache hits resume "
                "prefill mid-prompt, which only the chunked path can do"
            )
        # per-seq page budget covers the verify chunk's K-token overshoot
        pages_per_seq = -(-(max_ctx + self.speculate_k) // page_size)
        if num_pages is None:
            # worst case every slot at full context, plus the dump page
            num_pages = max_slots * pages_per_seq + 1
        self.cache = PagedKVCache(
            n_layers=self.cfg.n_layers,
            kv_dim=self.cfg.d_model,
            num_pages=num_pages,
            page_size=page_size,
            max_slots=max_slots,
            max_pages_per_seq=pages_per_seq,
            # kv_heads over the mesh 'model' axis under TP (~1/TP pool bytes
            # per chip); the cache re-applies it on crash-recovery re-init
            pool_sharding=model.pool_sharding(),
            prefix_cache=self.prefix_cache,
            prefix_cache_pages=prefix_cache_pages,
        )
        self.scheduler = Scheduler(
            self.cache, max_queue=max_queue, quotas=quotas,
            prefill_chunk=self.prefill_chunk, largest_bucket=self.buckets[-1],
            speculate_k=self.speculate_k,
        )
        self.k_pages, self.v_pages = self.cache.make_pools()

        # warmup detection (ISSUE 17): each wrapped body runs ONLY while jax
        # traces it — exactly once per new input signature per executable,
        # i.e. precisely when a compile happens (prefill buckets included,
        # which the per-signature RecompileStats below never see) — so the
        # counter is a "this step compiled something" signal at zero
        # steady-state cost, on any backend, with or without the persistent
        # compile cache
        self._jit_traces = 0

        def _traced(fn):
            def wrapped(*a, **kw):
                self._jit_traces += 1
                return fn(*a, **kw)
            return wrapped

        # the executables; jit's shape cache turns the bucket list into
        # "a few padded lengths" -> a few compiles, decode into exactly one,
        # and the chunk program ([1, C] fixed shape) into exactly one more
        self._decode = jax.jit(_traced(model.decode_step),
                               donate_argnums=(1, 2))
        self._prefill = jax.jit(_traced(model.prefill))
        self._commit = jax.jit(_traced(model.commit_prefill),
                               donate_argnums=(0, 1))
        self._prefill_chunk = jax.jit(_traced(model.prefill_chunk),
                                      donate_argnums=(1, 2))
        # the verify executable only exists when speculation is on: K=0
        # compiles nothing and the engine step never calls _speculate's body
        self._verify = (
            jax.jit(_traced(model.verify_chunk), donate_argnums=(1, 2))
            if self.speculate_k else None
        )
        # compile-heavy steps observe second-scale "service times" that
        # poison the load estimator's EWMA (PR 10); the step loop resets it
        # automatically at the FIRST step that ran clean after any compile,
        # so benches and drills no longer reset by hand
        self._load_est_dirty = False

        self.recompiles = stats.RecompileStats(warn_threshold=2)
        # the verify chunk's own one-signature gate ([1, K+1] fixed shape:
        # drafts, starts and sampling identity are data, never shape)
        self.verify_recompiles = stats.RecompileStats(warn_threshold=2)
        self.decode_steps = 0
        self.tokens_generated = 0
        self.prefill_chunks_committed = 0
        self._chunk_rr_slot = -1  # round-robin cursor over prefilling slots
        # speculative-decode telemetry (acceptance rate = accepted / drafted)
        self.spec_rounds = 0
        self.spec_tokens_drafted = 0
        self.spec_tokens_accepted = 0
        self.spec_pages_trimmed = 0
        # adaptive-K telemetry: sum of the effective draft length actually
        # used per round — spec_effective_k = sum / rounds
        self.spec_k_eff_sum = 0
        # per-slot prompt-lookup drafters, keyed (slot -> (request_id,
        # drafter)); lazily built, dropped at retirement / engine recovery
        self._drafters: Dict[int, tuple] = {}
        # push-streaming seam (ISSUE 16): the engine bumps a sequence number
        # once per step and wakes pusher threads; ALL socket writes happen on
        # those threads (server.py), so frame emission never blocks a step
        self._stream_cv = threading.Condition()
        self._stream_seq = 0
        # session-level request deadline defaults; per-tenant quota defaults
        # (quota.py deadlines_for) take precedence, explicit per-request
        # values beat both
        self.default_deadline_s = default_deadline_s
        self.default_ttft_deadline_s = default_ttft_deadline_s
        # supervisor state (server mode): restart budget, stall watchdog,
        # and the engine GENERATION — a superseded (stalled) engine thread
        # re-checks the generation when it wakes and exits without touching
        # session state, so recovery never races a zombie
        self.engine_restart_max = int(engine_restart_max)
        self.engine_stall_timeout_s = float(engine_stall_timeout_s)
        self.engine_restarts = 0
        self.engine_error: Optional[BaseException] = None
        self._engine_gen = 0
        # serializes the supersede handshake: the engine flips
        # _engine_in_step only after re-checking its generation UNDER this
        # lock, and the stall recovery bumps the generation under the same
        # lock only while the engine is BETWEEN steps — so a wedged thread
        # that wakes at the wrong moment can never run a step concurrently
        # with the supervisor's pool re-init (check-then-act closed)
        self._gen_lock = threading.Lock()
        self._engine_fault: Optional[BaseException] = None
        self._engine_in_step = False
        self._last_progress = time.monotonic()
        self._stop = threading.Event()
        self._work = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    # -- intake -------------------------------------------------------------
    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        ttft_deadline_s: Optional[float] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        seed: Optional[int] = None,
    ) -> RequestHandle:
        """Queue one generation request; raises QuotaExceeded at the front
        door when admission control says no (including a load-aware shed
        when the estimated queue wait exceeds the request's deadline
        budget). Deadlines resolve explicit arg → tenant quota default →
        session default; None all the way down means none. Sampling knobs
        resolve explicit arg → session default (temperature 0 = greedy,
        top_k 0 = off); `seed` defaults to a request-stable derivation so
        crash replay is bitwise (ISSUE 11). Thread-safe."""
        if self.engine_error is not None:
            raise RuntimeError(
                "serving engine died; no new requests accepted"
            ) from self.engine_error
        prompt = list(prompt)
        if not prompt:
            raise ValueError("empty prompt")
        max_new = min(
            self.max_new_limit,
            self.max_new_limit if max_new_tokens is None else int(max_new_tokens),
        )
        if max_new <= 0:
            raise ValueError("max_new_tokens must be positive")
        # the silent-overflow guard (ISSUE 11 satellite): a position past
        # max_len would index params["pos"] out of range inside jit, which
        # XLA CLAMPS silently — wrong tokens, no error. Reject here, named.
        if len(prompt) + max_new > self.cfg.max_len:
            raise ValueError(
                f"max_len exceeded: prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new}) = {len(prompt) + max_new} tokens > the model's "
                f"max_len {self.cfg.max_len}; clamped position embeddings "
                f"would silently corrupt the output"
            )
        if not self._chunked_prompt(prompt):
            # whole-prompt (bucketed) prefill path: prompt must fit a bucket
            _bucket_for(self.buckets, len(prompt))
        need = self.cache.pages_needed(
            len(prompt) + max_new + self.speculate_k
        )
        if need > min(self.cache.max_pages_per_seq, self.cache.num_pages - 1):
            # an undersized pool must reject at the front door, not leave the
            # queue head unadmittable forever
            raise ValueError(
                f"request needs {need} KV pages; pool allows "
                f"{min(self.cache.max_pages_per_seq, self.cache.num_pages - 1)}"
            )
        if deadline_s is None or ttft_deadline_s is None:
            qd = qtd = None
            if self.scheduler.quotas is not None:
                qd, qtd = self.scheduler.quotas.deadlines_for(tenant)
            if deadline_s is None:
                deadline_s = qd if qd is not None else self.default_deadline_s
            if ttft_deadline_s is None:
                ttft_deadline_s = (
                    qtd if qtd is not None else self.default_ttft_deadline_s
                )
        # request trace context: the submitter's current span (the RPC
        # handler's server span, or whatever the caller has open) — the
        # engine thread's queue-wait/prefill/ttft spans stitch under it.
        # Captured BEFORE submit: the engine can admit the request the
        # moment it is queued, so a post-submit assignment would race
        handle = self.scheduler.submit(
            prompt, max_new, tenant, trace_ctx=trace.wire_context(),
            deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
            seed=seed,
            temperature=(
                self.default_temperature if temperature is None
                else float(temperature)
            ),
            top_k=self.default_top_k if top_k is None else int(top_k),
        )
        # the full prompt rides the handle (ISSUE 18): a router takeover
        # sweep reads it back via the `outstanding` RPC so a request whose
        # OWNING replica also dies can be re-submitted to a survivor
        # token-identically — prompt + pinned seed are the whole sampling
        # identity, and after a router death the replica is the only
        # surviving holder of both
        handle.prompt_tokens = prompt
        SERVING_EVENTS.incr("serving_submitted")
        with self._work:
            self._work.notify()
        return handle

    # -- engine steps -------------------------------------------------------
    def _chunked_prompt(self, prompt) -> bool:
        """True when this prompt prefills chunk-by-chunk: longer than the
        per-step chunk budget, OR longer than every bucket (with chunking
        on, NO prompt up to max_len is unservable — a prompt in the gap
        between the largest bucket and a larger chunk size must not be
        rejected where a longer one would be admitted)."""
        return self.prefill_chunk is not None and (
            len(prompt) > self.prefill_chunk or len(prompt) > self.buckets[-1]
        )

    def _sampling_row(self, h) -> tuple:
        """(seeds, temps, top_ks) [1]-shaped device-data for one request's
        prefill — its sampled first token draws through
        fold_in(PRNGKey(seed), 0)."""
        return (
            np.array([h.seed], np.uint32),
            np.array([h.temperature], np.float32),
            np.array([h.top_k], np.int32),
        )

    def _observe_ttft(self, h, ctx) -> None:
        """Time-to-first-token bookkeeping, shared by the whole-prompt and
        chunked prefill paths. Latched once per REQUEST: a crash-replayed
        admission must not observe a second sample (or double-count a miss)
        for the same id."""
        if not h.ttft_observed:
            h.ttft_observed = True
            ttft_s = (h.t_first_token or h.t_submit) - h.t_submit
            TTFT_HISTOGRAM.observe(ttft_s)
            if (h.t_ttft_deadline is not None
                    and h.t_first_token is not None
                    and h.t_first_token > h.t_ttft_deadline):
                # TTFT deadline missed: counted (the client-hedging
                # signal) but NOT fatal — the request has its first token
                # now and only the total deadline cancels work
                obs_metrics.observe_deadline_miss("ttft")
                SERVING_EVENTS.incr("serving_ttft_deadline_missed")
        trace.span_from_monotonic(
            "serving.ttft", h.t_submit,
            trace_id=ctx and ctx.get("t"), parent_id=ctx and ctx.get("s"),
            attrs={"request_id": h.request_id},
        )

    def _admit(self, now: Optional[float] = None) -> None:
        """Run prefill for every request joining at this step boundary.
        Prompts longer than `prefill_chunk` (when set) only MARK the slot
        as prefilling here — their K/V commits one chunk per engine step in
        _prefill_chunks, interleaved with decode, so a long prompt joining
        never stalls the already-decoding slots for a whole-prompt forward."""
        import jax.numpy as jnp

        if _faults.get().active and self.scheduler.queue_depth():
            # chaos site: the page pool fails at admission (exhaustion /
            # corruption analog) — the supervisor must re-init the pool and
            # replay; gated on queued work so step=N counts admission
            # ATTEMPTS, not idle engine spins
            _faults.get().maybe_raise("page_exhaust")
        for slot, act in self.scheduler.pop_admissions(now):
            h = act.handle
            ctx = h.trace_ctx
            # queue-wait: submit → this admission boundary, under the
            # request's own trace id (measured on the scheduler's monotonic
            # clock, re-anchored to wall-clock for the export)
            trace.span_from_monotonic(
                "serving.queue_wait", h.t_submit,
                trace_id=ctx and ctx.get("t"), parent_id=ctx and ctx.get("s"),
                attrs={"request_id": h.request_id},
            )
            if act.prefix_hit or self._chunked_prompt(act.prompt):
                # chunked path: _prefill_chunks advances this slot one chunk
                # per engine step from here on. A prefix-cache hit ALWAYS
                # routes here, starting at the first un-cached token — the
                # aliased pages' KV is already committed, so the hit tokens
                # are prefill work this request simply never does (the page-
                # alignment cap guarantees >= 1 suffix token remains, so the
                # final chunk still emits the sampled first token)
                act.prefill_pos = act.prefix_hit
                continue
            bucket = _bucket_for(self.buckets, len(act.prompt))
            seeds, temps, top_ks = self._sampling_row(h)
            with trace.activate(ctx):
                with trace.span(
                    "serving.prefill", request_id=h.request_id, bucket=bucket
                ):
                    toks = np.zeros((1, bucket), np.int32)
                    toks[0, : len(act.prompt)] = act.prompt
                    lengths = np.array([len(act.prompt)], np.int32)
                    first_tok, kc, vc = self._prefill(
                        self.params, toks, lengths, seeds, temps, top_ks
                    )
                    rows = self.cache.slot_row(slot)
                    # tp-ok: per-ADMISSION placement of one request's commit
                    # operands (never per decode step); the block table the
                    # decode loop uses rides the jit dispatch untouched
                    self.k_pages, self.v_pages = self._commit(
                        self.k_pages, self.v_pages, kc, vc,
                        jnp.asarray(lengths), jnp.asarray(rows),
                        jnp.zeros((1,), jnp.int32),
                    )
                    # one tiny host fetch per ADMISSION (not per decode step):
                    # the prompt's first token — sampled on device
                    act.append(int(first_tok[0]))
            # the whole prompt is committed: register its full pages into
            # the tenant's prefix chain (no-op with the cache off)
            self.cache.commit_prefix(slot, h.tenant, act.prompt,
                                     len(act.prompt))
            # time-to-first-token: prefill emits the first sampled token, so
            # TTFT completes here — span under the request trace + histogram
            self._observe_ttft(h, ctx)
            SERVING_EVENTS.incr("serving_prefills")
            reason = act.finished(self.cfg.eos_id)
            if reason is not None:
                self.scheduler.retire(slot, reason)

    def _prefill_chunks(self) -> None:
        """Advance ONE prefilling slot by exactly one [1, C] chunk — the
        chunked-prefill half of the engine step (ISSUE 11). The chunk size
        IS the per-step prefill budget: each engine step spends at most C
        prompt tokens on prefill no matter how many long prompts are in
        flight (round-robin across prefilling slots keeps them all moving),
        and _decode_once still runs for every fully-prefilled slot in the
        same engine step — so no decode step is ever skipped for a prefill
        and the decode streams' inter-token latency is bounded by decode +
        ONE chunk, not by a whole-prompt forward. The final chunk emits the
        request's first sampled token (one host fetch per REQUEST, there)."""
        prefilling = [
            (slot, act) for slot, act in self.scheduler.active_slots()
            if act.prefilling
        ]
        if not prefilling:
            return
        # round-robin: resume after the last slot serviced so co-resident
        # long prompts share the per-step budget fairly (deterministic —
        # and result-irrelevant: per-slot math never crosses slots)
        prefilling.sort(
            key=lambda sa: (sa[0] <= self._chunk_rr_slot, sa[0])
        )
        for slot, act in prefilling[:1]:
            self._chunk_rr_slot = slot
            h = act.handle
            c = self.prefill_chunk
            start = act.prefill_pos
            piece = act.prompt[start : start + c]
            toks = np.zeros((1, c), np.int32)
            toks[0, : len(piece)] = piece
            lengths = np.array([len(act.prompt)], np.int32)
            starts = np.array([start], np.int32)
            seeds, temps, top_ks = self._sampling_row(h)
            rows = self.cache.slot_row(slot)
            # span-ok: ring-buffer write only, constant name, int attrs — the
            # chunk loop is hot-path like the decode loop (lint-pinned)
            with trace.activate(h.trace_ctx):
                with trace.span(
                    "serving.prefill_chunk", request_id=h.request_id,
                    start=start,
                ):
                    # ONE dispatch per chunk: forward + commit fused, pages
                    # donated through (see model.prefill_chunk docstring)
                    self.k_pages, self.v_pages, tok = self._prefill_chunk(
                        self.params, self.k_pages, self.v_pages, toks,
                        starts, lengths, rows, seeds, temps, top_ks,
                    )
            act.prefill_pos = min(start + c, len(act.prompt))
            # incremental registration (ISSUE 19): every full prompt page
            # this chunk just committed enters the tenant's prefix chain NOW
            # — a concurrent same-prefix admission aliases it one step later
            # (only COMMITTED pages ever register, so an alias can never see
            # half-written KV). No-op with the cache off.
            self.cache.commit_prefix(slot, h.tenant, act.prompt,
                                     act.prefill_pos)
            self.prefill_chunks_committed += 1
            SERVING_EVENTS.incr("serving_prefill_chunks")
            if not act.prefilling:
                # sync-ok: one host fetch per REQUEST (not per chunk, not per
                # step) — the FINAL chunk's sampled first token, which the
                # autoregressive loop needs on host; intermediate chunks
                # never fetch (their `tok` stays device-resident and unused)
                act.append(int(tok[0]))
                self._observe_ttft(h, h.trace_ctx)
                SERVING_EVENTS.incr("serving_prefills")
                reason = act.finished(self.cfg.eos_id)
                if reason is not None:
                    self.scheduler.retire(slot, reason)

    def _drafter_for(self, slot: int, act):
        """This slot's (drafter, adaptive-K cell), rebuilt when the slot was
        recycled to a different request (stale entries are bounded by
        max_slots; retirement and engine recovery drop them eagerly). The
        K cell is derived state exactly like the drafter: a replay regrows
        the same acceptance history, hence the same K at every round —
        which keeps crash recovery bitwise with adaptive K on."""
        from paddle_tpu.serving.speculation import PromptLookupDrafter

        rid = act.handle.request_id
        ent = self._drafters.get(slot)
        if ent is None or ent[0] != rid:
            ent = (rid, PromptLookupDrafter(), [self.speculate_k])
            self._drafters[slot] = ent
        return ent[1], ent[2]

    def _speculate(self) -> set:
        """One prompt-lookup draft/verify round for EVERY eligible slot
        (ISSUE 16): the slot's drafter proposes up to K continuation tokens
        from the request's own committed n-grams, one [1, K+1] verify_chunk
        call scores them all against the paged cache, and the matched prefix
        commits — the first divergent token comes free from the verify
        logits, so a round always advances the slot by >= 1 token. Slots
        with no draft (or exhausted budget) fall through to _decode_once.

        Eligibility is a pure function of the REQUEST's own state (its
        committed tokens decide whether a draft exists), never of batch
        composition or engine scheduling — that is what keeps crash replay
        and router failover bitwise at temperature > 0: a replay regrows the
        same committed prefix, drafts the same tokens, samples through the
        same (seed, emitted-token-index) keys, and accepts the same prefix.
        Returns the slots advanced this round (skipped by _decode_once)."""
        from paddle_tpu.serving.speculation import next_draft_k

        advanced: set = set()
        if not self.speculate_k:
            return advanced
        candidates = [
            (slot, act) for slot, act in self.scheduler.active_slots()
            if not act.prefilling
        ]
        if candidates and _faults.get().active:
            # chaos site (spec_replay): the engine faults mid-speculation —
            # recovery must replay the in-flight drafts bitwise; gated on
            # live candidates so step=N counts real verify attempts
            _faults.get().maybe_raise("decode_raise")
        k = self.speculate_k
        for slot, act in candidates:
            h = act.handle
            remaining = h.max_new_tokens - act.generated
            if remaining <= 1:
                # the +K page headroom is no longer reachable (every future
                # write lands inside the base reservation): recycle it now
                # instead of riding it to retirement
                self.spec_pages_trimmed += self.cache.trim(
                    slot, h.prompt_len + h.max_new_tokens
                )
                continue
            drafter, kcell = self._drafter_for(slot, act)
            drafter.sync(act.prompt, h.tokens)
            # adaptive K (ROADMAP 1a): draft up to this request's CURRENT
            # effective K — grown/shrunk from its own acceptance history by
            # the pure next_draft_k rule — while the verify call below stays
            # [1, K_max+1] (short drafts zero-pad, signature stays 1)
            draft = drafter.draft(min(k, kcell[0]))
            if not draft:
                continue
            toks = np.zeros((1, k + 1), np.int32)
            toks[0, 0] = act.last_token
            toks[0, 1:1 + len(draft)] = draft  # short drafts zero-pad
            starts = np.array([act.next_pos], np.int32)
            steps0 = np.array([act.generated], np.int32)
            seeds, temps, top_ks = self._sampling_row(h)
            rows = self.cache.slot_row(slot)
            # one-signature assertion data: the verify shape is [1, K+1]
            # no matter the draft, the request mix, or the round
            self.verify_recompiles.record(
                stats.batch_signature(
                    {"tokens": toks, "starts": starts, "block_rows": rows,
                     "seeds": seeds, "steps0": steps0, "temps": temps,
                     "top_ks": top_ks}
                )
            )
            # span-ok: ring-buffer write only, constant name, int attrs —
            # the verify loop is hot-path like the decode loop (lint-pinned)
            with trace.span(
                "serving.verify_chunk", request_id=h.request_id,
                drafted=len(draft),
            ):
                self.k_pages, self.v_pages, sampled = self._verify(
                    self.params, self.k_pages, self.v_pages, toks,
                    starts, rows, seeds, steps0, temps, top_ks,
                )
                # sync-ok: ONE fetch per verify round — the K+1 sampled
                # tokens, which the host needs to run acceptance (the
                # autoregressive loop's EOS/budget checks ride the same
                # fetch); pages stay donated through, logits never land
                out = np.asarray(sampled)
            act.engine_steps += 1
            limit = min(len(draft), remaining - 1)
            n_match = 0
            while n_match < limit and int(out[n_match]) == draft[n_match]:
                n_match += 1
            emit = [int(out[i]) for i in range(n_match + 1)]
            # never commit past EOS: a drafted continuation that crosses the
            # stop token truncates there (the tail was never "emitted")
            for j, t in enumerate(emit):
                if t == self.cfg.eos_id:
                    emit = emit[: j + 1]
                    break
            for t in emit:
                act.append(t)
            self.tokens_generated += len(emit)
            self.spec_rounds += 1
            self.spec_tokens_drafted += len(draft)
            self.spec_tokens_accepted += max(0, len(emit) - 1)
            self.spec_k_eff_sum += len(draft)
            kcell[0] = next_draft_k(
                kcell[0], k, len(draft), max(0, len(emit) - 1)
            )
            SERVING_EVENTS.incr("serving_spec_rounds")
            SERVING_EVENTS.incr("serving_spec_accepted", max(0, len(emit) - 1))
            advanced.add(slot)
            reason = act.finished(self.cfg.eos_id)
            if reason is not None:
                self._drafters.pop(slot, None)
                self.scheduler.retire(slot, reason)
        return advanced

    def _decode_once(self, skip: frozenset = frozenset()) -> None:
        """One continuous-batching decode step: every active, fully-prefilled
        slot advances by one token inside the single fixed-shape executable
        (slots mid-chunked-prefill sit this one out as inactive lanes — their
        KV is still being committed; slots in `skip` already advanced through
        a speculative verify round this step)."""
        active = [
            (slot, act) for slot, act in self.scheduler.active_slots()
            if not act.prefilling and slot not in skip
        ]
        if not active:
            return
        if _faults.get().active:
            # chaos site: the engine faults mid-decode — the supervisor must
            # restart it, re-init the page pool and replay in-flight work;
            # gated on live slots so step=N counts real decode attempts
            _faults.get().maybe_raise("decode_raise")
        s = self.cache.max_slots
        tokens = np.zeros(s, np.int32)
        positions = np.zeros(s, np.int32)
        act_mask = np.zeros(s, bool)
        seeds = np.zeros(s, np.uint32)
        steps = np.zeros(s, np.int32)
        temps = np.zeros(s, np.float32)
        top_ks = np.zeros(s, np.int32)
        for slot, act in active:
            tokens[slot] = act.last_token
            positions[slot] = act.next_pos
            act_mask[slot] = True
            # sampling identity rides as DATA: the token this step emits for
            # the slot is draw `generated` of request `seed` — exactly what a
            # crash replay re-draws (bitwise), and still one decode signature
            seeds[slot] = act.handle.seed
            steps[slot] = act.generated
            temps[slot] = act.handle.temperature
            top_ks[slot] = act.handle.top_k
        bt = self.cache.block_table()
        # zero-recompile assertion data: the decode signature must be the
        # same every step no matter the request mix (fixed [max_slots] shape)
        self.recompiles.record(
            stats.batch_signature(
                {"tokens": tokens, "positions": positions, "active": act_mask,
                 "block_table": bt, "seeds": seeds, "steps": steps,
                 "temps": temps, "top_ks": top_ks}
            )
        )
        # span-ok: ring-buffer write only, constant name, int attr — no file
        # I/O or string formatting on the decode hot path; a no-op truth
        # test when PADDLE_TPU_TRACE is off (tests/test_lint_hotloop.py)
        with trace.span("serving.decode_step", active=len(active)):
            self.k_pages, self.v_pages, next_tok = self._decode(
                self.params, self.k_pages, self.v_pages,
                tokens, positions, act_mask, bt, seeds, steps, temps, top_ks,
            )
            # sync-ok: the ONE sanctioned fetch in the serving hot loop — the
            # sampled token ids, which the autoregressive loop needs on host to
            # detect EOS/budget and stream tokens; everything else stays device-
            # resident (pages are donated through, logits never leave the device)
            toks = np.asarray(next_tok)
        self.decode_steps += 1
        SERVING_EVENTS.incr("serving_decode_steps")
        for slot, act in active:
            act.append(toks[slot])
            act.engine_steps += 1
            self.tokens_generated += 1
            reason = act.finished(self.cfg.eos_id)
            if reason is not None:
                self._drafters.pop(slot, None)
                self.scheduler.retire(slot, reason)

    def step(self, now: Optional[float] = None) -> bool:
        """One engine iteration: reap expired/cancelled requests, then
        retire/admit at the boundary, then one prefill chunk per prefilling
        slot, then one decode step — chunked prefill and decode INTERLEAVE
        inside every engine step rather than alternate across them. Returns
        True when any work was done."""
        if now is None:
            # clock-ok: the ONE sanctioned wall-clock read per engine step —
            # deadline expiry, cancellation reaping and admission stamps all
            # batch off this single timestamp (a per-request read would scale
            # with occupancy; tests/test_lint_hotloop.py pins this site)
            now = time.monotonic()
        self._last_progress = now  # supervisor stall-watchdog heartbeat
        traces_before = self._jit_traces
        self.scheduler.reap(now)
        self._admit(now)
        self._prefill_chunks()
        before = self.decode_steps
        spec_before = self.spec_rounds
        advanced = self._speculate()
        self._decode_once(advanced)
        self._notify_streams()
        # auto EWMA reset (ISSUE 17): a step that compiled an executable
        # retired requests with second-scale service times; the first CLEAN
        # step afterwards forgets the poisoned estimate and lets
        # steady-state retirements re-seed it — a later first-hit bucket
        # compile re-arms the same healing
        if self._jit_traces != traces_before:
            self._load_est_dirty = True
        elif self._load_est_dirty:
            self._load_est_dirty = False
            self.scheduler.reset_load_estimate()
        return (
            self.decode_steps != before
            or self.spec_rounds != spec_before
            or bool(self.scheduler.active_slots())
        )

    # -- push-streaming seam (ISSUE 16) -------------------------------------
    def _notify_streams(self) -> None:
        """Wake frame pushers at this step boundary. The engine's entire
        contribution to push streaming is this sequence-number bump: no
        socket writes, no file I/O, no per-stream work — pusher threads
        (server.py) diff token lists and emit frames on their own time, so
        a slow or dead client can never block an engine step."""
        with self._stream_cv:
            self._stream_seq += 1
            self._stream_cv.notify_all()

    def stream_wait(self, seq: int, timeout: float = 0.1) -> int:
        """Block (pusher-thread side) until the engine advances past step
        sequence `seq` or `timeout` elapses; returns the current sequence.
        The timeout doubles as the liveness tick — pushers re-check their
        handles even when the engine idles (cancellations complete without
        a step)."""
        with self._stream_cv:
            if self._stream_seq == seq:
                self._stream_cv.wait(timeout)
            return self._stream_seq

    def run_until_idle(self) -> None:
        """Drive the engine on the calling thread until queue + slots drain
        (the single-threaded harness used by tests and the bench)."""
        while self.scheduler.has_work():
            self.step()

    # -- supervised engine thread (server mode) -----------------------------
    def serve_forever(self) -> "ServingSession":
        """Start the SUPERVISED engine: a supervisor thread spawns the
        engine thread and watches it — a fault or stall triggers recovery
        (pool re-init + in-flight replay) up to `engine_restart_max` times,
        after which every outstanding request fails `engine_error` (the
        trainer's precedent: fail loudly, never look healthy-but-slow).

        Idempotent: a second call while supervised is a no-op — two
        supervisors would race two engine threads over the same donated
        page pools (ServingServer.start + a manual caller is the easy way
        to get here)."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._supervise, name="serving-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def _engine_loop(self, gen: int) -> None:
        """The engine proper, pinned to generation `gen`: superseded threads
        (a stall recovery bumped the generation while this one was wedged)
        notice at the loop guard and exit WITHOUT touching session state."""
        while not self._stop.is_set() and self._engine_gen == gen:
            if not self.scheduler.has_work():
                with self._work:
                    self._work.wait(timeout=0.05)
                continue
            if _faults.maybe_stall(
                "engine_stall", env="PADDLE_TPU_SERVING_STALL_S",
                default_s=300.0,
            ):
                continue  # woke superseded: the loop guard re-checks gen
            # _engine_in_step gates the stall watchdog: a slow step (first-
            # step jit compile can take seconds) must never read as a stall —
            # only a wedge BETWEEN steps (the seeded site above, the only
            # place recovery can safely supersede this thread) counts. The
            # gen re-check and the flag flip are ATOMIC under _gen_lock: a
            # zombie waking between the loop guard and here would otherwise
            # race the supervisor's bump-then-recover into a concurrent step
            with self._gen_lock:
                if self._stop.is_set() or self._engine_gen != gen:
                    return
                self._engine_in_step = True
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — hand the fault to the
                # supervisor (recovery or give-up happens there, off the
                # engine thread); BaseException stays fatal on purpose
                self._engine_fault = e
                return
            finally:
                self._engine_in_step = False

    def _supervise(self) -> None:
        log = logging.getLogger("paddle_tpu.serving")
        poll_s = max(0.02, min(0.25, self.engine_stall_timeout_s / 4.0))
        while not self._stop.is_set():
            gen = self._engine_gen
            self._engine_fault = None
            # clock-ok: once per engine (re)start — the watchdog anchor
            self._last_progress = time.monotonic()
            eng = threading.Thread(
                target=self._engine_loop, args=(gen,),
                name="serving-engine", daemon=True,
            )
            eng.start()
            cause: Optional[str] = None
            busy_since: Optional[float] = None
            stale_polls = 0
            while not self._stop.is_set():
                eng.join(timeout=poll_s)
                if not eng.is_alive():
                    if self._engine_fault is None:
                        return  # clean stop
                    cause = "fault"
                    break
                # stall watchdog: only meaningful while work is pending AND
                # the engine sits between steps (an in-flight step may be a
                # multi-second first compile — and a mid-step thread cannot
                # be superseded safely anyway); anchored at the LATER of
                # last step start / when the queue last became non-empty, so
                # idle periods never read as stalls and a flood of submits
                # cannot mask a real one. Two consecutive stale samples
                # required, closing the microsecond between-steps window.
                now = time.monotonic()  # clock-ok: watchdog poll (4-16 Hz)
                if not self.scheduler.has_work():
                    busy_since = None
                    stale_polls = 0
                    continue
                if busy_since is None:
                    busy_since = now
                if (not self._engine_in_step
                        and now - max(self._last_progress, busy_since)
                        > self.engine_stall_timeout_s):
                    stale_polls += 1
                    if stale_polls >= 2:
                        # atomic supersede: bump the generation under the
                        # same lock the engine takes to enter a step, and
                        # only while it is still BETWEEN steps — a zombie
                        # that slipped into step() since the last sample
                        # keeps its generation and we go back to watching
                        # instead of re-initializing pools under its feet
                        with self._gen_lock:
                            if not self._engine_in_step:
                                self._engine_gen += 1
                                cause = "stall"
                        if cause is not None:
                            break
                        stale_polls = 0
                else:
                    stale_polls = 0
            if self._stop.is_set():
                return
            if cause == "fault":
                # the engine thread exited on its own (we saw it dead), so
                # no zombie can race recovery — bump for uniform invariants
                with self._gen_lock:
                    self._engine_gen += 1
            err = self._engine_fault
            if self.engine_restarts >= self.engine_restart_max:
                self.engine_error = err or RuntimeError(
                    f"serving engine stalled >"
                    f"{self.engine_stall_timeout_s}s and the restart budget "
                    f"({self.engine_restart_max}) is exhausted"
                )
                log.error(
                    "serving engine %s and restart budget (%d) exhausted; "
                    "failing %d outstanding request(s) and stopping",
                    cause, self.engine_restart_max,
                    len(self.scheduler.active_slots())
                    + self.scheduler.queue_depth(),
                )
                self._fail_outstanding()
                self._stop.set()
                return
            self._recover(cause, err, log)

    def _recover(self, cause: str, err: Optional[BaseException],
                 log: logging.Logger) -> None:
        """Engine restart: fresh page pool (the dead engine's donated
        buffers are consumed), in-flight requests replayed from their
        prompts (greedy decode is deterministic — result-transparent),
        past-deadline ones failed with the named reason."""
        t0 = time.monotonic()  # clock-ok: once per engine restart
        self.engine_restarts += 1
        SERVING_EVENTS.incr("serving_engine_restarts")
        obs_metrics.observe_engine_restart(cause)
        requeued, expired = self.scheduler.requeue_active(t0)
        self.cache.reset()
        self.k_pages, self.v_pages = self.cache.make_pools()
        # drafters are derived state: replayed requests regrow them from
        # the prompt (deterministically — same drafts, same acceptances)
        self._drafters.clear()
        SERVING_EVENTS.incr("serving_requests_replayed", requeued)
        trace.span_from_monotonic(
            "serving.engine_restart", t0,
            attrs={"cause": cause, "requeued": requeued, "expired": expired},
        )
        log.warning(
            "serving engine %s (%r); restart %d/%d: page pool re-initialized, "
            "%d in-flight request(s) replayed, %d failed past-deadline",
            cause, err, self.engine_restarts, self.engine_restart_max,
            requeued, expired,
        )

    def _fail_outstanding(self) -> None:
        """Complete every waiting + running handle as CANCELLED('engine_error')
        so result() raises instead of timing out; pages are released for
        accounting hygiene even though the engine is done."""
        sch = self.scheduler
        with sch.lock:
            waiting = list(sch.waiting)
            sch.waiting.clear()
            running = [(i, a) for i, a in enumerate(sch.slots) if a is not None]
            for slot, _ in running:
                sch.slots[slot] = None
                self.cache.release(slot)
        for w in waiting:
            if sch.quotas is not None:
                sch.quotas.release(w.handle.tenant)
            w.handle._complete(RequestHandle.CANCELLED, "engine_error")
        for _, act in running:
            if sch.quotas is not None:
                sch.quotas.release(act.handle.tenant)
            act.handle._complete(RequestHandle.CANCELLED, "engine_error")

    def stop(self) -> None:
        self._stop.set()
        with self._gen_lock:
            self._engine_gen += 1  # supersede any wedged engine thread
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def cancel_tenant(self, tenant: str) -> int:
        return self.scheduler.cancel_tenant(tenant)

    # -- telemetry ----------------------------------------------------------
    def progress_marker(self) -> tuple:
        """A tuple that changes whenever the engine makes ANY observable
        progress (decode steps, prefill chunks, retirements, cancellations).
        The fleet ReplicaAgent compares successive markers to self-fence a
        wedged engine: work pending + an unchanged marker past the fence
        window + the engine parked between steps = stop claiming liveness
        (serving/fleet.py)."""
        sch = self.scheduler
        return (
            self.decode_steps,
            self.prefill_chunks_committed,
            sch.completed,
            sch.cancelled,
            self.engine_restarts,
            # a single-stream speculative workload can advance through
            # verify rounds alone (decode skipped every step) — without
            # this term the fleet agent would self-fence a healthy engine
            self.spec_rounds,
        )

    def decode_shape_signatures(self) -> int:
        """Distinct decode-step input signatures seen — 1 means the entire
        serving lifetime shared one compiled decode program."""
        return self.recompiles.total_signatures()

    def verify_shape_signatures(self) -> int:
        """Distinct verify_chunk input signatures seen — 1 means every
        speculative round shared one compiled [1, K+1] program (0 when
        speculation never ran)."""
        return self.verify_recompiles.total_signatures()

    def stats(self) -> Dict:
        sch = self.scheduler
        return {
            "decode_steps": self.decode_steps,
            # TP accounting from SHARDING METADATA, not trust: what one chip
            # actually holds (replicated leaves count fully, sharded 1/N)
            "tp": self.model.tp_size,
            "param_bytes_per_chip": stats.per_chip_tree_bytes(self.params),
            "pool_bytes_per_chip": stats.per_chip_tree_bytes(
                [self.k_pages, self.v_pages]
            ),
            "tokens_generated": self.tokens_generated,
            "decode_shape_signatures": self.decode_shape_signatures(),
            "queue_depth": sch.queue_depth(),
            "active_slots": len(sch.active_slots()),
            "max_slots": self.cache.max_slots,
            "free_pages": self.cache.free_pages,
            "pages_in_use": self.cache.pages_in_use,
            "completed": sch.completed,
            "rejected": sch.rejected,
            "cancelled": sch.cancelled,
            "shed": sch.shed,
            "deadline_misses": sch.deadline_misses,
            "pages_recycled_on_cancel": sch.pages_recycled_on_cancel,
            "engine_restarts": self.engine_restarts,
            "estimated_queue_wait_s": round(sch.estimate_wait_s(), 4),
            "prefill_buckets": list(self.buckets),
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunks_committed": self.prefill_chunks_committed,
            "default_temperature": self.default_temperature,
            "default_top_k": self.default_top_k,
            "speculate_k": self.speculate_k,
            "spec_rounds": self.spec_rounds,
            "spec_tokens_drafted": self.spec_tokens_drafted,
            "spec_tokens_accepted": self.spec_tokens_accepted,
            "spec_acceptance_rate": round(
                self.spec_tokens_accepted / self.spec_tokens_drafted, 4
            ) if self.spec_tokens_drafted else 0.0,
            "spec_pages_trimmed": self.spec_pages_trimmed,
            # adaptive draft length (ISSUE 19 satellite): mean tokens
            # actually DRAFTED per verify round — converges up toward K on
            # accepting streams, down toward 1 when drafts keep missing
            "spec_effective_k": round(
                self.spec_k_eff_sum / self.spec_rounds, 4
            ) if self.spec_rounds else 0.0,
            "verify_shape_signatures": self.verify_shape_signatures(),
            # shared-prefix cache (ISSUE 19): hit rate + sharing/COW/eviction
            # counters; stable keys (zeros) with the cache off
            **self.cache.prefix_stats(),
        }


def make_demo_session(
    vocab: int = 128,
    n_layers: int = 2,
    d_model: int = 32,
    n_heads: int = 2,
    seed: int = 0,
    tp: int = 0,
    **session_kw,
) -> ServingSession:
    """A small seeded model + session (CLI --demo, benches, tests).

    tp > 1 builds the 2-D ("data"=1, "model"=tp) rules mesh and serves
    tensor-parallel over tp chips: params and the KV page pool shard over
    the model axis, tokens stay identical to tp=0/1 (the single-chip
    oracle) — pinned in tests/test_tp_serving.py."""
    import jax

    buckets = session_kw.pop("prefill_buckets", (16, 32, 64))
    max_new = session_kw.pop("max_new_limit", 64)
    # chunked prefill serves prompts beyond the largest bucket, so callers
    # exercising it can ask for more position room than the bucket default
    max_len = session_kw.pop("max_len", None) or max(buckets) + max_new
    mesh = None
    if tp and int(tp) > 1:
        from paddle_tpu.parallel.rules import make_tp_mesh

        mesh = make_tp_mesh(int(tp))
    model = ServableLM(LMConfig(
        vocab=vocab, n_layers=n_layers, d_model=d_model, n_heads=n_heads,
        max_len=max_len,
    ), mesh=mesh)
    params = model.init_params(jax.random.PRNGKey(seed))
    return ServingSession(
        model, params, prefill_buckets=buckets, max_new_limit=max_new,
        **session_kw,
    )
