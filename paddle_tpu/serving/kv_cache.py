"""Paged KV cache: one physical page pool + per-slot block tables.

The device layout follows the TPU paged-attention kernel convention
(jax.experimental.pallas.ops.tpu.paged_attention; "Ragged Paged Attention",
PAPERS.md): every sequence shares ONE pool

    k_pages, v_pages : [n_layers, num_pages, page_size, kv_dim]

and each decode slot owns a row of the block table
[max_slots, max_pages_per_seq] mapping logical page j -> physical page id.
Page 0 is reserved as the dump page: inactive slots write their (discarded)
step KV there and unused block-table entries point there, so the compiled
decode program always runs at one fixed shape — which slots are live and how
long each sequence is are pure *data*, never *shape*. That is what lets a
mixed-age, mixed-length batch share a single executable with zero recompiles
(asserted via stats.RecompileStats in the serving session).

Allocation is a host-side free list over REFCOUNTED pages (ISSUE 19). A
request reserves ceil((prompt_len + max_new_tokens) / page_size) pages at
admission — worst case up front, so a running sequence can never hit page
exhaustion mid-flight (admission control is the only place that says no).
Without the prefix cache every page has refcount 1 and the arithmetic is
bitwise the old free-list's.

With `prefix_cache=True` the shared-prefix index (prefix_cache.py) rides on
top: reserve() first walks the tenant's chain and ALIASES every matching
committed full page into the new slot's block table read-only (+1 ref each
— a handful of host ints; the compiled executables never know), then pops
fresh pages only for the uncached suffix. Committed prompt pages register
into the index (the index holds its own +1 ref), so they outlive their
request and serve later ones; a page only returns to the free list when its
LAST reference drops — a slot releasing, a trim, or an LRU eviction of an
unreferenced cached page under pool pressure. Copy-on-write falls out of
page granularity: only FULL immutable prompt pages are ever shared, and the
first divergent page is a fresh private page the request's own chunked
prefill writes. Retirement/cancel recycling is counted in PHYSICAL frees
(a shared page decrefs without freeing), so the leak-watch counters stay
exact under aliasing."""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.serving.prefix_cache import PrefixIndex


class PagedKVCache:
    """Host-side page allocator + device-resident page pool.

    The device arrays are created lazily (jax import deferred) and are
    *owned by the serving session* once handed out: the compiled decode/commit
    steps donate and replace them, so this class only tracks the host-side
    free list, refcounts, block tables and (optionally) the prefix index."""

    def __init__(
        self,
        n_layers: int,
        kv_dim: int,
        num_pages: int,
        page_size: int,
        max_slots: int,
        max_pages_per_seq: int,
        pool_sharding=None,
        prefix_cache: bool = False,
        prefix_cache_pages: Optional[int] = None,
    ):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the dump page)")
        self.n_layers = n_layers
        self.kv_dim = kv_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_pages_per_seq = max_pages_per_seq
        # TP placement (ISSUE 12): a NamedSharding splitting the kv_dim's
        # kv_heads over the mesh 'model' axis — per-chip pool bytes drop
        # ~TPx. Stored here so every make_pools call (init AND the crash-
        # recovery re-init) lands the pools on the same layout. None =
        # single-chip default placement.
        self.pool_sharding = pool_sharding
        # pop() hands out ascending ids; page 0 is never allocatable
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
        # the block table rides to the device as step *data* each decode —
        # same shape every step, so it never perturbs the executable cache
        self._table = np.zeros((max_slots, max_pages_per_seq), np.int32)
        # refcounts (ISSUE 19): slots + the prefix index each hold one
        # reference; a page recycles only at zero. Prefix off => every page
        # is refcount<=1 and the accounting is bitwise the old free list's.
        self._refcount: List[int] = [0] * num_pages
        # shared-prefix index (None = disabled). The _prefix_lock guards the
        # index STRUCTURE against the one cross-thread access — a submit
        # thread's admission-pricing peek racing the engine thread's
        # insert/evict; free-list/refcount mutations stay engine-thread-only
        # (under the scheduler lock), exactly as before.
        self.prefix: Optional[PrefixIndex] = (
            PrefixIndex(page_size) if prefix_cache else None
        )
        self.prefix_cache_pages = (
            None if prefix_cache_pages is None else int(prefix_cache_pages)
        )
        self._prefix_lock = threading.Lock()
        # per-slot prefix state: hit tokens aliased at reserve, prompt pages
        # registered so far, and the chain node registration continues from
        self._slot_hit: List[int] = [0] * max_slots
        self._slot_reg: List[int] = [0] * max_slots
        self._slot_node: List[int] = [0] * max_slots

    # -- device pool --------------------------------------------------------
    def make_pools(self, dtype=None):
        """Fresh zeroed (k_pages, v_pages) device arrays, placed on
        `pool_sharding` when the cache is tensor-parallel."""
        import jax
        import jax.numpy as jnp

        shape = (self.n_layers, self.num_pages, self.page_size, self.kv_dim)
        dtype = dtype or jnp.float32
        if self.pool_sharding is None:
            return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
        zeros = jax.jit(
            lambda: jnp.zeros(shape, dtype),
            out_shardings=self.pool_sharding,
        )
        return zeros(), zeros()

    # -- accounting ---------------------------------------------------------
    def pages_needed(self, total_len: int) -> int:
        return -(-int(total_len) // self.page_size)  # ceil div

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def _decref(self, page: int) -> bool:
        """Drop one reference; True when the page physically recycled."""
        rc = self._refcount[page] - 1
        self._refcount[page] = rc
        if rc == 0:
            self._free.append(page)
            return True
        return False

    def can_reserve(self, total_len: int) -> bool:
        n = self.pages_needed(total_len)
        avail = len(self._free)
        if self.prefix is not None:
            # unreferenced cached pages are reclaimable on demand (reserve
            # evicts LRU under pressure), so admission counts them as free
            with self._prefix_lock:
                avail += self.prefix.evictable(self._refcount)
        return n <= self.max_pages_per_seq and n <= avail

    # -- reserve / release --------------------------------------------------
    def reserve(
        self,
        slot: int,
        total_len: int,
        tenant: str = "default",
        prompt: Optional[Sequence[int]] = None,
    ) -> List[int]:
        """Reserve pages covering `total_len` tokens for `slot`; returns the
        physical page ids. Raises if the slot is occupied or pages are short —
        callers gate on can_reserve (admission control).

        With the prefix cache enabled and `prompt` given, the leading pages
        come ALIASED from the tenant's chain (read-only, +1 ref each) and
        only the uncached suffix pops fresh pages — `hit_tokens(slot)` then
        reports how many prompt tokens the slot skipped prefilling. Under
        pool pressure, unreferenced cached pages are LRU-evicted to make
        room before giving up."""
        if self._slot_pages[slot]:
            raise RuntimeError(f"slot {slot} already holds pages")
        n = self.pages_needed(total_len)
        if n > self.max_pages_per_seq:
            raise ValueError(
                f"sequence of {total_len} tokens needs {n} pages > "
                f"max_pages_per_seq={self.max_pages_per_seq}"
            )
        matched: List[int] = []
        node = 0
        if self.prefix is not None and prompt is not None:
            with self._prefix_lock:
                cow0 = self.prefix.cow_events
                matched, node = self.prefix.match(tenant, prompt)
                cow = self.prefix.cow_events - cow0
            # alias the cached prefix BEFORE any eviction below: ref >= 2
            # makes these pages invisible to evict_lru
            for p in matched:
                self._refcount[p] += 1
            if matched:
                obs_metrics.observe_prefix_hit(len(matched))
            if cow:
                obs_metrics.observe_prefix_cow(cow)
        need_fresh = n - len(matched)
        if need_fresh > len(self._free) and self.prefix is not None:
            evicted = 0
            with self._prefix_lock:
                while need_fresh > len(self._free):
                    page = self.prefix.evict_lru(self._refcount)
                    if page is None:
                        break
                    self._decref(page)  # the index's own reference
                    evicted += 1
            if evicted:
                obs_metrics.observe_prefix_evictions(evicted)
        if need_fresh > len(self._free):
            for p in matched:  # roll the aliases back — nothing reserved
                self._decref(p)
            raise RuntimeError(
                f"KV pool exhausted: need {need_fresh} pages, "
                f"{len(self._free)} free"
            )
        fresh = [self._free.pop() for _ in range(need_fresh)]
        for p in fresh:
            self._refcount[p] = 1
        pages = matched + fresh
        self._slot_pages[slot] = pages
        self._slot_hit[slot] = len(matched) * self.page_size
        self._slot_reg[slot] = len(matched)
        self._slot_node[slot] = node
        self._table[slot, :] = 0
        self._table[slot, : len(pages)] = pages
        return pages

    def hit_tokens(self, slot: int) -> int:
        """Prompt tokens slot `slot` aliased from the prefix cache at its
        reservation — the chunked prefill starts at exactly this offset."""
        return self._slot_hit[slot]

    def peek_hit_tokens(self, tenant: str, prompt: Sequence[int]) -> int:
        """Admission-pricing probe (Scheduler.submit): leading prompt tokens
        cached right now. Read-only — no recency bump, no counters — so the
        load estimate never perturbs eviction order. 0 when disabled."""
        if self.prefix is None:
            return 0
        with self._prefix_lock:
            return self.prefix.peek_hit_tokens(tenant, prompt)

    def commit_prefix(self, slot: int, tenant: str,
                      prompt: Sequence[int], committed_len: int) -> int:
        """Register slot `slot`'s prompt pages fully covered by
        `committed_len` committed tokens into the tenant's chain (the index
        takes one reference per NEWLY registered page, which is what lets
        the pages outlive the request). Incremental: called after the
        whole-prompt commit and after every prefill chunk, it only walks the
        pages added since the last call. Returns pages newly registered."""
        if self.prefix is None:
            return 0
        upto = min(int(committed_len), len(prompt)) // self.page_size
        frm = self._slot_reg[slot]
        if upto <= frm:
            return 0
        pages = self._slot_pages[slot]
        with self._prefix_lock:
            node, registered = self.prefix.extend(
                tenant, self._slot_node[slot], prompt, frm, upto, pages
            )
            for p in registered:
                self._refcount[p] += 1  # the index's reference
            self._slot_node[slot] = node
            self._slot_reg[slot] = upto
            evicted = self._enforce_cap_locked()
        if evicted:
            obs_metrics.observe_prefix_evictions(evicted)
        return len(registered)

    def _enforce_cap_locked(self) -> int:
        """Best-effort `prefix_cache_pages` cap (caller holds _prefix_lock):
        LRU-evict unreferenced entries until the index fits. Entries still
        aliased by live slots pin — the cap re-checks when those slots
        release. Returns pages evicted."""
        if self.prefix_cache_pages is None:
            return 0
        evicted = 0
        while len(self.prefix) > self.prefix_cache_pages:
            page = self.prefix.evict_lru(self._refcount)
            if page is None:
                break
            self._decref(page)
            evicted += 1
        return evicted

    def trim(self, slot: int, total_len: int) -> int:
        """Release the slot's surplus tail pages beyond what `total_len`
        tokens need (speculative-decode rollback, ISSUE 16): admission
        reserves `speculate_k` tokens of headroom so a verify chunk can
        always scatter its K+1 positions, and once the request's remaining
        budget can no longer use that headroom the surplus recycles here
        instead of riding to retirement. Tail pages are always private
        (aliased prefix pages sit at the FRONT and registration never
        reaches past the prompt), so the decref frees them physically.
        Returns how many pages were freed; idempotent."""
        pages = self._slot_pages[slot]
        keep = self.pages_needed(total_len)
        if not pages or keep >= len(pages):
            return 0
        surplus = pages[keep:]
        self._slot_pages[slot] = pages[:keep]
        freed = sum(1 for p in surplus if self._decref(p))
        self._table[slot, keep:] = 0
        return freed

    def release(self, slot: int) -> int:
        """Drop the slot's references (KV recycling); returns how many pages
        PHYSICALLY returned to the free list — a page another slot still
        aliases, or one the prefix index caches, only decrefs (satellite 2:
        cancel/retire accounting counts each physical free exactly once).
        Idempotent for an empty slot."""
        pages = self._slot_pages[slot]
        self._slot_pages[slot] = []
        freed = sum(1 for p in pages if self._decref(p))
        self._table[slot, :] = 0
        self._slot_hit[slot] = 0
        self._slot_reg[slot] = 0
        self._slot_node[slot] = 0
        if self.prefix is not None and self.prefix_cache_pages is not None:
            # this release may have unpinned cached entries past the cap
            with self._prefix_lock:
                evicted = self._enforce_cap_locked()
            if evicted:
                obs_metrics.observe_prefix_evictions(evicted)
        return freed

    def flush_prefix(self) -> int:
        """Drop every prefix-index entry and release the index's references;
        pages no slot holds return to the free list (the rest recycle when
        their slots release). Benches/tests use this for the zero-leak gate;
        live slots keep decoding untouched — their aliased pages stay
        referenced, only un-cacheable from now on."""
        if self.prefix is None:
            return 0
        with self._prefix_lock:
            pages = self.prefix.drop_all()
        freed = sum(1 for p in pages if self._decref(p))
        # chain continuation points are gone: let still-prefilling slots
        # re-register from the root on their next commit
        self._slot_reg = [0] * self.max_slots
        self._slot_node = [0] * self.max_slots
        return freed

    def reset(self) -> None:
        """Rebuild the allocator to its just-constructed state (engine crash
        recovery): every page free, every slot empty, table zeroed — and the
        prefix index INVALIDATED, because every cached page id points into
        the dead pool; replayed requests re-populate it against the fresh
        one (no stale aliases). The device pools are NOT touched here — the
        session re-creates them via make_pools(), because a failed donated
        decode/commit step has already consumed the old buffers."""
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._refcount = [0] * self.num_pages
        self._slot_pages = [[] for _ in range(self.max_slots)]
        self._slot_hit = [0] * self.max_slots
        self._slot_reg = [0] * self.max_slots
        self._slot_node = [0] * self.max_slots
        self._table[:] = 0
        if self.prefix is not None:
            with self._prefix_lock:
                self.prefix.drop_all()

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    def page_refcount(self, page: int) -> int:
        return self._refcount[page]

    def prefix_stats(self) -> dict:
        """The prefix-cache telemetry block session.stats() embeds — stable
        keys whether or not the cache is enabled."""
        if self.prefix is None:
            return {
                "prefix_cache_enabled": False,
                "prefix_hit_rate": 0.0,
                "prefix_pages_shared": 0,
                "prefix_pages_cached": 0,
                "prefix_pages_cow": 0,
                "prefix_evictions": 0,
                "prefix_hit_rate_by_tenant": {},
            }
        with self._prefix_lock:
            d = self.prefix.stats()
            d["prefix_pages_unreferenced"] = self.prefix.evictable(
                self._refcount
            )
        d["prefix_cache_enabled"] = True
        d["prefix_cache_pages_cap"] = self.prefix_cache_pages
        return d

    def block_table(self) -> np.ndarray:
        """The [max_slots, max_pages_per_seq] int32 table (live view — copy
        is taken by the device transfer itself). On TPU this same table is
        the SCALAR-PREFETCH operand of the ragged paged-attention kernel
        (ops/pallas/paged_attention.py): its rows drive the page-gather DMA."""
        return self._table

    def slot_row(self, slot: int) -> np.ndarray:
        """One slot's [1, max_pages_per_seq] block-table row — the shape the
        per-slot prefill/commit/chunk executables take (live view)."""
        return self._table[slot : slot + 1]
