"""Paged KV cache: one physical page pool + per-slot block tables.

The device layout follows the TPU paged-attention kernel convention
(jax.experimental.pallas.ops.tpu.paged_attention; "Ragged Paged Attention",
PAPERS.md): every sequence shares ONE pool

    k_pages, v_pages : [n_layers, num_pages, page_size, kv_dim]

and each decode slot owns a row of the block table
[max_slots, max_pages_per_seq] mapping logical page j -> physical page id.
Page 0 is reserved as the dump page: inactive slots write their (discarded)
step KV there and unused block-table entries point there, so the compiled
decode program always runs at one fixed shape — which slots are live and how
long each sequence is are pure *data*, never *shape*. That is what lets a
mixed-age, mixed-length batch share a single executable with zero recompiles
(asserted via stats.RecompileStats in the serving session).

Allocation is a host-side free list. A request reserves
ceil((prompt_len + max_new_tokens) / page_size) pages at admission — worst
case up front, so a running sequence can never hit page exhaustion mid-flight
(admission control is the only place that says no). Retirement returns the
pages for reuse; recycling is tested (tests/test_serving.py)."""

from __future__ import annotations

from typing import List

import numpy as np


class PagedKVCache:
    """Host-side page allocator + device-resident page pool.

    The device arrays are created lazily (jax import deferred) and are
    *owned by the serving session* once handed out: the compiled decode/commit
    steps donate and replace them, so this class only tracks the host-side
    free list and block tables."""

    def __init__(
        self,
        n_layers: int,
        kv_dim: int,
        num_pages: int,
        page_size: int,
        max_slots: int,
        max_pages_per_seq: int,
        pool_sharding=None,
    ):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the dump page)")
        self.n_layers = n_layers
        self.kv_dim = kv_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_pages_per_seq = max_pages_per_seq
        # TP placement (ISSUE 12): a NamedSharding splitting the kv_dim's
        # kv_heads over the mesh 'model' axis — per-chip pool bytes drop
        # ~TPx. Stored here so every make_pools call (init AND the crash-
        # recovery re-init) lands the pools on the same layout. None =
        # single-chip default placement.
        self.pool_sharding = pool_sharding
        # pop() hands out ascending ids; page 0 is never allocatable
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
        # the block table rides to the device as step *data* each decode —
        # same shape every step, so it never perturbs the executable cache
        self._table = np.zeros((max_slots, max_pages_per_seq), np.int32)

    # -- device pool --------------------------------------------------------
    def make_pools(self, dtype=None):
        """Fresh zeroed (k_pages, v_pages) device arrays, placed on
        `pool_sharding` when the cache is tensor-parallel."""
        import jax
        import jax.numpy as jnp

        shape = (self.n_layers, self.num_pages, self.page_size, self.kv_dim)
        dtype = dtype or jnp.float32
        if self.pool_sharding is None:
            return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
        zeros = jax.jit(
            lambda: jnp.zeros(shape, dtype),
            out_shardings=self.pool_sharding,
        )
        return zeros(), zeros()

    # -- accounting ---------------------------------------------------------
    def pages_needed(self, total_len: int) -> int:
        return -(-int(total_len) // self.page_size)  # ceil div

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def can_reserve(self, total_len: int) -> bool:
        n = self.pages_needed(total_len)
        return n <= self.max_pages_per_seq and n <= len(self._free)

    # -- reserve / release --------------------------------------------------
    def reserve(self, slot: int, total_len: int) -> List[int]:
        """Reserve pages covering `total_len` tokens for `slot`; returns the
        physical page ids. Raises if the slot is occupied or pages are short —
        callers gate on can_reserve (admission control)."""
        if self._slot_pages[slot]:
            raise RuntimeError(f"slot {slot} already holds pages")
        n = self.pages_needed(total_len)
        if n > self.max_pages_per_seq:
            raise ValueError(
                f"sequence of {total_len} tokens needs {n} pages > "
                f"max_pages_per_seq={self.max_pages_per_seq}"
            )
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {n} pages, {len(self._free)} free"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._slot_pages[slot] = pages
        self._table[slot, :] = 0
        self._table[slot, : len(pages)] = pages
        return pages

    def trim(self, slot: int, total_len: int) -> int:
        """Return the slot's surplus tail pages beyond what `total_len`
        tokens need (speculative-decode rollback, ISSUE 16): admission
        reserves `speculate_k` tokens of headroom so a verify chunk can
        always scatter its K+1 positions, and once the request's remaining
        budget can no longer use that headroom the surplus recycles here
        instead of riding to retirement. Returns how many pages were freed;
        idempotent (trimming to the current size is a no-op)."""
        pages = self._slot_pages[slot]
        keep = self.pages_needed(total_len)
        if not pages or keep >= len(pages):
            return 0
        surplus = pages[keep:]
        self._slot_pages[slot] = pages[:keep]
        self._free.extend(surplus)
        self._table[slot, keep:] = 0
        return len(surplus)

    def release(self, slot: int) -> int:
        """Return the slot's pages to the free list (KV recycling); returns
        how many were freed. Idempotent for an empty slot."""
        pages = self._slot_pages[slot]
        self._slot_pages[slot] = []
        self._free.extend(pages)
        self._table[slot, :] = 0
        return len(pages)

    def reset(self) -> None:
        """Rebuild the allocator to its just-constructed state (engine crash
        recovery): every page free, every slot empty, table zeroed. The
        device pools are NOT touched here — the session re-creates them via
        make_pools(), because a failed donated decode/commit step has already
        consumed the old buffers."""
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._slot_pages = [[] for _ in range(self.max_slots)]
        self._table[:] = 0

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    def block_table(self) -> np.ndarray:
        """The [max_slots, max_pages_per_seq] int32 table (live view — copy
        is taken by the device transfer itself). On TPU this same table is
        the SCALAR-PREFETCH operand of the ragged paged-attention kernel
        (ops/pallas/paged_attention.py): its rows drive the page-gather DMA."""
        return self._table

    def slot_row(self, slot: int) -> np.ndarray:
        """One slot's [1, max_pages_per_seq] block-table row — the shape the
        per-slot prefill/commit/chunk executables take (live view)."""
        return self._table[slot : slot + 1]
