"""ServableLM: a decode-oriented causal LM with paged attention.

The serving runtime is split the way TPU inference engines split it
("Ragged Paged Attention", PAPERS.md):

  * `prefill`     — full-context forward over a *bucket-padded* prompt
                    [B, T_bucket]; returns the first sampled token plus the
                    per-position K/V to commit into the page pool. One
                    executable per bucket (a handful, fixed up front).
  * `prefill_chunk` — ONE fixed-size chunk [1, C] of a long prompt: attends
                    over the slot's already-committed pages (positions <
                    chunk start) plus causally within the chunk, so a prompt
                    of any length is committed C tokens per engine step
                    interleaved with decode (ISSUE 11 chunked prefill; the
                    Orca-style continuous-batching refinement, PAPERS.md).
                    One executable for every prompt length.
  * `commit_prefill` — scatters prompt K/V into the slot's pages at an
                    arbitrary `starts` offset (whole prompts and chunks
                    share this one scatter).
  * `decode_step` — ONE token for ALL slots at the fixed [max_slots] shape:
                    write the step K/V into each slot's current page, gather
                    each slot's pages through its block-table row, masked
                    attention up to its own position. Sequence length, batch
                    occupancy and sequence age are data, not shape — the
                    whole serving lifetime runs this single executable.

On TPU the decode gather+softmax runs as the Pallas ragged paged-attention
kernel (ops/pallas/paged_attention.py) — the jnp gather path here stays the
CPU oracle, asserted equivalent in interpret mode (tests/test_decode_fastpath).

Sampling (ISSUE 11) happens ON DEVICE in every token-emitting executable:
`_sample` draws through a per-request key `fold_in(PRNGKey(seed), step)` with
per-slot temperature / top-k riding as DATA, so the one compiled decode
program serves greedy (temperature 0 — bitwise the old argmax) and sampled
requests side by side, and an engine-crash replay that reuses the request's
seed and step index regenerates bitwise-identical tokens (PR 10's
result-transparent restart extends to sampling).

Per-slot computation is strictly batched-independent (every einsum keeps the
slot dimension; no cross-slot reduction), which is what makes continuous
batching *bitwise* transparent: a request's tokens are identical whether it
ran alone or joined a full batch mid-stream (tests/test_serving.py).

Tensor parallelism (ISSUE 12) rides the named sharding-rules mesh
(parallel/rules.py): every parameter declares LOGICAL axes once
(`param_logical_axes`), the rules table maps them to the mesh `model` axis
(heads/kv_heads/mlp/vocab split, embed replicated), and the per-layer
resharding points carry `with_sharding_constraint`s so XLA's partitioner
emits exactly one all-reduce per row-parallel projection (wo, w2) and one
logits all-gather at the unembed output — sampling then runs on REPLICATED
logits, so the greedy branch stays collective-free and tokens are identical
to the single-chip oracle. The paged KV pool shards its kv_heads dim over
the same axis (per-chip pool bytes drop ~TPx), block tables stay replicated
host state, and `_paged_attention` runs per-shard over the LOCAL head slice
under shard_map — the Pallas kernel and the jnp gather oracle take the same
specs, so the CPU tests exercise the TP code structure bit-for-bit.
With no mesh (or model axis 1) every path is bitwise the PR-11 single-chip
program — TP support costs the one-chip deployment nothing.

All methods are pure functions of (params, inputs) — the serving session owns
jit + donation. The model is deliberately small-config-friendly (the repo's
CPU oracle discipline) but structurally a real transformer LM: pre-RMSNorm,
multi-head causal attention, GELU MLP, learned positions, tied nothing."""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

Array = jax.Array

NEG_INF = -1e9

# the paged KV pools' logical axes [n_layers, num_pages, page_size, kv_dim]:
# only the flattened (kv_heads * head_dim) dim shards, over the model axis
POOL_LOGICAL_AXES = (None, None, None, "kv_heads")


@dataclasses.dataclass(frozen=True)
class LMConfig:
    vocab: int
    n_layers: int = 2
    d_model: int = 64
    n_heads: int = 2
    max_len: int = 512
    bos_id: int = 1
    eos_id: int = 2

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def _rms(x: Array, scale: Array) -> Array:
    return x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * scale


class ServableLM:
    def __init__(self, cfg: LMConfig, mesh=None, rules=None):
        from paddle_tpu.parallel.rules import ShardingRules

        self.cfg = cfg
        self.scale = 1.0 / float(np.sqrt(cfg.head_dim))
        self.rules = rules if rules is not None else ShardingRules()
        self._axes_cache: Optional[Dict[str, Tuple[Optional[str], ...]]] = None
        # a mesh whose model axis is 1 (or absent) is the single-chip path:
        # drop it so every program stays bitwise the unsharded PR-11 one
        tp = int(dict(mesh.shape).get("model", 1)) if mesh is not None else 1
        self.mesh = mesh if tp > 1 else None
        if self.mesh is not None:
            for what, n in (("n_heads", cfg.n_heads), ("vocab", cfg.vocab)):
                if n % tp:
                    raise ValueError(
                        f"tensor parallelism over {tp} chips needs "
                        f"{what} % {tp} == 0 (got {what}={n}): heads and "
                        "vocab split over the mesh 'model' axis"
                    )

    @property
    def tp_size(self) -> int:
        return int(dict(self.mesh.shape)["model"]) if self.mesh is not None else 1

    # -- named sharding (ISSUE 12) ------------------------------------------
    def param_logical_axes(self) -> Dict[str, Tuple[Optional[str], ...]]:
        """Every parameter's LOGICAL axes — declared once, resolved through
        the rules table (parallel/rules.py DEFAULT_RULES). Megatron-style TP:
        qkv/w1 column-parallel (heads/mlp), wo/w2 row-parallel, embed rows +
        unembed columns over vocab; norms/biases/positions replicated.
        Built once and cached: shard_params resolves every parameter
        through here (O(P) placements, not O(P^2) dict rebuilds)."""
        if self._axes_cache is not None:
            return self._axes_cache
        axes: Dict[str, Tuple[Optional[str], ...]] = {
            "embed": ("vocab", "embed"),
            "pos": ("length", "embed"),
            "lnf": ("embed",),
            "unembed": ("embed", "vocab"),
        }
        for i in range(self.cfg.n_layers):
            axes.update({
                f"l{i}.wq": ("embed", "heads"),
                f"l{i}.wk": ("embed", "kv_heads"),
                f"l{i}.wv": ("embed", "kv_heads"),
                f"l{i}.wo": ("heads", "embed"),
                f"l{i}.w1": ("embed", "mlp"),
                f"l{i}.w2": ("mlp", "embed"),
                f"l{i}.b1": ("mlp",),
                f"l{i}.b2": ("embed",),
                f"l{i}.ln1": ("embed",),
                f"l{i}.ln2": ("embed",),
            })
        self._axes_cache = axes
        return axes

    def param_sharding(self, name: str, ndim: int):
        """One param's NamedSharding through the rules table, or None when
        there is no TP mesh (single-chip: the session device_puts plainly).
        A param MISSING from param_logical_axes raises: silently replicating
        it would quietly erode the per-chip memory win the table exists to
        deliver — same contract as the rules table's unknown-name error."""
        if self.mesh is None:
            return None
        axes = self.param_logical_axes().get(name)
        if axes is None:
            raise KeyError(
                f"param {name!r} has no entry in param_logical_axes — every "
                "tensor must declare its logical axes (use ('embed',)-style "
                "replicated entries explicitly, never by omission)"
            )
        return self.rules.sharding_for(self.mesh, axes, ndim=ndim, param=name)

    def shard_params(self, params: Dict[str, Array]) -> Dict[str, Array]:
        """Place params on the TP mesh per the rules (identity on 1 chip)."""
        if self.mesh is None:
            return jax.device_put(params)
        return {
            k: jax.device_put(v, self.param_sharding(k, jnp.ndim(v)))
            for k, v in params.items()
        }

    def pool_sharding(self):
        """The paged KV pools' placement: kv_heads (inside the flattened KD
        dim) over the model axis — per-chip pool bytes drop ~TPx. None on a
        single chip."""
        if self.mesh is None:
            return None
        return self.rules.sharding_for(
            self.mesh, POOL_LOGICAL_AXES, param="k_pages"
        )

    def _constrain(self, x: Array, *logical: Optional[str]) -> Array:
        """`with_sharding_constraint` through the rules table — the explicit
        resharding points that pin where the partitioner places collectives.
        Identity without a TP mesh, so single-chip programs are untouched."""
        if self.mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, self.rules.sharding_for(self.mesh, logical, ndim=jnp.ndim(x))
        )

    # -- params -------------------------------------------------------------
    def init_params(self, rng: Array) -> Dict[str, Array]:
        cfg = self.cfg
        d, v = cfg.d_model, cfg.vocab
        # per-tensor keys derived by name-stable fold_in so adding a tensor
        # never reshuffles the others (checkpoint/test determinism)
        p: Dict[str, Array] = {
            "embed": 0.1 * jax.random.normal(
                jax.random.fold_in(rng, 1), (v, d), jnp.float32
            ),
            "pos": 0.02 * jax.random.normal(
                jax.random.fold_in(rng, 2), (cfg.max_len, d), jnp.float32
            ),
            "lnf": jnp.ones((d,)),
            "unembed": 0.1 * jax.random.normal(
                jax.random.fold_in(rng, 3), (d, v), jnp.float32
            ),
        }
        for i in range(cfg.n_layers):
            for j, (name, shape) in enumerate((
                ("wq", (d, d)), ("wk", (d, d)), ("wv", (d, d)), ("wo", (d, d)),
                ("w1", (d, 4 * d)), ("w2", (4 * d, d)),
            )):
                k = jax.random.fold_in(jax.random.fold_in(rng, 1000 + i), j)
                p[f"l{i}.{name}"] = 0.1 * jax.random.normal(k, shape, jnp.float32)
            p[f"l{i}.b1"] = jnp.zeros((4 * d,))
            p[f"l{i}.b2"] = jnp.zeros((d,))
            p[f"l{i}.ln1"] = jnp.ones((d,))
            p[f"l{i}.ln2"] = jnp.ones((d,))
        return p

    def save(self, path: str, params: Dict[str, Array]) -> None:
        np.savez(path, __vocab__=self.cfg.vocab, __n_layers__=self.cfg.n_layers,
                 __d_model__=self.cfg.d_model, __n_heads__=self.cfg.n_heads,
                 __max_len__=self.cfg.max_len, __bos__=self.cfg.bos_id,
                 __eos__=self.cfg.eos_id,
                 **{k: np.asarray(v) for k, v in params.items()})

    @classmethod
    def load(
        cls, path: str, mesh=None, rules=None
    ) -> Tuple["ServableLM", Dict[str, Array]]:
        """Checkpoints are CANONICAL full arrays (save() materializes every
        shard), so the same .npz loads onto any layout: single chip, TP=2,
        TP=4 — the cross-layout contract tests/test_tp_serving.py pins."""
        with np.load(path) as z:
            cfg = LMConfig(
                vocab=int(z["__vocab__"]), n_layers=int(z["__n_layers__"]),
                d_model=int(z["__d_model__"]), n_heads=int(z["__n_heads__"]),
                max_len=int(z["__max_len__"]), bos_id=int(z["__bos__"]),
                eos_id=int(z["__eos__"]),
            )
            params = {
                k: jnp.asarray(z[k]) for k in z.files if not k.startswith("__")
            }
        return cls(cfg, mesh=mesh, rules=rules), params

    # -- on-device sampling -------------------------------------------------
    def _sample(
        self,
        logits: Array,   # [B, V]
        seeds: Array,    # [B] uint32 per-request seed
        steps: Array,    # [B] int32 token index within the request (0 = first)
        temps: Array,    # [B] f32; 0 = greedy argmax (bitwise the old path)
        top_ks: Array,   # [B] int32; 0 = no top-k truncation
    ) -> Array:
        """Per-slot token sampling, batched-independent (vmap keeps the slot
        dimension, so a slot's token never depends on its batch-mates — the
        continuous-batching transparency contract extends to sampling). The
        key is `fold_in(PRNGKey(seed), step)`: a crash replay that re-runs
        (seed, step) draws the same gumbel noise, hence the same token.

        The sampled branch (per-slot full-vocab sort + gumbel draw) sits
        behind a lax.cond on `any(temps > 0)`: an all-greedy batch — the
        default serving config — skips it entirely at runtime, so sampling
        support costs the greedy decode hot loop nothing."""
        greedy = jnp.argmax(logits, -1).astype(jnp.int32)

        def _sampled(_):
            def one(lg, seed, step, temp, k):
                key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
                # top-k as a threshold: keep logits >= the k-th largest
                # (ties keep all — deterministic, no index shuffling)
                thr = jnp.sort(lg)[::-1][jnp.clip(k, 1, lg.shape[-1]) - 1]
                keep = (k <= 0) | (lg >= thr)
                safe_t = jnp.where(temp > 0, temp, 1.0)
                z = jnp.where(keep, lg / safe_t, NEG_INF).astype(jnp.float32)
                # gumbel-max: argmax(z + g) ~ softmax(z) — one pass, no cumsum
                u = jax.random.uniform(key, lg.shape, jnp.float32, 1e-20, 1.0)
                return jnp.argmax(z - jnp.log(-jnp.log(u))).astype(jnp.int32)

            sampled = jax.vmap(one)(logits, seeds, steps, temps, top_ks)
            return jnp.where(temps > 0.0, sampled, greedy)

        return jax.lax.cond(
            jnp.any(temps > 0.0), _sampled, lambda _: greedy, operand=None
        )

    # -- shared block body --------------------------------------------------
    def _mlp(self, params, i: int, x: Array) -> Array:
        h = _rms(x, params[f"l{i}.ln2"])
        out = x + (
            jax.nn.gelu(h @ params[f"l{i}.w1"] + params[f"l{i}.b1"])
            @ params[f"l{i}.w2"] + params[f"l{i}.b2"]
        )
        # TP resharding point: w2 is row-parallel (contraction dim sharded
        # over 'model'), so the partitioner all-reduces the partial sums
        # HERE — one collective per layer's MLP, activations replicated out
        return self._constrain(out)

    # -- full-context forward (prefill + the sequential reference path) -----
    def _context_forward(self, params, tokens: Array) -> Tuple[Array, Array, Array]:
        """The ONE causal-forward implementation: padded [B, T] tokens ->
        (logits [B, T, V], kc, vc [L, B, T, kv_dim]). Both the sequential
        reference path (forward_logits) and the serving prefill call this,
        so the attention math the equivalence tests compare against cannot
        drift between them. Unused outputs are DCE'd under jit."""
        cfg = self.cfg
        b, t = tokens.shape
        h_, hd = cfg.n_heads, cfg.head_dim
        x = self._constrain(params["embed"][tokens] + params["pos"][:t][None])
        causal = jnp.tril(jnp.ones((t, t), bool))
        kcs, vcs = [], []
        for i in range(cfg.n_layers):
            h = _rms(x, params[f"l{i}.ln1"])
            q = (h @ params[f"l{i}.wq"]).reshape(b, t, h_, hd)
            kf = h @ params[f"l{i}.wk"]
            vf = h @ params[f"l{i}.wv"]
            kcs.append(kf)
            vcs.append(vf)
            k = kf.reshape(b, t, h_, hd)
            v = vf.reshape(b, t, h_, hd)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * self.scale
            s = jnp.where(causal[None, None], s, NEG_INF)
            w = jax.nn.softmax(s.astype(jnp.float32), -1).astype(x.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, t, -1)
            # TP resharding point: wo is row-parallel — all-reduce here
            x = self._constrain(x + ctx @ params[f"l{i}.wo"])
            x = self._mlp(params, i, x)
        # the unembed is column-parallel (vocab sharded): constraining the
        # logits REPLICATED places one all-gather here, so sampling below is
        # collective-free and bitwise the single-chip math
        logits = self._constrain(_rms(x, params["lnf"]) @ params["unembed"])
        kc = self._constrain(jnp.stack(kcs), None, None, None, "kv_heads")
        vc = self._constrain(jnp.stack(vcs), None, None, None, "kv_heads")
        return logits, kc, vc

    def forward_logits(self, params, tokens: Array) -> Array:
        """Causal forward over padded [B, T] prompts -> logits [B, T, V].
        Padding positions produce garbage logits but cannot leak into valid
        ones: causal masking means position t only sees positions <= t, all
        of which are real tokens whenever t is — masking is positional, so
        no lengths argument exists (ISSUE 11 removed the dead parameter)."""
        return self._context_forward(params, tokens)[0]

    def prefill(
        self, params, tokens: Array, lengths: Array,
        seeds: Array, temps: Array, top_ks: Array,
    ) -> Tuple[Array, Array, Array]:
        """Bucket-padded prompt forward.

        tokens [B, T_bucket] int32, lengths [B] -> (first_tok [B] int32 —
        sampled on device at each prompt's last valid position (step 0 of
        the request's key; temperature 0 = greedy argmax), so the host never
        fetches a logits tensor — kc, vc [L, B, T, kv_dim] to commit)."""
        logits, kc, vc = self._context_forward(params, tokens)
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0]  # [B, V]
        first_tok = self._sample(
            last, seeds, jnp.zeros_like(lengths), temps, top_ks
        )
        return first_tok, kc, vc

    # -- chunked prefill (ISSUE 11) -----------------------------------------
    def prefill_chunk(
        self,
        params,
        k_pages: Array,      # [L, NP, PS, KD] (donated: chunk KV commits here)
        v_pages: Array,
        tokens: Array,       # [1, C] int32 — chunk tokens, zero-padded
        starts: Array,       # [1] int32 — chunk's first position
        lengths: Array,      # [1] int32 — the FULL prompt length
        block_rows: Array,   # [1, max_pages_per_seq] int32 — the slot's row
        seeds: Array,        # [1] uint32   (sampling: used on the final chunk)
        temps: Array,        # [1] f32
        top_ks: Array,       # [1] int32
    ) -> Tuple[Array, Array, Array]:
        """One C-token chunk of a long prompt: attention = (already-committed
        pages, masked to positions < start) ++ (causal within the chunk), so
        iterating chunks reproduces the whole-prompt causal forward exactly —
        the K/V committed per chunk equals the corresponding slice of
        `prefill`'s, and the final chunk's last-position logits equal the
        whole prompt's. ONE executable serves every prompt length (chunk
        geometry is fixed [1, C]; start/length are data).

        The chunk's K/V commits via `commit_prefill` INSIDE this program
        (pages donated in/out, the decode_step convention): reading and
        scattering the pool in one executable lets XLA update it in place,
        where a separate commit dispatch would copy the whole pool — the
        donated input would still be pinned by this program's in-flight read.

        Returns (k_pages, v_pages, tok [1] int32 — sampled at position
        length-1, meaningful only on the final chunk; the host fetches it
        exactly once, there)."""
        b, c = tokens.shape
        logits, kc, vc = self._chunk_forward(
            params, k_pages, v_pages, tokens, starts, block_rows
        )
        # last valid position falls in this chunk only on the final chunk;
        # clamp keeps the index in range for the earlier ones (tok unused)
        last_in_chunk = jnp.clip(lengths - 1 - starts, 0, c - 1)
        last = jnp.take_along_axis(
            logits, last_in_chunk[:, None, None], axis=1
        )[:, 0]
        tok = self._sample(
            last, seeds, jnp.zeros_like(lengths), temps, top_ks
        )
        k_pages, v_pages = self.commit_prefill(
            k_pages, v_pages, kc, vc, lengths, block_rows, starts,
        )
        return k_pages, v_pages, tok

    def _chunk_forward(
        self,
        params,
        k_pages: Array,      # [L, NP, PS, KD]
        v_pages: Array,
        tokens: Array,       # [1, C] int32
        starts: Array,       # [1] int32 — position of tokens[:, 0]
        block_rows: Array,   # [1, max_pages_per_seq] int32
    ) -> Tuple[Array, Array, Array]:
        """The ONE chunk-shaped forward: attention = (already-committed
        pages, masked to positions < start) ++ (causal within the chunk).
        Shared by `prefill_chunk` (long-prompt prefill) and `verify_chunk`
        (speculative-decode scoring, ISSUE 16), so the two cannot drift —
        the verify call literally IS a prefill-chunk forward over
        [last_token, draft_1..K]. Returns (logits [1, C, V], kc, vc
        [L, 1, C, KD]); the pools are only READ here — each caller commits
        through `commit_prefill` itself."""
        cfg = self.cfg
        b, c = tokens.shape
        h_, hd = cfg.n_heads, cfg.head_dim
        ps = k_pages.shape[2]
        pos = starts[:, None] + jnp.arange(c)[None, :]          # [1, C]
        # padded tail may run past max_len; clamp the INDEX only (those
        # positions are causally invisible to every valid one)
        x = self._constrain(
            params["embed"][tokens]
            + params["pos"][jnp.minimum(pos, cfg.max_len - 1)]
        )
        t_ctx = block_rows.shape[1] * ps
        ctx_idx = jnp.arange(t_ctx)
        # committed-context mask: this chunk sees pages strictly before it
        past = ctx_idx[None, None, :] < starts[:, None, None]   # [1, 1, T_ctx]
        causal = jnp.tril(jnp.ones((c, c), bool))
        kcs, vcs = [], []
        for i in range(cfg.n_layers):
            h = _rms(x, params[f"l{i}.ln1"])
            q = (h @ params[f"l{i}.wq"]).reshape(b, c, h_, hd)
            kf = h @ params[f"l{i}.wk"]
            vf = h @ params[f"l{i}.wv"]
            kcs.append(kf)
            vcs.append(vf)
            k_self = kf.reshape(b, c, h_, hd)
            v_self = vf.reshape(b, c, h_, hd)
            k_past = k_pages[i][block_rows].reshape(b, t_ctx, h_, hd)
            v_past = v_pages[i][block_rows].reshape(b, t_ctx, h_, hd)
            sp = jnp.einsum("bqhd,bkhd->bhqk", q, k_past) * self.scale
            sp = jnp.where(past[:, None], sp, NEG_INF)
            ss = jnp.einsum("bqhd,bkhd->bhqk", q, k_self) * self.scale
            ss = jnp.where(causal[None, None], ss, NEG_INF)
            s_all = jnp.concatenate([sp, ss], -1)               # [1,H,C,T+C]
            w = jax.nn.softmax(s_all.astype(jnp.float32), -1).astype(x.dtype)
            ctx = (
                jnp.einsum("bhqk,bkhd->bqhd", w[..., :t_ctx], v_past)
                + jnp.einsum("bhqk,bkhd->bqhd", w[..., t_ctx:], v_self)
            ).reshape(b, c, -1)
            # TP resharding point: row-parallel wo all-reduces here
            x = self._constrain(x + ctx @ params[f"l{i}.wo"])
            x = self._mlp(params, i, x)
        # replicated logits: the one all-gather, sampling collective-free
        logits = self._constrain(_rms(x, params["lnf"]) @ params["unembed"])
        return logits, jnp.stack(kcs), jnp.stack(vcs)

    # -- speculative decoding (ISSUE 16) ------------------------------------
    def verify_chunk(
        self,
        params,
        k_pages: Array,      # [L, NP, PS, KD] (donated: chunk KV commits here)
        v_pages: Array,
        tokens: Array,       # [1, K+1] int32: last committed token + K drafts
        starts: Array,       # [1] int32 — position of the last committed token
        block_rows: Array,   # [1, max_pages_per_seq] int32 — the slot's row
        seeds: Array,        # [1] uint32 — the request's sampling seed
        steps0: Array,       # [1] int32 — emitted-token index of sampled[0]
        temps: Array,        # [1] f32
        top_ks: Array,       # [1] int32
    ) -> Tuple[Array, Array, Array]:
        """Score K drafted tokens in ONE prefill-chunk-shaped call
        (prompt-lookup speculative decoding, ISSUE 16). The chunk is
        [last_token, draft_1..K] at positions [starts .. starts+K]: the
        logits at chunk position i are exactly what a sequential decode
        would see after emitting drafts 1..i, so `sampled[i]` is the token
        the model WOULD emit there — the host accepts draft_{i+1} while
        sampled[i] == draft_{i+1} and takes the first divergent token free.

        The replay/determinism contract is carried by `steps0`: position i
        samples through fold_in(PRNGKey(seed), steps0 + i) — keyed by the
        EMITTED TOKEN INDEX, never the engine step — so a crash replay or
        router failover that re-runs speculation from the prompt re-draws
        the same keys in the same order and regenerates bitwise-identical
        tokens even at temperature > 0.

        All K+1 positions' K/V commit into the slot's pages here (fused,
        pools donated — the prefill_chunk convention). Rejected positions
        leave stale K/V behind, which is harmless by construction: every
        attention mask excludes positions at/after the committed frontier
        (`ctx_idx < starts` here, `ctx_idx <= positions` in decode), and
        the next verify/decode step REWRITES each position before it can
        become visible. Returns (k_pages, v_pages, sampled [K+1] int32)."""
        b, c = tokens.shape
        logits, kc, vc = self._chunk_forward(
            params, k_pages, v_pages, tokens, starts, block_rows
        )
        lane = jnp.arange(c, dtype=jnp.int32)
        sampled = self._sample(
            logits[0],                                   # [K+1, V]
            jnp.broadcast_to(seeds, (c,)),
            steps0 + lane,                               # emitted-token index
            jnp.broadcast_to(temps, (c,)),
            jnp.broadcast_to(top_ks, (c,)),
        )
        # commit every chunk position (lengths = starts + K + 1): positions
        # past the slot's reserved pages fall through the block-table row's
        # zero entries into dump page 0, so over-speculation near the budget
        # end can never corrupt a neighbour
        k_pages, v_pages = self.commit_prefill(
            k_pages, v_pages, kc, vc, starts + c, block_rows, starts,
        )
        return k_pages, v_pages, sampled

    # -- page pool plumbing -------------------------------------------------
    def commit_prefill(
        self,
        k_pages: Array,  # [L, NP, PS, KD] (donated)
        v_pages: Array,
        kc: Array,  # [L, B, T, KD] from prefill / prefill_chunk
        vc: Array,
        lengths: Array,  # [B] — the FULL prompt length
        block_rows: Array,  # [B, max_pages_per_seq] int32
        starts: Array,  # [B] — position of kc[..., 0, :] (0 = whole prompt)
    ) -> Tuple[Array, Array]:
        """Scatter prompt K/V into the slots' pages at offset `starts`
        (whole-prompt prefill passes zeros; chunked prefill commits each
        chunk at its own offset). Positions past a prompt's length land in
        dump page 0 (never read unmasked)."""
        ps = k_pages.shape[2]
        l, b, t, kd = kc.shape
        pos = starts[:, None] + jnp.arange(t)[None, :]  # [B, T] absolute
        valid = pos < lengths[:, None]  # [B, T]
        logical = jnp.minimum(pos // ps, block_rows.shape[1] - 1)
        page = jnp.take_along_axis(block_rows, logical, axis=1)
        page = jnp.where(valid, page, 0).reshape(-1)  # [B*T]
        offs = (pos % ps).reshape(-1)
        kf = kc.reshape(l, b * t, kd)
        vf = vc.reshape(l, b * t, kd)
        # pool placement pinned at every producing seam: the scatter keeps
        # the kv_heads dim sharded (indices touch page/offset dims only), so
        # donated pools round-trip their TP layout with no resharding
        return (
            self._constrain(k_pages.at[:, page, offs].set(kf), *POOL_LOGICAL_AXES),
            self._constrain(v_pages.at[:, page, offs].set(vf), *POOL_LOGICAL_AXES),
        )

    # -- the ONE decode executable ------------------------------------------
    def _paged_attention_local(
        self,
        q: Array,            # [S, KD_local] — this shard's head slice
        k_pages_i: Array,    # [NP, PS, KD_local]
        v_pages_i: Array,
        block_table: Array,  # [S, P]
        positions: Array,    # [S]
        n_heads: int,
    ) -> Array:
        """Ragged paged attention over `n_heads` heads (the FULL head count
        on one chip; the LOCAL slice per shard under TP — heads are
        batched-independent, so the per-shard math is bitwise the
        single-chip math for those heads).

        Two numerically-equivalent paths behind one seam: the Pallas kernel
        (ops/pallas/paged_attention.py — block table drives the page gathers
        in the DMA engine, online f32 softmax in VMEM) when `pallas.enabled()`
        (TPU, or PADDLE_TPU_PALLAS=1/interpret), else the dense jnp gather —
        which is also the kernel's CPU ORACLE: interpret-mode equality across
        mixed lengths/block tables is pinned in tests/test_decode_fastpath."""
        from paddle_tpu.ops import pallas as _pallas

        s = q.shape[0]
        h_, hd = n_heads, self.cfg.head_dim
        if _pallas.enabled():
            from paddle_tpu.ops.pallas.paged_attention import (
                paged_attention_decode,
            )

            return paged_attention_decode(
                q, k_pages_i, v_pages_i, block_table, positions,
                scale=self.scale, n_heads=h_,
            ).astype(q.dtype)
        ps = k_pages_i.shape[1]
        qh = q.reshape(s, h_, hd)
        # dense gather: [S, P, PS, KD] -> [S, T_ctx, H, hd]
        k_seq = k_pages_i[block_table].reshape(s, -1, h_, hd)
        v_seq = v_pages_i[block_table].reshape(s, -1, h_, hd)
        ctx_idx = jnp.arange(block_table.shape[1] * ps)
        att_mask = ctx_idx[None, :] <= positions[:, None]  # [S, T_ctx]
        sc = jnp.einsum("shd,sthd->sht", qh, k_seq) * self.scale
        sc = jnp.where(att_mask[:, None, :], sc, NEG_INF)
        w = jax.nn.softmax(sc.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("sht,sthd->shd", w, v_seq).reshape(s, -1)

    def _paged_attention(
        self,
        q: Array,            # [S, KD] — this layer's queries
        k_pages_i: Array,    # [NP, PS, KD] — this layer's page pools
        v_pages_i: Array,
        block_table: Array,  # [S, P]
        positions: Array,    # [S]
    ) -> Array:
        """The TP dispatch seam over `_paged_attention_local`.

        Single chip: the local body at the full head count (unchanged PR-11
        program). Under TP: shard_map over the mesh 'model' axis — each
        shard runs the SAME body (Pallas kernel on TPU, jnp gather oracle on
        CPU, identical in_specs) on its resident kv_heads slice of the page
        pool, with the block table and positions replicated; attention never
        crosses heads, so the seam adds ZERO collectives and the kernel's
        scalar-prefetch block-table operand (its grid geometry) is the same
        per shard as on one chip — just fewer heads per page fetch."""
        if self.mesh is None:
            return self._paged_attention_local(
                q, k_pages_i, v_pages_i, block_table, positions,
                n_heads=self.cfg.n_heads,
            )
        from paddle_tpu.parallel.shard_map_compat import shard_map

        local = functools.partial(
            self._paged_attention_local,
            n_heads=self.cfg.n_heads // self.tp_size,
        )
        return shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                P(None, "model"),        # q: head slice
                P(None, None, "model"),  # k_pages[i]: kv_heads slice
                P(None, None, "model"),  # v_pages[i]
                P(None, None),           # block table: replicated host state
                P(None),                 # positions: replicated
            ),
            out_specs=P(None, "model"),
            check_vma=False,
        )(q, k_pages_i, v_pages_i, block_table, positions)

    def decode_step(
        self,
        params,
        k_pages: Array,  # [L, NP, PS, KD] (donated)
        v_pages: Array,
        tokens: Array,  # [S] int32: each slot's last token
        positions: Array,  # [S] int32: that token's position
        active: Array,  # [S] bool
        block_table: Array,  # [S, max_pages_per_seq] int32
        seeds: Array,  # [S] uint32 per-request sampling seed
        steps: Array,  # [S] int32 token index within the request
        temps: Array,  # [S] f32 temperature (0 = greedy)
        top_ks: Array,  # [S] int32 top-k truncation (0 = off)
    ) -> Tuple[Array, Array, Array]:
        """One decode step for all slots at the fixed [max_slots] shape.

        Writes each active slot's step K/V into its current page (inactive
        slots dump into page 0), then attends over the slot's own gathered
        pages masked to positions <= its own (the _paged_attention seam:
        Pallas ragged kernel on TPU, jnp gather oracle elsewhere) and samples
        on device through each request's own key. Returns (k_pages, v_pages,
        next_tok [S] int32). Every op keeps the slot dimension batched (no
        cross-slot reduction), so a slot's result is bitwise independent of
        the rest of the batch."""
        cfg = self.cfg
        ps = k_pages.shape[2]
        # the embed table is row-sharded over vocab: the token gather's
        # cross-shard combine happens here, activations replicated after
        x = self._constrain(
            params["embed"][tokens] + params["pos"][positions]
        )
        cur_page = jnp.take_along_axis(
            block_table, (positions // ps)[:, None], axis=1
        )[:, 0]
        cur_page = jnp.where(active, cur_page, 0)
        offs = positions % ps
        for i in range(cfg.n_layers):
            h = _rms(x, params[f"l{i}.ln1"])
            q = h @ params[f"l{i}.wq"]  # [S, KD]
            k_new = h @ params[f"l{i}.wk"]  # [S, KD]
            v_new = h @ params[f"l{i}.wv"]
            k_pages = k_pages.at[i, cur_page, offs].set(k_new)
            v_pages = v_pages.at[i, cur_page, offs].set(v_new)
            ctx = self._paged_attention(
                q, k_pages[i], v_pages[i], block_table, positions
            )
            # TP resharding point: row-parallel wo all-reduces here
            x = self._constrain(x + ctx @ params[f"l{i}.wo"])
            x = self._mlp(params, i, x)
        # replicated logits (the one all-gather): sampling below then runs
        # entirely locally — no collective in the greedy branch, tokens
        # bitwise the single-chip oracle's
        logits = self._constrain(_rms(x, params["lnf"]) @ params["unembed"])
        next_tok = self._sample(logits, seeds, steps, temps, top_ks)
        return (
            self._constrain(k_pages, *POOL_LOGICAL_AXES),
            self._constrain(v_pages, *POOL_LOGICAL_AXES),
            next_tok,
        )
