"""ServableLM: a decode-oriented causal LM with paged attention.

The serving runtime is split the way TPU inference engines split it
("Ragged Paged Attention", PAPERS.md):

  * `prefill`     — full-context forward over a *bucket-padded* prompt
                    [B, T_bucket]; returns the first sampled token plus the
                    per-position K/V to commit into the page pool. One
                    executable per bucket (a handful, fixed up front).
  * `commit_prefill` — scatters the prompt K/V into the slot's pages.
  * `decode_step` — ONE token for ALL slots at the fixed [max_slots] shape:
                    write the step K/V into each slot's current page, gather
                    each slot's pages through its block-table row, masked
                    attention up to its own position. Sequence length, batch
                    occupancy and sequence age are data, not shape — the
                    whole serving lifetime runs this single executable.

Per-slot computation is strictly batched-independent (every einsum keeps the
slot dimension; no cross-slot reduction), which is what makes continuous
batching *bitwise* transparent: a request's tokens are identical whether it
ran alone or joined a full batch mid-stream (tests/test_serving.py).

All methods are pure functions of (params, inputs) — the serving session owns
jit + donation. The model is deliberately small-config-friendly (the repo's
CPU oracle discipline) but structurally a real transformer LM: pre-RMSNorm,
multi-head causal attention, GELU MLP, learned positions, tied nothing."""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class LMConfig:
    vocab: int
    n_layers: int = 2
    d_model: int = 64
    n_heads: int = 2
    max_len: int = 512
    bos_id: int = 1
    eos_id: int = 2

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def _rms(x: Array, scale: Array) -> Array:
    return x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * scale


class ServableLM:
    def __init__(self, cfg: LMConfig):
        self.cfg = cfg
        self.scale = 1.0 / float(np.sqrt(cfg.head_dim))

    # -- params -------------------------------------------------------------
    def init_params(self, rng: Array) -> Dict[str, Array]:
        cfg = self.cfg
        d, v = cfg.d_model, cfg.vocab
        # per-tensor keys derived by name-stable fold_in so adding a tensor
        # never reshuffles the others (checkpoint/test determinism)
        p: Dict[str, Array] = {
            "embed": 0.1 * jax.random.normal(
                jax.random.fold_in(rng, 1), (v, d), jnp.float32
            ),
            "pos": 0.02 * jax.random.normal(
                jax.random.fold_in(rng, 2), (cfg.max_len, d), jnp.float32
            ),
            "lnf": jnp.ones((d,)),
            "unembed": 0.1 * jax.random.normal(
                jax.random.fold_in(rng, 3), (d, v), jnp.float32
            ),
        }
        for i in range(cfg.n_layers):
            for j, (name, shape) in enumerate((
                ("wq", (d, d)), ("wk", (d, d)), ("wv", (d, d)), ("wo", (d, d)),
                ("w1", (d, 4 * d)), ("w2", (4 * d, d)),
            )):
                k = jax.random.fold_in(jax.random.fold_in(rng, 1000 + i), j)
                p[f"l{i}.{name}"] = 0.1 * jax.random.normal(k, shape, jnp.float32)
            p[f"l{i}.b1"] = jnp.zeros((4 * d,))
            p[f"l{i}.b2"] = jnp.zeros((d,))
            p[f"l{i}.ln1"] = jnp.ones((d,))
            p[f"l{i}.ln2"] = jnp.ones((d,))
        return p

    def save(self, path: str, params: Dict[str, Array]) -> None:
        np.savez(path, __vocab__=self.cfg.vocab, __n_layers__=self.cfg.n_layers,
                 __d_model__=self.cfg.d_model, __n_heads__=self.cfg.n_heads,
                 __max_len__=self.cfg.max_len, __bos__=self.cfg.bos_id,
                 __eos__=self.cfg.eos_id,
                 **{k: np.asarray(v) for k, v in params.items()})

    @classmethod
    def load(cls, path: str) -> Tuple["ServableLM", Dict[str, Array]]:
        with np.load(path) as z:
            cfg = LMConfig(
                vocab=int(z["__vocab__"]), n_layers=int(z["__n_layers__"]),
                d_model=int(z["__d_model__"]), n_heads=int(z["__n_heads__"]),
                max_len=int(z["__max_len__"]), bos_id=int(z["__bos__"]),
                eos_id=int(z["__eos__"]),
            )
            params = {
                k: jnp.asarray(z[k]) for k in z.files if not k.startswith("__")
            }
        return cls(cfg), params

    # -- shared block body --------------------------------------------------
    def _mlp(self, params, i: int, x: Array) -> Array:
        h = _rms(x, params[f"l{i}.ln2"])
        return x + (
            jax.nn.gelu(h @ params[f"l{i}.w1"] + params[f"l{i}.b1"])
            @ params[f"l{i}.w2"] + params[f"l{i}.b2"]
        )

    # -- full-context forward (prefill + the sequential reference path) -----
    def _context_forward(self, params, tokens: Array) -> Tuple[Array, Array, Array]:
        """The ONE causal-forward implementation: padded [B, T] tokens ->
        (logits [B, T, V], kc, vc [L, B, T, kv_dim]). Both the sequential
        reference path (forward_logits) and the serving prefill call this,
        so the attention math the equivalence tests compare against cannot
        drift between them. Unused outputs are DCE'd under jit."""
        cfg = self.cfg
        b, t = tokens.shape
        h_, hd = cfg.n_heads, cfg.head_dim
        x = params["embed"][tokens] + params["pos"][:t][None]
        causal = jnp.tril(jnp.ones((t, t), bool))
        kcs, vcs = [], []
        for i in range(cfg.n_layers):
            h = _rms(x, params[f"l{i}.ln1"])
            q = (h @ params[f"l{i}.wq"]).reshape(b, t, h_, hd)
            kf = h @ params[f"l{i}.wk"]
            vf = h @ params[f"l{i}.wv"]
            kcs.append(kf)
            vcs.append(vf)
            k = kf.reshape(b, t, h_, hd)
            v = vf.reshape(b, t, h_, hd)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * self.scale
            s = jnp.where(causal[None, None], s, NEG_INF)
            w = jax.nn.softmax(s.astype(jnp.float32), -1).astype(x.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, t, -1)
            x = x + ctx @ params[f"l{i}.wo"]
            x = self._mlp(params, i, x)
        logits = _rms(x, params["lnf"]) @ params["unembed"]
        return logits, jnp.stack(kcs), jnp.stack(vcs)

    def forward_logits(self, params, tokens: Array, lengths: Array) -> Array:
        """Causal forward over padded [B, T] prompts -> logits [B, T, V].
        Padding positions produce garbage logits but cannot leak into valid
        ones: causal masking means position t only sees positions <= t, all
        of which are real tokens whenever t is. (`lengths` kept for API
        symmetry; masking is positional.)"""
        del lengths
        return self._context_forward(params, tokens)[0]

    def prefill(
        self, params, tokens: Array, lengths: Array
    ) -> Tuple[Array, Array, Array]:
        """Bucket-padded prompt forward.

        tokens [B, T_bucket] int32, lengths [B] -> (first_tok [B] int32 —
        greedy argmax at each prompt's last valid position, so the host never
        fetches a logits tensor — kc, vc [L, B, T, kv_dim] to commit)."""
        logits, kc, vc = self._context_forward(params, tokens)
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0]  # [B, V]
        first_tok = jnp.argmax(last, -1).astype(jnp.int32)
        return first_tok, kc, vc

    # -- page pool plumbing -------------------------------------------------
    def commit_prefill(
        self,
        k_pages: Array,  # [L, NP, PS, KD] (donated)
        v_pages: Array,
        kc: Array,  # [L, B, T, KD] from prefill
        vc: Array,
        lengths: Array,  # [B]
        block_rows: Array,  # [B, max_pages_per_seq] int32
    ) -> Tuple[Array, Array]:
        """Scatter prompt K/V into the slots' pages. Positions past a
        prompt's length land in dump page 0 (never read unmasked)."""
        ps = k_pages.shape[2]
        l, b, t, kd = kc.shape
        pos = jnp.arange(t)
        valid = pos[None, :] < lengths[:, None]  # [B, T]
        logical = pos // ps  # [T]
        page = jnp.take_along_axis(
            block_rows, jnp.broadcast_to(logical[None, :], (b, t)), axis=1
        )
        page = jnp.where(valid, page, 0).reshape(-1)  # [B*T]
        offs = jnp.broadcast_to((pos % ps)[None, :], (b, t)).reshape(-1)
        kf = kc.reshape(l, b * t, kd)
        vf = vc.reshape(l, b * t, kd)
        return (
            k_pages.at[:, page, offs].set(kf),
            v_pages.at[:, page, offs].set(vf),
        )

    # -- the ONE decode executable ------------------------------------------
    def decode_step(
        self,
        params,
        k_pages: Array,  # [L, NP, PS, KD] (donated)
        v_pages: Array,
        tokens: Array,  # [S] int32: each slot's last token
        positions: Array,  # [S] int32: that token's position
        active: Array,  # [S] bool
        block_table: Array,  # [S, max_pages_per_seq] int32
    ) -> Tuple[Array, Array, Array]:
        """One decode step for all slots at the fixed [max_slots] shape.

        Writes each active slot's step K/V into its current page (inactive
        slots dump into page 0), then attends over the slot's own gathered
        pages masked to positions <= its own. Returns (k_pages, v_pages,
        next_tok [S] int32 — greedy). Every op keeps the slot dimension
        batched (no cross-slot reduction), so a slot's result is bitwise
        independent of the rest of the batch."""
        cfg = self.cfg
        s = tokens.shape[0]
        h_, hd = cfg.n_heads, cfg.head_dim
        ps = k_pages.shape[2]
        x = params["embed"][tokens] + params["pos"][positions]
        cur_page = jnp.take_along_axis(
            block_table, (positions // ps)[:, None], axis=1
        )[:, 0]
        cur_page = jnp.where(active, cur_page, 0)
        offs = positions % ps
        ctx_idx = jnp.arange(block_table.shape[1] * ps)
        att_mask = ctx_idx[None, :] <= positions[:, None]  # [S, T_ctx]
        for i in range(cfg.n_layers):
            h = _rms(x, params[f"l{i}.ln1"])
            q = (h @ params[f"l{i}.wq"]).reshape(s, h_, hd)
            k_new = h @ params[f"l{i}.wk"]  # [S, KD]
            v_new = h @ params[f"l{i}.wv"]
            k_pages = k_pages.at[i, cur_page, offs].set(k_new)
            v_pages = v_pages.at[i, cur_page, offs].set(v_new)
            # gather this slot's pages: [S, P, PS, KD] -> [S, T_ctx, H, hd]
            k_seq = k_pages[i][block_table].reshape(s, -1, h_, hd)
            v_seq = v_pages[i][block_table].reshape(s, -1, h_, hd)
            sc = jnp.einsum("shd,sthd->sht", q, k_seq) * self.scale
            sc = jnp.where(att_mask[:, None, :], sc, NEG_INF)
            w = jax.nn.softmax(sc.astype(jnp.float32), -1).astype(x.dtype)
            ctx = jnp.einsum("sht,sthd->shd", w, v_seq).reshape(s, -1)
            x = x + ctx @ params[f"l{i}.wo"]
            x = self._mlp(params, i, x)
        logits = _rms(x, params["lnf"]) @ params["unembed"]
        next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
        return k_pages, v_pages, next_tok
