"""Serving fleet membership: replica leases, piggybacked health, the agent.

The router tier (ISSUE 15) goes wide the way the master plane went elastic:
N `ServingServer` replicas (each possibly `--tp`) sit behind one router, and
every signal the router needs to dispatch — liveness, queue depth, free
pages, the load estimator's queue-wait figure, engine-restart count — rides
traffic that already flows, never a per-decision round trip ("RPC Considered
Harmful", PAPERS.md):

  * a replica REGISTERS with the router (`replica_register`, advertising its
    serving endpoint) and renews the lease with `replica_heartbeat` every
    lease/3, the heartbeat REQUEST carrying a load snapshot straight out of
    `ServingSession.stats()`;
  * the heartbeat REPLY carries the router's control signals back — a
    planned drain order, a "re-register" hint after an eviction the replica
    outlived — exactly the trick the resize drain signal uses on the master
    plane;
  * a WEDGED replica self-fences: the agent's heartbeat loop watches the
    session's progress marker, and an engine that has work but has made no
    progress past `stall_fence_s` (and is not inside a step — first-step jit
    compiles are not wedges) stops claiming liveness, so the router's lease
    expiry is the one arbiter of "alive" and a stalled-but-heartbeating
    replica cannot hold assignments hostage.

This module is the membership half: `Replica` (the router's view of one
replica), `FleetView` (lease + load bookkeeping — no RPCs live here, every
datum arrived piggybacked) and `ReplicaAgent` (the replica-side joiner).
The dispatch/failover/dedup machinery lives in serving/router.py."""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from paddle_tpu.core import stats
from paddle_tpu.runtime.master import (
    EndpointsLike,
    MasterClient,
    parse_endpoints,
)

log = logging.getLogger("paddle_tpu.serving.fleet")

# the load-snapshot keys a replica heartbeat piggybacks (subset of
# ServingSession.stats()): everything the router's least-loaded choice,
# fleet-wide shed AND the autoscaler's pressure signals (cumulative shed /
# deadline-miss counters, ISSUE 17) reason about, nothing more — heartbeats
# stay small and the controller reads the whole fleet with zero new RPCs
LOAD_KEYS = (
    "queue_depth", "active_slots", "max_slots", "free_pages",
    "estimated_queue_wait_s", "engine_restarts", "decode_steps",
    "shed", "deadline_misses",
)


class ReplicaState:
    LIVE = "live"          # holding a lease, assignable
    DRAINING = "draining"  # planned drain: no new assignments, in-flight runs
    DRAINED = "drained"    # drain complete: deregistered cleanly
    EVICTED = "evicted"    # lease expired / connection dead: failed over
    CLOSED = "closed"      # pump shut down; terminal


class Replica:
    """The router's view of one ServingServer replica. All mutation happens
    under the owning Router's lock; this object is pure bookkeeping."""

    def __init__(self, replica_id: str, endpoint: Tuple[str, int],
                 index: int):
        self.replica_id = replica_id
        self.endpoint = (str(endpoint[0]), int(endpoint[1]))
        # registration order: the deterministic tie-break for assignment
        # scoring (replica ids carry a random prefix, so id order is not
        # stable across runs — tests and drills need stable placement)
        self.index = index
        self.state = ReplicaState.LIVE
        self.last_seen = time.monotonic()
        self.load: Dict[str, Any] = {}
        # fleet request ids whose DELIVERY the router still expects from
        # this replica (live assignments; hedging/failover bookkeeping)
        self.outstanding: Set[int] = set()
        # fleet rid -> replica-side rid for every request ever forwarded and
        # not yet answered/cancelled: survives eviction so the pump can keep
        # polling a partitioned replica and catch a LATE winner (which the
        # dedup map drops + counts) instead of going blind at the instant the
        # lease lapses
        self.rids: Dict[int, int] = {}
        self.assigned_total = 0
        self.failovers = 0
        self.late_results_dropped = 0
        self.conn_failures = 0
        # delta-poll cursors (ISSUE 16): fleet rid -> how many tokens of
        # that request THIS replica has already sent us, so each pump cycle
        # re-reads only the unseen suffix. Keyed per replica (a failover
        # target starts at 0 and re-sends the full mirror) and dropped with
        # the rids entry; purely an optimization — a lost cursor just means
        # one full-width reply
        self.poll_cursors: Dict[int, int] = {}
        self.evicted_at: Optional[float] = None
        self.drain_deadline: Optional[float] = None
        # set once the drain completed: the next heartbeat reply tells the
        # agent, which fires its on_drained callback and stops renewing
        self.drained = False

    def view(self) -> Dict[str, Any]:
        return {
            "replica_id": self.replica_id,
            "endpoint": list(self.endpoint),
            "state": self.state,
            "outstanding": len(self.outstanding),
            "assigned_total": self.assigned_total,
            "failovers": self.failovers,
            "late_results_dropped": self.late_results_dropped,
            "load": dict(self.load),
        }


def _score(rep: Replica) -> tuple:
    """Least-loaded ordering key, computed ONLY from piggybacked state and
    the router's own assignment bookkeeping — no RPC per decision. Occupancy
    (what the router has in flight there + what the replica reports queued
    and decoding) normalized by slot width, then the replica's own queue-wait
    estimate, then engine-restart count (a flapping replica loses ties), then
    registration order for determinism."""
    load = rep.load
    slots = max(1, int(load.get("max_slots", 1) or 1))
    occupancy = (
        len(rep.outstanding)
        + int(load.get("queue_depth", 0) or 0)
        + int(load.get("active_slots", 0) or 0)
    )
    return (
        occupancy / slots,
        float(load.get("estimated_queue_wait_s", 0.0) or 0.0),
        int(load.get("engine_restarts", 0) or 0),
        rep.index,
    )


class FleetView:
    """Replica membership + load bookkeeping for the router.

    The serving-tenant `_Membership` idiom applied to replicas: register
    mints a lease, heartbeats renew it, silence past `lease_s` is eviction.
    No RPCs happen here — every datum arrived piggybacked on a replica
    heartbeat or on the router's own dispatch path."""

    def __init__(self, lease_s: float = 5.0):
        self.lease_s = float(lease_s)
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._prefix = uuid.uuid4().hex[:6]
        self._next = 0
        self.evicted_total = 0

    def register(self, endpoint: Tuple[str, int]) -> Replica:
        with self._lock:
            rep = Replica(
                f"rep-{self._prefix}-{self._next}", endpoint, self._next
            )
            self._next += 1
            self._replicas[rep.replica_id] = rep
            return rep

    def heartbeat(self, replica_id: Optional[str],
                  load: Optional[Dict[str, Any]]) -> Optional[Replica]:
        """Renew a lease + absorb the piggybacked load snapshot. Returns the
        replica, or None for an id this fleet does not hold a live lease for
        (evicted/unknown — the caller's reply tells the agent to
        re-register; adopt-on-sight would resurrect a replica the router
        already failed over, aliasing late results with live ones)."""
        if not replica_id:
            return None
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.state not in (
                ReplicaState.LIVE, ReplicaState.DRAINING
            ):
                return rep  # caller inspects state (drained vs unknown)
            rep.last_seen = time.monotonic()
            if load:
                rep.load = {k: load[k] for k in LOAD_KEYS if k in load}
            return rep

    def get(self, replica_id: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(replica_id)

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def live(self) -> List[Replica]:
        with self._lock:
            return [
                r for r in self._replicas.values()
                if r.state == ReplicaState.LIVE
            ]

    def expired(self, now: Optional[float] = None) -> List[Replica]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return [
                r for r in self._replicas.values()
                if r.state in (ReplicaState.LIVE, ReplicaState.DRAINING)
                and now - r.last_seen > self.lease_s
            ]

    # occupancy slack the affinity preference may cost: the affine replica
    # wins while its occupancy-per-slot is within this much of the
    # least-loaded choice, so warm-prefix placement never piles a hot
    # prompt onto an already-saturated replica
    AFFINITY_SLACK = 0.25

    def choose(self, exclude: Set[str] = frozenset(),
               prefer: Optional[str] = None) -> Optional[Replica]:
        """The least-loaded LIVE replica (None when none) — pure piggybacked
        state, deterministic tie-breaks; see _score.

        Prefix affinity (ISSUE 20 / ROADMAP 2a): with `prefer` naming a
        replica, that replica wins while it is LIVE, not excluded, and its
        occupancy is within AFFINITY_SLACK of the least-loaded candidate —
        multi-turn traffic sharing a prompt head lands on the replica whose
        prefix cache is already warm. A dead/evicted/overloaded preferred
        replica degrades to the plain least-loaded choice (failover keeps
        working because the preference is a hint, never a constraint)."""
        with self._lock:
            candidates = [
                r for r in self._replicas.values()
                if r.state == ReplicaState.LIVE
                and r.replica_id not in exclude
            ]
        if not candidates:
            return None
        best = min(candidates, key=_score)
        if prefer is not None and prefer != best.replica_id:
            for r in candidates:
                if (r.replica_id == prefer
                        and _score(r)[0] <= _score(best)[0]
                        + self.AFFINITY_SLACK):
                    return r
        return best


class ReplicaAgent:
    """Replica-side fleet joiner: registers this ServingServer with the
    router and renews the lease with load-snapshot heartbeats.

    Self-fencing (the wedge story): each tick reads the session's progress
    marker; an engine that HAS work but has made no progress for longer than
    `stall_fence_s` while sitting between steps stops heartbeating — a
    wedged replica must not claim liveness, so the router's lease expiry
    fails its requests over to a survivor. When the wedge clears (the PR-10
    supervisor recovered it, or the stall simply passed) heartbeats resume;
    an evicted-then-healed replica is told to RE-REGISTER and rejoins under
    a fresh lease, while its old pump connection lets any late results it
    still produces reach the router's dedup map (dropped + counted).

    Router HA (ISSUE 18): `router_endpoints` may list a primary AND a warm
    standby. The agent manages rotation ITSELF (one single-endpoint client
    at a time, not MasterClient's internal list rotation) so that every
    control hint in a reply — `reregister`, `drain` — is provably from the
    endpoint the agent just spoke to and is honored against THAT endpoint;
    the old arrangement could race a reregister hint into a registration
    against the dead primary. Replies carry the router's per-incarnation
    `instance` token; a hint from a FOREIGN incarnation is obeyed only when
    this agent's registered incarnation is provably gone (its endpoint
    re-bound by the new incarnation, or unreachable past ROTATE_AFTER
    consecutive failures) — otherwise it is a stale reply from a
    partitioned old primary, counted and dropped (instance-token fencing,
    the double-takeover guard)."""

    # consecutive connection failures against the REGISTERED endpoint
    # before the agent concludes its router is gone and rotates
    ROTATE_AFTER = 2

    def __init__(
        self,
        router_endpoints: EndpointsLike,
        session,
        advertise: Tuple[str, int],
        client_kw: Optional[dict] = None,
        stall_fence_s: float = 5.0,
        on_drained: Optional[Callable[[], None]] = None,
    ):
        self._eps = parse_endpoints(router_endpoints)
        self._cur = 0
        self._client_kw = dict(client_kw or {"timeout": 5.0, "retries": 3})
        self._client = MasterClient(self._eps[self._cur], **self._client_kw)
        # which router incarnation + endpoint index holds our registration
        self.router_instance: Optional[str] = None
        self._reg_ep: Optional[int] = None
        self._conn_failures = 0
        self.rotations = 0
        self.stale_replies = 0
        self.session = session
        self.advertise = (str(advertise[0]), int(advertise[1]))
        self.stall_fence_s = float(stall_fence_s)
        self.on_drained = on_drained
        self.replica_id: Optional[str] = None
        self.lease_s = 5.0
        self.fenced_heartbeats = 0
        self._last_marker: Optional[tuple] = None
        self._last_change = time.monotonic()
        self._evt = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="replica-agent", daemon=True
        )

    # -- health -------------------------------------------------------------
    def _healthy(self, now: float) -> bool:
        """False only for a genuine wedge: work pending, the engine parked
        BETWEEN steps (an in-flight step may be a multi-second first
        compile), and no progress past the fence window."""
        s = self.session
        if s is None:
            return True
        marker = s.progress_marker()
        if marker != self._last_marker:
            self._last_marker = marker
            self._last_change = now
            return True
        if not s.scheduler.has_work() or s._engine_in_step:
            self._last_change = now
            return True
        return (now - self._last_change) <= self.stall_fence_s

    def _load_snapshot(self) -> Dict[str, Any]:
        if self.session is None:
            return {}
        st = self.session.stats()
        return {k: st[k] for k in LOAD_KEYS if k in st}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ReplicaAgent":
        self._register()
        self._thread.start()
        return self

    def _rotate(self) -> None:
        """Move to the next router endpoint (no-op for a single-endpoint
        list): close the current single-endpoint client and open the next."""
        if len(self._eps) <= 1:
            return
        self._client.close()
        self._cur = (self._cur + 1) % len(self._eps)
        self._client = MasterClient(self._eps[self._cur], **self._client_kw)
        self.rotations += 1
        stats.FT_EVENTS.incr("replica_router_rotate")
        log.warning("replica agent rotating to router endpoint %s:%d",
                    *self._eps[self._cur])

    def _note_conn_failure(self) -> None:
        self._conn_failures += 1
        # unregistered, any live router will do — rotate on the first
        # failure; registered, stay pinned to our router until its death is
        # confirmed (ROTATE_AFTER strikes), so one transient hiccup cannot
        # hand control hints to a different incarnation
        threshold = 1 if self.replica_id is None else self.ROTATE_AFTER
        if self._conn_failures >= threshold:
            self._rotate()

    def _register(self) -> bool:
        try:
            resp = self._client.call(
                "replica_register",
                endpoint=list(self.advertise),
                load=self._load_snapshot(),
            )
        except ConnectionError as e:
            # the router being down must not kill the replica: it keeps
            # serving direct traffic and the heartbeat loop keeps trying
            log.warning("replica register with router failed (%s); retrying "
                        "from the heartbeat loop", e)
            self._note_conn_failure()
            return False
        if "replica_id" not in resp:
            log.warning("router refused replica registration: %r", resp)
            return False
        self.replica_id = resp["replica_id"]
        self.lease_s = float(resp.get("lease_s", 5.0))
        self.router_instance = resp.get("instance")
        self._reg_ep = self._cur
        self._conn_failures = 0
        stats.FT_EVENTS.incr("replica_registered")
        return True

    def _handle_reply(self, resp: dict) -> Optional[str]:
        """Fold one heartbeat reply into agent state. Returns 'drained' when
        the agent should stop renewing, else None. Split out of the loop so
        the fencing decisions are drivable by tests without sockets."""
        inst = resp.get("instance")
        foreign = (
            inst is not None and self.router_instance is not None
            and inst != self.router_instance
        )
        if foreign:
            at_home = self._reg_ep is not None and self._cur == self._reg_ep
            lost_home = self._conn_failures >= self.ROTATE_AFTER
            if not (at_home or lost_home):
                # instance-token fencing (the double-takeover guard): a
                # DIFFERENT router incarnation answered while our own was
                # last known reachable — a stale/partitioned old primary.
                # Ignore its hints and go home; only our incarnation's
                # death (port re-bound, or unreachable past the threshold)
                # makes a foreign hint actionable.
                self.stale_replies += 1
                stats.FT_EVENTS.incr("replica_stale_router_reply")
                if self._reg_ep is not None and self._cur != self._reg_ep:
                    self._client.close()
                    self._cur = self._reg_ep
                    self._client = MasterClient(
                        self._eps[self._cur], **self._client_kw
                    )
                return None
            # our incarnation is gone: whatever this reply says, a fresh
            # registration against the endpoint that ANSWERED is the move
            self.replica_id = None
            stats.FT_EVENTS.incr("replica_reregister")
            self._register()
            return None
        self._conn_failures = 0
        if resp.get("drained"):
            # planned drain completed router-side: deregistered; tell
            # the operator hook and stop renewing
            if self.on_drained is not None:
                try:
                    self.on_drained()
                except Exception:
                    log.exception("on_drained callback failed")
            return "drained"
        if resp.get("reregister"):
            # the router evicted this lease (we were wedged/partitioned
            # past it) and we outlived the verdict: rejoin fresh — the
            # old id stays dead so late results stay distinguishable.
            # The registration goes through self._client, i.e. against
            # the endpoint that ISSUED this hint — a concurrent failover
            # can no longer race it onto a dead primary.
            self.replica_id = None
            stats.FT_EVENTS.incr("replica_reregister")
            self._register()
        return None

    def _loop(self) -> None:
        while True:
            period = max(0.05, self.lease_s / 3.0)
            if self._evt.wait(period):
                return
            now = time.monotonic()
            if not self._healthy(now):
                # self-fence: a wedged engine must not renew the lease —
                # the router's failover story depends on eviction being
                # reachable while the agent thread itself is perfectly alive
                self.fenced_heartbeats += 1
                stats.FT_EVENTS.incr("replica_heartbeat_fenced")
                continue
            if self.replica_id is None:
                self._register()
                continue
            try:
                resp = self._client.call(
                    "replica_heartbeat",
                    replica_id=self.replica_id,
                    load=self._load_snapshot(),
                )
            except ConnectionError:
                stats.FT_EVENTS.incr("replica_heartbeat_lost")
                self._note_conn_failure()
                continue
            if self._handle_reply(resp) == "drained":
                return

    def stop(self) -> None:
        """Clean leave: deregister so the router drops the lease now."""
        self._evt.set()
        self._thread.join(timeout=5.0)
        if self.replica_id is not None:
            try:
                self._client.call(
                    "replica_deregister", replica_id=self.replica_id
                )
            except ConnectionError:
                pass  # lease will simply expire
        self._client.close()

    def kill(self) -> None:
        """Crash semantics (drills): stop heartbeating WITHOUT deregistering
        — the router must discover the death through lease expiry / dead
        connections, exactly like a real process kill."""
        self._evt.set()
        self._client.close()
