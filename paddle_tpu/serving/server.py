"""Serving front-end: the master's request-routing plane, repurposed.

The network layer deliberately reuses `runtime/master.py` machinery instead
of inventing a second RPC stack (ROADMAP item 1 names the master as the
request-routing plane):

  * transport — the same newline-delimited line-JSON TCP protocol;
    `ServingClient` wraps `MasterClient`, inheriting reconnect, endpoint
    failover, bounded backoff + jitter, and the `conn_reset` chaos site.
  * tenancy — `_Membership` register/heartbeat leases: a client `register`s
    for a tenant lease and renews it implicitly on every RPC; a tenant
    silent past the lease is evicted by the reaper and its QUEUED requests
    are cancelled (running sequences finish — their KV work is paid for).
  * quotas — per-tenant token buckets + concurrency caps (quota.py) checked
    at `submit`/`generate` time; a rejection is an RPC-level error naming
    the reason, not a timeout.

Methods: register | heartbeat | deregister | submit | poll | poll_many
(the router pump's one-round-trip batch poll) | cancel |
generate (blocking submit+wait) | stats. A config-driven `GenerationSession`
can ride
alongside the token engine (method `generate_config`) so v1-config golden
models are served by the same long-lived process."""

from __future__ import annotations

import json
import logging
import os
import socketserver
import tempfile
import threading
import uuid
from typing import Any, Dict, Optional

import numpy as np

from paddle_tpu.core import stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.runtime import frames
from paddle_tpu.runtime.master import (
    EndpointsLike,
    MasterClient,
    _Membership,
)
from paddle_tpu.serving.quota import QuotaExceeded
from paddle_tpu.serving.scheduler import RequestHandle
from paddle_tpu.serving.session import ServingSession

log = logging.getLogger("paddle_tpu.serving")


def encode_frame(obj: Any, framed: bool = False) -> bytes:
    """Wire encoding for ONE push-stream frame — the single stream-encode
    seam (ISSUE 16 named it; ISSUE 20 filled in the binary branch). On a
    legacy connection it is the line-JSON framing the request/reply plane
    already speaks; on a negotiated framed connection it delegates to
    `frames.encode_stream`, whose compact delta form costs 4 bytes per
    token plus a 20-byte header instead of a JSON object per frame."""
    if framed:
        return frames.encode_stream(obj)
    return json.dumps(obj).encode() + b"\n"


# Coalescing rules for the FRAMED push wire (ISSUE 20): under fan-out the
# per-stream header cost dominates, so a pusher holding a small delta waits
# a few engine steps for more tokens before emitting — one frame, one
# header, many tokens. Below the fan-out threshold latency wins and every
# delta flushes immediately; `done` frames ALWAYS flush; the legacy
# line-JSON wire is never held (its cadence must stay bit-for-bit what
# pre-frames clients observed).
COALESCE_FANOUT = 8      # active pushers at/above which coalescing arms
COALESCE_MIN_TOKENS = 8  # target tokens per binary delta under fan-out
COALESCE_MAX_HOLDS = 7   # engine steps a partial delta may be held


def clamp_cursor(val: Any, n: int) -> int:
    """Clamp a client-supplied delta-poll/stream cursor into [0, n]: a
    stale, negative or garbage cursor degrades to a bigger (or full)
    token suffix, never an error or an out-of-range slice."""
    try:
        c = int(val or 0)
    except (TypeError, ValueError):
        return 0
    return max(0, min(c, n))


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv: ServingServer = self.server.ctx  # type: ignore[attr-defined]
        for line in self.rfile:
            if getattr(srv, "_killed", False):
                # crash semantics: server_close() only shuts the listener —
                # a killed process must also stop answering on established
                # connections, or a standby's clients would never notice
                # the primary died (they'd keep heartbeating a ghost)
                break
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                self._reply({"err": "bad json"})
                continue
            if req.get("method") == "_hello":
                # wire negotiation (ISSUE 20) — deliberately line-JSON: a
                # frame-capable client probes, this connection upgrades to
                # the framed loop; a legacy client never sends the probe
                # and is served bit-for-bit by this unchanged line path
                if req.get("frames") == 1:
                    self._reply({"frames": 1})
                    self._serve_frames(srv)
                    return
                self._reply({"frames": 0})
                continue
            resp, stream = self._dispatch(srv, req)
            self._reply(resp)
            if stream is not None:
                # push mode: this connection becomes a frame stream for one
                # request (until its final frame, then the read loop resumes)
                self._push_frames(srv, *stream)

    def _dispatch(self, srv: Any, req: dict) -> tuple:
        tenant_id = req.get("tenant_id")
        srv.membership.note_seen(tenant_id)
        try:
            # handler span adopts the client's piggybacked trace context
            # (ServingClient rides on MasterClient, which injects
            # `_trace`) — and is itself the parent the session's
            # queue-wait/prefill/ttft spans stitch under
            with obs_trace.server_span(
                "rpc." + str(req.get("method")), req.get("_trace"),
                side="server",
            ):
                resp = srv.dispatch(req.get("method"), req, tenant_id)
        except QuotaExceeded as e:
            resp = {"err": str(e), "rejected": e.reason}
            if getattr(e, "retry_after_ms", None) is not None:
                # load-shed hint: when retrying could plausibly succeed,
                # derived from queue wait + free-page pressure
                resp["retry_after_ms"] = e.retry_after_ms
        except Exception as e:  # a bad request must not kill the server
            log.warning("serving RPC failed: %r", e)
            resp = {"err": f"{type(e).__name__}: {e}"}
        stream = (
            resp.pop("_stream", None) if isinstance(resp, dict) else None
        )
        return resp, stream

    def _serve_frames(self, srv: Any) -> None:
        """Framed loop for one negotiated connection: same dispatch, but
        replies are frames with token runs packed binary, and push streams
        cut compact binary deltas instead of JSON lines."""
        while not getattr(srv, "_killed", False):
            try:
                got = frames.read_frame(self.rfile)
            except frames.FrameError as e:
                # a malformed frame severs THIS connection with a named
                # error instead of wedging the handler thread mid-read
                self._reply_frame({"err": f"{type(e).__name__}: {e}"}, 0, 0)
                return
            except OSError:
                return
            if got is None:
                return
            obj, rid, flags, blob = got
            req = frames.decode_payload(obj, rid, flags, blob)
            resp, stream = self._dispatch(srv, req)
            rflags = 0
            bin_out = b""
            if isinstance(resp, dict):
                resp, bin_out = frames.pack_tokens(resp)
                if bin_out:
                    rflags |= frames.FLAG_BIN_TOKENS
            self._reply_frame(resp, rid, rflags, bin_out)
            if stream is not None:
                self._push_frames(srv, *stream, framed=True)

    def _reply_frame(self, obj: Any, req_id: int, flags: int,
                     bin_payload: bytes = b"") -> None:
        try:
            frames.write_frame(
                self.wfile, obj, req_id=req_id, flags=flags,
                bin_payload=bin_payload,
            )
        except (OSError, ValueError):
            pass  # peer vanished; its retry path handles it

    def _reply(self, obj: Any) -> None:
        try:
            self.wfile.write(json.dumps(obj).encode() + b"\n")
            self.wfile.flush()
        except (OSError, ValueError):
            pass  # peer vanished; its retry path handles it

    def _push_frames(self, srv: Any, handle: Any, cursor: int,
                     framed: bool = False) -> None:
        """Push token frames for one request until it finishes or the peer
        vanishes. Frames are DELTAS from `cursor` (the same cursor contract
        delta-poll uses, so a reattach after a dropped connection resumes
        mid-stream without re-sending tokens). All socket writes happen on
        THIS handler thread — the engine only bumps a step sequence
        (`stream_wait`); a slow or dead client stalls its own pusher, never
        a decode step. Polling the same request stays authoritative: a
        stream is a fast path, not the source of truth."""
        seq = 0
        held = 0
        grown = cursor  # high-water mark: counts THIS stream's decode steps,
        # not global wakes (every pusher shares one notify sequence)
        srv.note_stream(1)
        try:
            while True:
                next_seq = srv.stream_wait(seq)
                # done BEFORE tokens: completion is latched after the final
                # append, so a True here guarantees the token read is complete
                # (the reverse order could stamp `done` on a truncated frame)
                done = handle.done
                toks = list(handle.tokens)
                n = len(toks)
                if n > cursor or done:
                    delta = n - cursor
                    if (framed and not done
                            and delta < COALESCE_MIN_TOKENS
                            and held < COALESCE_MAX_HOLDS
                            and srv.stream_active >= COALESCE_FANOUT):
                        if n > grown:
                            held += 1
                            grown = n
                        seq = next_seq
                        continue
                    held = 0
                    grown = n
                    frame = {
                        "request_id": handle.request_id,
                        "from": cursor,
                        "tokens": toks[cursor:],
                        "tokens_so_far": n,
                    }
                    cursor = n
                    if done:
                        frame.update(srv._stream_final(handle))
                    buf = encode_frame(frame, framed)
                    try:
                        self.wfile.write(buf)
                        self.wfile.flush()
                    except (OSError, ValueError):
                        # peer went away; poll/reattach picks it back up
                        return
                    # coalescing observability (ISSUE 20): a multi-token
                    # delta IS the coalesced frame — a subscriber that fell
                    # behind (or was held under fan-out) gets the whole
                    # backlog in one frame, one encode
                    srv.note_frames(1, nbytes=len(buf), ntokens=delta,
                                    coalesced=1 if delta > 1 else 0)
                    if done:
                        return
                seq = next_seq
        finally:
            srv.note_stream(-1)


class ServingServer:
    """Threaded TCP wrapper around a ServingSession (and optionally a
    config-driven GenerationSession). start()/stop(); port 0 picks a free
    port — the master's in-process-localhost idiom."""

    def __init__(
        self,
        session: Optional[ServingSession] = None,
        gen_session=None,  # trainer.generation.GenerationSession
        host: str = "127.0.0.1",
        port: int = 0,
        lease_s: float = 30.0,
        require_register: bool = False,
        handle_ttl_s: float = 600.0,
        master_endpoints: Optional[EndpointsLike] = None,
        router_endpoints: Optional[EndpointsLike] = None,
        advertise_host: Optional[str] = None,
        stall_fence_s: float = 5.0,
        on_drained=None,
    ):
        if session is None and gen_session is None:
            raise ValueError("need a ServingSession and/or a GenerationSession")
        self.session = session
        self.gen_session = gen_session
        # control-plane visibility: with master_endpoints set, stats()
        # forwards the routing master's health (snapshot failures, lease
        # evictions, live/evicted trainers) so a serving deployment sees
        # control-plane degradation from the same endpoint it already polls
        self.master_endpoints = master_endpoints
        self._master_client: Optional[MasterClient] = None
        self._master_client_lock = threading.Lock()
        # (monotonic, result) of the last probe: stats() calls are served
        # concurrently (ThreadingTCPServer), and a DOWN master costs ~10s of
        # retries per probe — at most one probe is ever in flight, everyone
        # else reads the cached view instead of queueing behind the lock
        self._master_health_cache: tuple = (0.0, None)
        self._master_health_ttl_s = 2.0
        self.membership = _Membership(lease_s)
        self.require_register = require_register
        # ids THIS server minted via register: require_register must check
        # against these, not membership — note_seen adopts any id on sight
        # (the master's retry-exact discipline), so a fabricated tenant_id
        # would otherwise pass as registered and mint itself a fresh quota
        # bucket per request
        self._minted: set = set()
        self._minted_lock = threading.Lock()
        # finished handles are garbage-collected this long after completion
        # (submit-and-vanish clients must not grow a long-lived server; poll
        # is deliberately NON-destructive so the retrying transport can
        # re-read a completion whose response was lost on the wire)
        self.handle_ttl_s = float(handle_ttl_s)
        self._handles: Dict[int, RequestHandle] = {}
        # client-supplied idempotency keys, scoped (tenant, key): a retried
        # submit/generate with the same client_req_id reattaches to the
        # ORIGINAL request instead of queueing (and quota-charging) a
        # duplicate — the transport is MasterClient, whose whole contract is
        # retry-with-reconnect
        self._by_client_id: Dict[tuple, int] = {}
        self._handles_lock = threading.Lock()
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.ctx = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._reaper: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._gen_lock = threading.Lock()
        # fleet mode (ISSUE 15): with router_endpoints set, start() joins the
        # router fleet as a replica — a ReplicaAgent registers this server's
        # serving endpoint and renews the lease with load-snapshot heartbeats
        # (self-fencing when the engine wedges, serving/fleet.py)
        self.router_endpoints = router_endpoints
        self.advertise_host = advertise_host
        self.stall_fence_s = float(stall_fence_s)
        # autoscaler drain lever (ISSUE 17): fired by the replica agent when
        # a router-ordered planned drain completes — the spawn/drain
        # lifecycle hook (the serve CLI's --exit_on_drain shuts the process
        # down here, releasing the chip the controller reclaimed)
        self.on_drained = on_drained
        self._agent = None
        self._killed = False
        # push-streaming observability: frames written by pusher threads
        # (exported via stats + the obs counter; the engine never writes).
        # bytes/tokens/coalesced feed the bench's bytes-per-delivered-token
        # and coalescing-rate views (ISSUE 20)
        self.stream_frames = 0
        self.stream_bytes = 0
        self.stream_tokens = 0
        self.stream_coalesced = 0
        self.stream_active = 0  # pushers currently attached (fan-out gauge)
        self._stream_lock = threading.Lock()

    @property
    def address(self) -> tuple:
        return self._srv.server_address

    # -- RPC dispatch -------------------------------------------------------
    def dispatch(self, method: str, req: dict, tenant_id: Optional[str]) -> dict:
        if method == "register":
            tid = self.membership.register()
            with self._minted_lock:
                self._minted.add(tid)
            return {"tenant_id": tid, "lease_s": self.membership.lease_s}
        if method == "heartbeat":
            return {"ok": bool(tenant_id)}
        if method == "deregister":
            if tenant_id:
                self._forget_tenant(tenant_id)
            return {"ok": bool(tenant_id)}
        if method == "stats":
            out = dict(self.session.stats()) if self.session else {}
            out["live_tenants"] = self.membership.live
            out["evicted_tenants"] = self.membership.evicted
            out["stream_frames_pushed"] = self.stream_frames
            out["stream_bytes_pushed"] = self.stream_bytes
            out["stream_tokens_pushed"] = self.stream_tokens
            out["stream_frames_coalesced"] = self.stream_coalesced
            if self.master_endpoints is not None:
                out["master"] = self._master_health()
            return out
        if method == "metrics":
            return {"text": obs_metrics.to_prometheus_text()}
        if method == "trace_export":
            return {"chrome_trace": obs_trace.export_chrome()}
        if method in ("submit", "generate"):
            if self.session is None:
                return {
                    "err": "no token engine on this server (started with "
                    "--config only); use generate_config"
                }
            tenant = self._tenant_for(tenant_id)
            # idempotency keys are scoped PER TENANT: two tenants using the
            # same key must not alias (that would hand one tenant the other's
            # tokens — the same leak the poll tenancy check closes)
            client_req_id = req.get("client_req_id")
            req_key = (tenant, str(client_req_id)) if client_req_id else None
            handle = None
            if req_key is not None:
                with self._handles_lock:
                    rid = self._by_client_id.get(req_key)
                    handle = self._handles.get(rid) if rid is not None else None
            if handle is None:
                handle = self.session.submit(
                    req["prompt"],
                    req.get("max_new_tokens"),
                    tenant=tenant,
                    deadline_s=req.get("deadline_s"),
                    ttft_deadline_s=req.get("ttft_deadline_s"),
                    temperature=req.get("temperature"),
                    top_k=req.get("top_k"),
                    seed=req.get("seed"),
                )
                with self._handles_lock:
                    self._handles[handle.request_id] = handle
                    if req_key is not None:
                        self._by_client_id[req_key] = handle.request_id
            if method == "submit":
                out: Dict[str, Any] = {"request_id": handle.request_id}
                if req.get("stream"):
                    # opt-in push streaming (ISSUE 16): the ack carries the
                    # request id as usual, then token frames follow on this
                    # SAME connection until the final frame — submit and
                    # first-frame latency share one round trip
                    out["stream"] = True
                    out["_stream"] = (handle, 0)
                return out
            try:
                # cancel_on_timeout=False: the blocking-generate contract is
                # "still running; poll request_id later" — the caller chose
                # to wait, not to abandon (ServingClient abandonment goes
                # through result()'s default cancel path / the cancel RPC)
                handle.result(timeout=float(req.get("timeout_s", 120.0)),
                              cancel_on_timeout=False)
            except TimeoutError:
                # the request keeps running; the handle stays registered so
                # the caller can poll for the tokens it already paid for
                return {
                    "err": "generate timed out server-side; still running",
                    "request_id": handle.request_id,
                    "done": False,
                }
            except RuntimeError:
                pass  # cancelled: _completion below names the reason
            return dict(self._completion(handle),
                        request_id=handle.request_id)
        if method in ("poll", "cancel", "stream"):
            with self._handles_lock:
                if req.get("client_req_id"):
                    # identity is the (tenant, client_req_id) key, NOT the
                    # rid (ISSUE 18): across a server restart or router
                    # takeover the rid counter restarted, so a stale rid may
                    # name a DIFFERENT request — never fall back to it when
                    # the caller supplied its key. Keys are GC'd together
                    # with their handles, so a key miss means the request
                    # is not in these books.
                    rid = self._by_client_id.get(
                        (self._tenant_for(tenant_id),
                         str(req["client_req_id"]))
                    )
                    handle = (
                        self._handles.get(rid) if rid is not None else None
                    )
                else:
                    handle = self._handles.get(int(req["request_id"]))
            if handle is None:
                return {"err": f"unknown request_id {req['request_id']}"}
            # request ids are sequential — poll/cancel/stream must enforce
            # the SAME tenancy as submit, or guessing ids reads (or kills)
            # other tenants' requests
            if handle.tenant != self._tenant_for(tenant_id):
                return {"err": "request belongs to another tenant"}
            if method == "cancel":
                return {"cancelled": handle.cancel(), "done": handle.done}
            if method == "stream":
                # (re)attach a push stream mid-request: the client's `from`
                # cursor (tokens it already holds) resumes the frame stream
                # exactly where a dropped connection left off
                cur = clamp_cursor(req.get("from"), len(handle.tokens))
                return {
                    "request_id": handle.request_id, "stream": True,
                    "from": cur, "_stream": (handle, cur),
                }
            if not handle.done:
                # incremental delivery: the tokens generated SO FAR ride
                # every poll, from the client's `from` cursor on — a
                # delta-poll re-sends only the unseen suffix (`from` absent
                # = 0 = today's full-list reply, bit-for-bit)
                toks = list(handle.tokens)
                cur = clamp_cursor(req.get("from"), len(toks))
                return {
                    "done": False,
                    "tokens_so_far": len(toks),
                    "tokens": toks[cur:],
                    "from": cur,
                }
            # non-destructive: a lost response must be re-readable; the
            # reaper GCs finished handles after handle_ttl_s
            return self._completion(handle)
        if method == "poll_many":
            # the router pump's batch poll (ISSUE 15): ONE round trip answers
            # for every in-flight request on this replica, so result delivery
            # never costs an RPC per request per cycle ("RPC Considered
            # Harmful" — and the shape ROADMAP item 4's batched control
            # plane generalizes). Per-item tenancy: the router is a proxy
            # for many tenants, so each item names the tenant it polls as.
            out = []
            for it in req.get("items", []):
                try:
                    rid = int(it["request_id"])
                except (KeyError, TypeError, ValueError):
                    out.append({"err": "bad request_id"})
                    continue
                with self._handles_lock:
                    handle = self._handles.get(rid)
                if handle is None:
                    out.append({"request_id": rid, "err": "unknown"})
                elif handle.tenant != self._tenant_for(it.get("tenant_id")):
                    out.append({"request_id": rid, "err": "tenant"})
                elif handle.done:
                    # completions stay FULL-token replies (no cursor): the
                    # terminal result is the authoritative record the
                    # router's dedup latch delivers exactly once
                    out.append(dict(self._completion(handle),
                                    request_id=rid))
                else:
                    toks = list(handle.tokens)
                    cur = clamp_cursor(it.get("from"), len(toks))
                    out.append({
                        "request_id": rid, "done": False,
                        "tokens": toks[cur:], "from": cur,
                        "tokens_so_far": len(toks),
                    })
            return {"results": out}
        if method == "outstanding":
            # the takeover sweep (ISSUE 18): a freshly-elected router asks
            # each re-registering replica for every keyed request it still
            # holds — in flight AND finished-but-unpolled (server-held
            # results the dead router never delivered). The reply carries
            # the full re-submission identity (prompt, pinned seed,
            # sampling knobs), so the new router can rebuild its dedup/
            # in-flight books from the data plane and fail a request over
            # token-identically if THIS replica dies too. Cold path: one
            # call per replica registration event, never per poll cycle.
            out = []
            with self._handles_lock:
                keyed = [
                    (tenant, key, rid)
                    for (tenant, key), rid in self._by_client_id.items()
                ]
            for tenant, key, rid in keyed:
                with self._handles_lock:
                    handle = self._handles.get(rid)
                if handle is None:
                    continue
                out.append({
                    "request_id": rid,
                    "tenant_id": tenant,
                    "client_req_id": key,
                    "prompt": [int(t) for t in
                               getattr(handle, "prompt_tokens", None) or []],
                    "max_new_tokens": handle.max_new_tokens,
                    "seed": handle.seed,
                    "temperature": handle.temperature,
                    "top_k": handle.top_k,
                    "done": handle.done,
                    "tokens_so_far": len(handle.tokens),
                })
            return {"requests": out}
        if method == "generate_config":
            return self._generate_config(req)
        return {"err": f"unknown method {method!r}"}

    def _tenant_for(self, tenant_id: Optional[str]) -> str:
        if self.require_register:
            with self._minted_lock:
                known = tenant_id in self._minted
            if not known:
                # a fabricated or expired id must not pass: each unknown id
                # would mint itself a fresh full quota bucket
                raise QuotaExceeded(
                    "register first: this server requires a live tenant "
                    "lease (unknown or expired tenant_id)",
                    "unregistered",
                )
            return tenant_id
        return tenant_id or "default"

    def _master_health(self) -> dict:
        """The underlying routing master's control-plane health, forwarded
        into stats(). Unreachability is itself the signal — reported, never
        raised (a dead master must not take the serving stats down too).
        TTL-cached, single probe in flight: concurrent stats() callers read
        the last view instead of serializing behind a dead master's retries."""
        import time as _time

        ts, cached = self._master_health_cache
        if cached is not None and _time.monotonic() - ts < self._master_health_ttl_s:
            return cached
        if not self._master_client_lock.acquire(blocking=False):
            # another thread is probing right now — serve the stale view
            if cached is not None:
                return cached
            return {"reachable": False, "error": "health probe in flight"}
        try:
            try:
                if self._master_client is None:
                    self._master_client = MasterClient(
                        self.master_endpoints, timeout=5.0, retries=2,
                    )
                st = self._master_client.call("stats")
            except (ConnectionError, OSError) as e:
                out = {
                    "reachable": False,
                    "error": f"{type(e).__name__}: {e}"[-300:],
                }
            else:
                out = {
                    k: st[k]
                    for k in (
                        "snapshot_failures", "live_trainers",
                        "evicted_trainers", "todo", "pending", "done",
                        "discarded",
                    )
                    if k in st
                }
                out["reachable"] = True
        finally:
            self._master_client_lock.release()
        self._master_health_cache = (_time.monotonic(), out)
        return out

    def _forget_tenant(self, tid: str) -> int:
        """Drop a tenant's lease + minted id and cancel its queued work
        (deregister and lease-expiry share this path)."""
        self.membership.drop(tid)
        with self._minted_lock:
            self._minted.discard(tid)
        return self.session.cancel_tenant(tid) if self.session else 0

    @staticmethod
    def _completion(handle: RequestHandle) -> dict:
        return {
            "done": True,
            "tokens": handle.tokens,
            "finish_reason": handle.finish_reason,
            "cancelled": handle.status == RequestHandle.CANCELLED,
        }

    # -- push-stream plumbing (shared with _Handler._push_frames) -----------
    def stream_wait(self, seq: int, timeout: float = 0.25) -> int:
        """Pusher-thread wait for the next engine step boundary (delegates
        to the session's step-sequence condition; the timeout doubles as
        the liveness tick for cancellations that finish without a step)."""
        if self.session is None:
            self._stop_evt.wait(timeout)
            return seq
        return self.session.stream_wait(seq, timeout)

    @staticmethod
    def _stream_final(handle: RequestHandle) -> dict:
        """Terminal fields for a stream's final frame — delta-shaped (the
        client accumulated the tokens), completion metadata inline."""
        return {
            "done": True,
            "finish_reason": handle.finish_reason,
            "cancelled": handle.status == RequestHandle.CANCELLED,
        }

    def note_frames(self, n: int, nbytes: int = 0, ntokens: int = 0,
                    coalesced: int = 0) -> None:
        from paddle_tpu.serving.session import SERVING_EVENTS

        with self._stream_lock:
            self.stream_frames += n
            self.stream_bytes += nbytes
            self.stream_tokens += ntokens
            self.stream_coalesced += coalesced
        SERVING_EVENTS.incr("serving_stream_frames", n)

    def note_stream(self, delta: int) -> None:
        with self._stream_lock:
            self.stream_active += delta

    def _generate_config(self, req: dict) -> dict:
        """Whole-request generation against the long-lived GenerationSession
        (built/loaded once at server start — the reentrant capi contract).
        The batch arrives as {name: nested lists}; printer outputs return
        inline as {evaluator: text}."""
        if self.gen_session is None:
            return {"err": "no --config generation session on this server"}
        batch = {k: np.asarray(v) for k, v in req["batch"].items()}
        fd, dest = tempfile.mkstemp(suffix=".gen.txt")
        os.close(fd)
        written: Dict[str, str] = {}
        try:
            # the session is not reentrant per-request (printer result files);
            # serialize — throughput callers use the token engine instead
            with self._gen_lock:
                written = self.gen_session.generate(batch, result_file=dest)
            out = {}
            for name, path in written.items():
                with open(path) as f:
                    out[name] = f.read()
            return {"files": out}
        finally:
            # multi-printer configs fan out to per-evaluator files next to
            # `dest` — clean those up too
            for path in {dest, *written.values()}:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- lifecycle ----------------------------------------------------------
    def _reap_loop(self) -> None:
        import time as _time

        period = max(0.05, min(1.0, self.membership.lease_s / 4.0))
        while not self._stop_evt.wait(period):
            for tid in self.membership.expired():
                self.membership.evicted += 1
                stats.FT_EVENTS.incr("tenant_evicted")
                n = self._forget_tenant(tid)
                log.warning(
                    "tenant %s lease expired (%gs); evicted, %d queued "
                    "request(s) cancelled", tid, self.membership.lease_s, n,
                )
            # GC handles whose client submitted and never polled — a
            # long-lived server must not retain every completion forever
            cutoff = _time.monotonic() - self.handle_ttl_s
            with self._handles_lock:
                stale = [
                    rid for rid, h in self._handles.items()
                    if h.done and (h.t_done or 0) < cutoff
                ]
                for rid in stale:
                    del self._handles[rid]
                if stale:
                    dead = set(stale)
                    self._by_client_id = {
                        k: v for k, v in self._by_client_id.items()
                        if v not in dead
                    }
            if stale:
                log.info("GC'd %d unpolled finished request handle(s)", len(stale))

    def start(self) -> "ServingServer":
        if self.session is not None and self.session._thread is None:
            self.session.serve_forever()
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()
        if self.router_endpoints is not None and self.session is not None:
            from paddle_tpu.serving.fleet import ReplicaAgent

            host, port = self.address
            self._agent = ReplicaAgent(
                self.router_endpoints, self.session,
                advertise=(self.advertise_host or host, port),
                stall_fence_s=self.stall_fence_s,
                on_drained=self.on_drained,
            ).start()
        return self

    def kill(self) -> None:
        """Crash semantics (chaos drills): sever the TCP front-end and the
        fleet heartbeats abruptly — NO deregister, no drain — so the router
        discovers the death the way it would a real process kill: dead
        connections and a lapsed lease. Idempotent; safe before start()."""
        if self._killed:
            return
        self._killed = True
        self._stop_evt.set()
        if self._agent is not None:
            self._agent.kill()

        def _die():
            try:
                if self._thread is not None:
                    self._srv.shutdown()
                self._srv.server_close()
            except OSError:
                pass
            if self.session is not None:
                self.session.stop()

        # sever off-thread: kill() must not block the drill behind the
        # session supervisor's join (MasterServer.kill's idiom)
        threading.Thread(target=_die, daemon=True).start()

    def stop(self) -> None:
        if self._killed:
            return
        self._stop_evt.set()
        if self._agent is not None:
            self._agent.stop()  # clean leave: deregister from the router
        if self._thread is not None:
            self._srv.shutdown()
        self._srv.server_close()
        if self._reaper is not None:
            self._reaper.join(timeout=5.0)
        # non-blocking: an in-flight health probe (up to ~10s against a dead
        # master) must not stall shutdown — its daemon thread's socket dies
        # with the process
        if self._master_client_lock.acquire(blocking=False):
            try:
                if self._master_client is not None:
                    self._master_client.close()
            finally:
                self._master_client_lock.release()
        if self.session is not None:
            self.session.stop()


class Rejected(RuntimeError):
    """A submit/generate the server refused with a named reason; on load
    sheds `retry_after_ms` carries the server's backoff hint."""

    def __init__(self, msg: str, reason: Optional[str] = None,
                 retry_after_ms: Optional[int] = None):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_ms = retry_after_ms


class ServingClient:
    """Ergonomic wrapper over MasterClient (which supplies reconnect,
    failover lists, backoff and the conn_reset chaos site for free).

    MasterClient's contract is retry-with-reconnect, so every mutating call
    carries a client-generated idempotency key (`client_req_id`): a retry
    whose original DID reach the server reattaches to the same request
    instead of queueing and quota-charging a duplicate. `generate` is
    implemented as submit + poll — short retry-exact RPCs — rather than one
    long blocking read that would trip the socket timeout on a loaded
    server. The same dedup key is what makes HEDGING safe: `generate` with
    `hedge_ttft_s` re-issues the submit when no token has arrived by that
    deadline — if the original landed, the server reattaches (exactly one
    engine execution); if it was lost in a partition/failover, the hedge IS
    the request."""

    def __init__(self, address: EndpointsLike, **client_kw):
        # `address` may be a LIST ("primary:p1,standby:p2" or a sequence of
        # endpoints — ISSUE 18): MasterClient rotates on connection failure,
        # so a router primary + warm standby is one constructor argument and
        # every path below (generate/submit/poll/cancel/stream) fails over
        self._client = MasterClient(address, **client_kw)
        self.tenant_id: Optional[str] = None
        self.lease_s: float = 30.0
        # wire accounting for the dedicated stream connections (ISSUE 20):
        # each stream() conn folds its byte/round-trip counters in here when
        # it closes, so a bench can compute bytes per delivered token across
        # the request/reply client AND every push stream it ran
        self.stream_bytes_in = 0
        self.stream_bytes_out = 0
        self.stream_round_trips = 0
        self.hedges = 0  # hedged retries issued (TTFT-deadline misses)
        self.shed_retries = 0  # submits retried after a shed's retry_after_ms
        self.stream_reattaches = 0  # dropped push-streams resumed by cursor
        # submits re-issued under the same key after the server forgot the
        # request id (router takeover, failover to a peer): dedup reattaches
        # when the request still runs anywhere, so this is recovery, not
        # duplication
        self.reattach_resubmits = 0

    def register(self) -> str:
        resp = self._client.call("register")
        self.tenant_id = resp["tenant_id"]
        self.lease_s = float(resp.get("lease_s", 30.0))
        return self.tenant_id

    def _id_kw(self) -> dict:
        return {"tenant_id": self.tenant_id} if self.tenant_id else {}

    def generate(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        timeout_s: float = 120.0,
        poll_interval_s: float = 0.02,
        deadline_s: Optional[float] = None,
        ttft_deadline_s: Optional[float] = None,
        hedge_ttft_s: Optional[float] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        seed: Optional[int] = None,
        max_retries: int = 2,
        retry_sleep_cap_s: float = 2.0,
    ) -> dict:
        import time as _time

        key = uuid.uuid4().hex
        if seed is None:
            # pin the sampling identity CLIENT-side (ISSUE 18): if every
            # server-side holder of this request dies in one window (replica
            # + router), the re-submit under the same key below must re-draw
            # the same tokens — a server-minted seed dies with the server
            seed = int.from_bytes(uuid.uuid4().bytes[:4], "little")
        # sampling identity rides the idempotency envelope: a hedged retry
        # re-submits the SAME (seed, temperature, top_k), so even when the
        # original was lost and the hedge IS the request, tokens match what
        # the original would have produced (seeded per-request sampling)
        kw = dict(deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
                  temperature=temperature, top_k=top_k, seed=seed,
                  client_req_id=key)
        t0 = _time.monotonic()
        deadline = t0 + timeout_s
        # shed → sleep-and-retry: a server shed carrying retry_after_ms is a
        # promise, not a verdict — honor it (capped, and never past the
        # caller's own timeout budget) up to max_retries times before
        # surfacing Rejected. A shed without a hint stays terminal: the
        # server said nothing about when retrying could work.
        attempts = 0
        while True:
            try:
                rid = self.submit(prompt, max_new_tokens, **kw)
                break
            except Rejected as e:
                now = _time.monotonic()
                if (e.retry_after_ms is None or attempts >= max_retries
                        or now >= deadline):
                    raise
                attempts += 1
                self.shed_retries += 1
                _time.sleep(min(
                    e.retry_after_ms / 1e3, retry_sleep_cap_s,
                    max(0.0, deadline - now),
                ))
        hedged = False
        resubmits = 0
        while True:
            resp = self.poll(rid, client_req_id=key)
            if "err" in resp:
                # the server no longer knows rid (failover to a peer, router
                # takeover, handle GC): re-issue the submit under the SAME
                # idempotency key — dedup reattaches when the request still
                # runs anywhere; only a genuinely lost request becomes a
                # fresh one (and the client-pinned seed keeps even THAT
                # token-identical). Bounded: a persistent error surfaces.
                if resubmits >= max(1, max_retries):
                    raise RuntimeError(f"generate failed: {resp['err']}")
                try:
                    rid = self.submit(prompt, max_new_tokens, **kw)
                except Rejected as e:
                    now = _time.monotonic()
                    if e.retry_after_ms is not None and now < deadline:
                        # a SHED, not a verdict: a just-took-over router is
                        # alive before its replicas have re-registered —
                        # honor the hint and retry without burning the
                        # resubmit budget (bounded by the caller's timeout)
                        self.shed_retries += 1
                        _time.sleep(min(e.retry_after_ms / 1e3,
                                        retry_sleep_cap_s,
                                        max(0.0, deadline - now)))
                        continue
                    raise RuntimeError(
                        f"generate failed: {resp['err']} (re-submit under "
                        f"the same key was then rejected: {e})"
                    )
                resubmits += 1
                if hedge_ttft_s is not None and not hedged:
                    hedged = True
                    self.hedges += 1
                else:
                    self.reattach_resubmits += 1
                continue
            if resp.get("done"):
                return resp
            now = _time.monotonic()
            if (hedge_ttft_s is not None and not hedged
                    and not resp.get("tokens_so_far")
                    and now - t0 > hedge_ttft_s):
                # TTFT deadline missed with zero tokens delivered: hedge by
                # re-issuing the submit under the SAME idempotency key. The
                # server's (tenant, client_req_id) dedup reattaches when the
                # original landed — exactly one engine execution — and only
                # a lost original makes this a fresh request.
                hedged = True
                self.hedges += 1
                try:
                    rid = self.submit(prompt, max_new_tokens, **kw)
                except Rejected:
                    pass  # shed hedge: keep polling the original
            if now > deadline:
                raise TimeoutError(
                    f"generate: request {rid} not done after {timeout_s}s "
                    f"({resp.get('tokens_so_far', 0)} tokens so far); poll "
                    f"request_id {rid} to retrieve it later"
                )
            _time.sleep(poll_interval_s)

    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        deadline_s: Optional[float] = None,
        ttft_deadline_s: Optional[float] = None,
        client_req_id: Optional[str] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        seed: Optional[int] = None,
    ) -> int:
        resp = self._client.call(
            "submit", prompt=list(prompt), max_new_tokens=max_new_tokens,
            deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
            temperature=temperature, top_k=top_k, seed=seed,
            client_req_id=client_req_id or uuid.uuid4().hex, **self._id_kw(),
        )
        if "err" in resp:
            raise Rejected(
                f"submit rejected: {resp['err']}",
                reason=resp.get("rejected"),
                retry_after_ms=resp.get("retry_after_ms"),
            )
        return int(resp["request_id"])

    def poll(self, request_id: int, from_: Optional[int] = None,
             client_req_id: Optional[str] = None) -> dict:
        """Poll a request; with `from_` set, the not-done reply carries only
        tokens[from_:] (delta poll — `tokens_so_far` still counts them all,
        and `from` echoes the clamped cursor the suffix starts at). With
        `client_req_id` set the server falls back to resolving the request
        by its (tenant, key) identity when the id is unknown — the identity
        that survives a router takeover."""
        kw: Dict[str, Any] = {"request_id": request_id, **self._id_kw()}
        if from_ is not None:
            kw["from"] = int(from_)
        if client_req_id is not None:
            kw["client_req_id"] = str(client_req_id)
        return self._client.call("poll", **kw)

    def stream(
        self,
        prompt=None,
        max_new_tokens: Optional[int] = None,
        request_id: Optional[int] = None,
        deadline_s: Optional[float] = None,
        ttft_deadline_s: Optional[float] = None,
        client_req_id: Optional[str] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        seed: Optional[int] = None,
        reattach_retries: int = 4,
    ):
        """Push-streaming generator: yields token frames as the server emits
        them (each a dict with the `tokens` delta; the final frame carries
        `done`/`finish_reason`). With `prompt` given this is submit with
        `stream=True` — the ack and the first frame share one connection and
        one round trip; with `request_id` it attaches to an in-flight
        request. Runs on a DEDICATED connection (the request/reply client
        stays usable concurrently). A dropped stream reattaches up to
        `reattach_retries` times via the `stream` RPC with the token cursor,
        so delivered tokens are never re-sent and never lost; the submit
        leg rides the usual idempotency key, so a retried attach after a
        lost ack reattaches to the original request.

        Self-healing across a ROUTER death (ISSUE 18): the dedicated
        connection rotates the endpoint list, the reattach names the
        idempotency key (so the new incarnation resolves the request even
        though its ids restarted), and when the new router doesn't know the
        request at all (its replica died too) the reattach degrades to a
        re-submit under the same key + client-pinned seed. Frames are
        trimmed against the tokens already YIELDED — a takeover target
        whose mirror is still behind may re-send a prefix, and the consumer
        must see every token exactly once."""
        if (prompt is None) == (request_id is None):
            raise ValueError("stream() needs exactly one of prompt/request_id")
        key = client_req_id or uuid.uuid4().hex
        if prompt is not None and seed is None:
            # client-pinned sampling identity (see generate()): survives the
            # every-server-side-holder-died window token-identically
            seed = int.from_bytes(uuid.uuid4().bytes[:4], "little")
        delivered = 0  # tokens this generator has yielded — the one cursor
        failures = 0
        conn = MasterClient(
            self._client.endpoints, timeout=self._client.timeout, retries=2,
            wire=self._client.wire,
        )
        try:
            while True:
                if request_id is None:
                    frames = conn.call_stream(
                        "submit", prompt=list(prompt),
                        max_new_tokens=max_new_tokens, stream=True,
                        deadline_s=deadline_s,
                        ttft_deadline_s=ttft_deadline_s,
                        temperature=temperature, top_k=top_k, seed=seed,
                        client_req_id=key, **self._id_kw(),
                    )
                else:
                    frames = conn.call_stream(
                        "stream", **{"from": delivered},
                        request_id=request_id, client_req_id=key,
                        **self._id_kw(),
                    )
                try:
                    ack = next(frames)
                    if "err" in ack:
                        if (prompt is not None
                                and ack.get("retry_after_ms") is not None):
                            # a shed, not a verdict (e.g. a just-took-over
                            # router whose replicas are still re-joining):
                            # honor the hint within the reattach budget
                            failures += 1
                            if failures > max(0, int(reattach_retries)):
                                raise Rejected(
                                    f"stream rejected: {ack['err']}",
                                    reason=ack.get("rejected"),
                                    retry_after_ms=ack.get("retry_after_ms"),
                                )
                            import time as _time
                            _time.sleep(
                                min(ack["retry_after_ms"] / 1e3, 2.0)
                            )
                            continue
                        if request_id is not None and prompt is not None:
                            # the (possibly new) router knows neither the id
                            # nor the key: the request died with its holders
                            # — re-issue it under the same key; dedup makes
                            # this attach-or-execute, never a duplicate
                            request_id = None
                            self.reattach_resubmits += 1
                            continue
                        raise Rejected(
                            f"stream rejected: {ack['err']}",
                            reason=ack.get("rejected"),
                            retry_after_ms=ack.get("retry_after_ms"),
                        )
                    request_id = int(ack["request_id"])
                    for frame in frames:
                        toks = list(frame.get("tokens") or [])
                        base = int(frame.get("from", delivered))
                        # trim what this generator already yielded: a frame
                        # from a reattached (or takeover) stream may overlap
                        # the delivered prefix — exactly-once to the consumer
                        unseen = toks[max(0, delivered - base):]
                        if unseen or frame.get("done"):
                            out = dict(frame)
                            out["tokens"] = unseen
                            out["from"] = delivered
                            delivered += len(unseen)
                            out["tokens_so_far"] = max(
                                int(frame.get("tokens_so_far", delivered)),
                                delivered,
                            )
                            yield out
                            if out.get("done"):
                                return
                except OSError:
                    # ConnectionError AND recv timeouts: a killed-in-place
                    # router leaves the push socket open but silent — the
                    # cursor makes a spurious-timeout reattach harmless
                    failures += 1
                    if failures > max(0, int(reattach_retries)):
                        raise
                    self.stream_reattaches += 1
                    conn.close()  # reattach from `delivered` on a fresh socket
        finally:
            conn.close()
            self.stream_bytes_in += conn.bytes_received
            self.stream_bytes_out += conn.bytes_sent
            self.stream_round_trips += conn.round_trips

    def cancel(self, request_id: int) -> dict:
        """Cancel a submitted request server-side (pages recycle at the next
        decode-step boundary); idempotent once the request finished."""
        return self._client.call(
            "cancel", request_id=request_id, **self._id_kw()
        )

    def heartbeat(self) -> dict:
        return self._client.call("heartbeat", **self._id_kw())

    def stats(self) -> dict:
        return self._client.call("stats", **self._id_kw())

    def metrics(self) -> str:
        """The server's Prometheus metrics text (the `metrics` RPC)."""
        return self._client.call("metrics", **self._id_kw()).get("text", "")

    def trace_export(self) -> dict:
        """The server's span ring buffer as Chrome trace JSON — merge with
        the local export via obs.trace.merge_chrome for one stitched view."""
        return self._client.call(
            "trace_export", **self._id_kw()
        ).get("chrome_trace", {})

    @property
    def wire_framed(self) -> bool:
        """True once the request/reply connection negotiated binary frames."""
        return self._client.wire_framed

    def wire_totals(self) -> dict:
        """Bytes and round trips this client has spent on the wire — the
        request/reply connection plus every finished push stream (bench
        food: bytes per delivered token, round trips per token)."""
        return {
            "bytes_in": self._client.bytes_received + self.stream_bytes_in,
            "bytes_out": self._client.bytes_sent + self.stream_bytes_out,
            "round_trips": self._client.round_trips + self.stream_round_trips,
        }

    def close(self) -> None:
        self._client.close()
