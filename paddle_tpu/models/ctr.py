"""CTR wide&deep (BASELINE config #4; reference demo/ctr + the sparse
pserver path it exercises, SURVEY §2.5 sparse/EP row).

Wide part: multi-hot sparse feature vector through a linear projection (the
reference's sparse_binary_vector → fc). Deep part: per-slot categorical ids
through embeddings (the row-sharded pserver tables; declare the "expert"
LOGICAL axis via ParamAttr(logical_axes=...) and the rules table decides
which mesh axis — if any — it shards over, the EP-parity path) → MLP.
Output: sigmoid CTR estimate, soft binary cross-entropy loss."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import ParamAttr


def ctr_wide_deep(
    wide_dim: int = 1000,
    slot_vocab_sizes: Sequence[int] = (1000, 1000, 500, 100),
    embed_dim: int = 32,
    hidden_dims: Sequence[int] = (128, 64),
    embedding_sharding: Optional[Tuple] = None,
):
    """Returns (inputs, label, prediction, cost). inputs = [wide_input,
    slot0_ids, slot1_ids, ...]. embedding_sharding is a LOGICAL-axes tuple,
    e.g. ("expert", None): every deep table's rows declare the "expert"
    logical axis, and the deployment's rules table (parallel/rules.py)
    decides whether that shards them (an "expert"-axis mesh) or replicates
    (the data-only CPU mesh) — no mesh-axis names in model code."""
    wide_in = L.Data("wide_features", shape=(wide_dim,))
    slot_ids = [
        L.Data(f"slot{i}_id", shape=()) for i in range(len(slot_vocab_sizes))
    ]
    label = L.Data("click", shape=(1,))

    # wide: linear on the multi-hot vector
    wide = L.Fc(wide_in, 1, act=None, name="wide_lr")

    # deep: embeddings (optionally sharded like the pserver row-shards) + MLP
    embeds = []
    for i, (ids, vocab) in enumerate(zip(slot_ids, slot_vocab_sizes)):
        attr = (
            ParamAttr(logical_axes=tuple(embedding_sharding))
            if embedding_sharding is not None
            else None
        )
        embeds.append(
            L.Embedding(ids, embed_dim, vocab_size=vocab,
                        param_attr=attr, name=f"slot{i}_emb")
        )
    deep = L.Concat(embeds, name="deep_concat")
    for j, h in enumerate(hidden_dims):
        deep = L.Fc(deep, h, act="relu", name=f"deep_fc{j}")
    deep_out = L.Fc(deep, 1, act=None, name="deep_out")

    logit = L.Addto([wide, deep_out], act="sigmoid", name="ctr_prob")
    cost = C.SoftBinaryCrossEntropy(logit, label, name="cost")
    return [wide_in] + slot_ids, label, logit, cost
