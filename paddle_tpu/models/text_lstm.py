"""LSTM text classification — the reference's RNN benchmark
(benchmark/paddle/rnn/rnn.py: embedding + N×lstm + seq-pool + fc softmax;
BASELINE.md LSTM rows)."""

from __future__ import annotations

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.recurrent import simple_lstm
from paddle_tpu.nn.seq_layers import SeqPool


def text_lstm(
    vocab_size: int = 30000,
    embed_dim: int = 128,
    hidden_dim: int = 256,
    num_layers: int = 2,
    num_classes: int = 2,
):
    """Returns (data, label, logits, cost)."""
    ids = L.Data("word_ids", shape=(vocab_size,), is_seq=True)
    label = L.Data("label", shape=())
    x = L.Embedding(ids, embed_dim, vocab_size=vocab_size, name="emb")
    for i in range(num_layers):
        x = simple_lstm(x, hidden_dim, name=f"lstm{i}")
    pooled = SeqPool(x, "max", name="pool")
    logits = L.Fc(pooled, num_classes, act=None, name="logits")
    cost = C.ClassificationCost(logits, label, name="cost")
    return ids, label, logits, cost
