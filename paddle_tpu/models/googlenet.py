"""GoogleNet (Inception-v1) — parity with benchmark/paddle/image/googlenet.py
(BASELINE.md rows 2 and 5). Aux heads omitted in the bench config like the
reference's benchmark script (single loss3 head)."""

from __future__ import annotations

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L


def _inception(x, name, o1, o3r, o3, o5r, o5, pool_proj):
    b1 = L.Conv2D(x, o1, 1, act="relu", name=f"{name}.1x1")
    b3 = L.Conv2D(x, o3r, 1, act="relu", name=f"{name}.3x3r")
    b3 = L.Conv2D(b3, o3, 3, padding=1, act="relu", name=f"{name}.3x3")
    b5 = L.Conv2D(x, o5r, 1, act="relu", name=f"{name}.5x5r")
    b5 = L.Conv2D(b5, o5, 5, padding=2, act="relu", name=f"{name}.5x5")
    bp = L.Pool2D(x, 3, "max", stride=1, padding=1, name=f"{name}.pool")
    bp = L.Conv2D(bp, pool_proj, 1, act="relu", name=f"{name}.poolp")
    return L.Concat([b1, b3, b5, bp], name=f"{name}.cat")


def googlenet(num_classes: int = 1000, image_size: int = 224):
    img = L.Data("image", shape=(image_size, image_size, 3))
    label = L.Data("label", shape=())
    x = L.Conv2D(img, 64, 7, stride=2, padding=3, act="relu", name="conv1")
    x = L.Pool2D(x, 3, "max", stride=2, padding=1, name="pool1")
    x = L.Conv2D(x, 64, 1, act="relu", name="conv2r")
    x = L.Conv2D(x, 192, 3, padding=1, act="relu", name="conv2")
    x = L.Pool2D(x, 3, "max", stride=2, padding=1, name="pool2")
    x = _inception(x, "i3a", 64, 96, 128, 16, 32, 32)
    x = _inception(x, "i3b", 128, 128, 192, 32, 96, 64)
    x = L.Pool2D(x, 3, "max", stride=2, padding=1, name="pool3")
    x = _inception(x, "i4a", 192, 96, 208, 16, 48, 64)
    x = _inception(x, "i4b", 160, 112, 224, 24, 64, 64)
    x = _inception(x, "i4c", 128, 128, 256, 24, 64, 64)
    x = _inception(x, "i4d", 112, 144, 288, 32, 64, 64)
    x = _inception(x, "i4e", 256, 160, 320, 32, 128, 128)
    x = L.Pool2D(x, 3, "max", stride=2, padding=1, name="pool4")
    x = _inception(x, "i5a", 256, 160, 320, 32, 128, 128)
    x = _inception(x, "i5b", 384, 192, 384, 48, 128, 128)
    x = L.GlobalPool(x, "avg", name="gap")
    x = L.Dropout(x, 0.4, name="drop")
    logits = L.Fc(x, num_classes, act=None, name="logits")
    cost = C.ClassificationCost(logits, label, name="cost")
    return img, label, logits, cost
