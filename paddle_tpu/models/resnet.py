"""ResNet for ImageNet — BASELINE config #2 and the flagship bench model.

Capability parity with v1_api_demo/model_zoo/resnet/resnet.py (resnet_50/101/152
built from conv_bn_layer + bottleneck blocks); re-designed NHWC + bf16-friendly
for the MXU. The residual add is an Addto layer (AddtoLayer.cpp) exactly as the
reference composes it."""

from __future__ import annotations

from typing import Optional, Tuple

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import Layer, ParamAttr

# LOGICAL sharding axes (ROADMAP item 3c): conv filters declare their
# out-channel axis as "mlp" (the column-parallel vocabulary entry) and the
# classifier head declares ("embed", "vocab") — the rules table
# (parallel/rules.py) maps these to a 'model' mesh axis on a TP deployment
# and replicates them on the data-only CPU mesh; model code names meanings,
# never mesh axes. Conv kernels are HWIO: spatial + input-channel axes stay
# unsharded (None).
CONV_W_AXES = (None, None, None, "mlp")
BN_AXES = ("mlp",)


def conv_bn(
    x: Layer,
    num_filters: int,
    filter_size: int,
    stride: int = 1,
    padding: Optional[int] = None,
    act: Optional[str] = "relu",
    name: str = "",
) -> Layer:
    """conv → BN → act, conv without bias (BN has the shift) — the
    conv_bn_layer composite of the reference's resnet config."""
    if padding is None:
        padding = (filter_size - 1) // 2
    conv = L.Conv2D(
        x,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        act=None,
        bias=False,
        param_attr=ParamAttr(logical_axes=CONV_W_AXES),
        name=f"{name}.conv",
    )
    return L.BatchNorm(
        conv,
        act=act,
        param_attr=ParamAttr(logical_axes=BN_AXES),
        bias_attr=ParamAttr(logical_axes=BN_AXES),
        name=f"{name}.bn",
    )


def bottleneck(x: Layer, mid: int, out: int, stride: int, name: str) -> Layer:
    """1x1 → 3x3 → 1x1 bottleneck with projection shortcut when shape changes."""
    in_ch = _out_channels(x)
    a = conv_bn(x, mid, 1, stride, 0, "relu", f"{name}.a")
    b = conv_bn(a, mid, 3, 1, 1, "relu", f"{name}.b")
    c = conv_bn(b, out, 1, 1, 0, None, f"{name}.c")
    if stride != 1 or in_ch != out:
        shortcut = conv_bn(x, out, 1, stride, 0, None, f"{name}.proj")
    else:
        shortcut = x
    return L.Addto([c, shortcut], act="relu", name=f"{name}.add")


def _out_channels(layer: Layer) -> int:
    # walk the spec graph for the static channel count
    if isinstance(layer, L.Data):
        return layer.shape[-1]
    if isinstance(layer, L.Conv2D):
        return layer.num_filters
    if isinstance(layer, (L.BatchNorm, L.Pool2D, L.Addto)):
        return _out_channels(layer.inputs[0])
    raise ValueError(f"cannot infer channels of {layer}")


DEPTHS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


def resnet(
    depth: int = 50,
    num_classes: int = 1000,
    image_size: int = 224,
) -> Tuple[Layer, Layer, Layer, Layer]:
    """Returns (data, label, logits, cost). NHWC input [B, S, S, 3]."""
    blocks = DEPTHS[depth]
    img = L.Data("image", shape=(image_size, image_size, 3))
    label = L.Data("label", shape=())
    x = conv_bn(img, 64, 7, 2, 3, "relu", "stem")
    x = L.Pool2D(x, 3, "max", stride=2, padding=1, name="stem.pool")
    widths = [(64, 256), (128, 512), (256, 1024), (512, 2048)]
    for stage, (n_blocks, (mid, out)) in enumerate(zip(blocks, widths)):
        for blk in range(n_blocks):
            stride = 2 if (stage > 0 and blk == 0) else 1
            x = bottleneck(x, mid, out, stride, f"s{stage}b{blk}")
    pooled = L.GlobalPool(x, "avg", name="gap")
    logits = L.Fc(
        pooled,
        num_classes,
        act=None,
        param_attr=ParamAttr(logical_axes=("embed", "vocab")),
        bias_attr=ParamAttr(logical_axes=("vocab",)),
        name="logits",
    )
    cost = C.ClassificationCost(logits, label, name="cost")
    return img, label, logits, cost


def resnet50(num_classes: int = 1000, image_size: int = 224):
    return resnet(50, num_classes, image_size)
