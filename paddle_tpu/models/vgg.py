"""VGG-16/19 — parity with benchmark/paddle/image/vgg.py and the
vgg_16_network helper (trainer_config_helpers/networks.py:468)."""

from __future__ import annotations

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L


def _block(x, n_convs, channels, name):
    for i in range(n_convs):
        x = L.Conv2D(
            x, channels, 3, padding=1, act="relu", bias=True, name=f"{name}.conv{i}"
        )
    return L.Pool2D(x, 2, "max", name=f"{name}.pool")


def vgg(depth: int, num_classes: int = 1000, image_size: int = 224, fc_dim: int = 4096):
    cfg = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}[depth]
    img = L.Data("image", shape=(image_size, image_size, 3))
    label = L.Data("label", shape=())
    x = img
    for i, (n, ch) in enumerate(zip(cfg, (64, 128, 256, 512, 512))):
        x = _block(x, n, ch, f"b{i}")
    side = image_size // 32
    x = L.Reshape(x, (side * side * 512,), name="flatten")
    x = L.Fc(x, fc_dim, act="relu", name="fc6")
    x = L.Dropout(x, 0.5, name="drop6")
    x = L.Fc(x, fc_dim, act="relu", name="fc7")
    x = L.Dropout(x, 0.5, name="drop7")
    logits = L.Fc(x, num_classes, act=None, name="logits")
    cost = C.ClassificationCost(logits, label, name="cost")
    return img, label, logits, cost


def vgg16(num_classes: int = 1000, image_size: int = 224):
    return vgg(16, num_classes, image_size)


def vgg19(num_classes: int = 1000, image_size: int = 224):
    return vgg(19, num_classes, image_size)
