"""Seq2seq NMT with attention — BASELINE config #3.

Capability parity with the reference's seq2seq demo (wmt14 via
python/paddle/v2/dataset, encoder-decoder with attention composed in
demo configs; RecurrentGradientMachine for decode). TPU-native: bi-GRU encoder,
scan-based attention-GRU decoder with teacher forcing, jit-compiled beam search
(paddle_tpu/nn/beam_search.py)."""

from __future__ import annotations

import dataclasses

import jax

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.attention_layers import AttentionDecoder
from paddle_tpu.nn.beam_search import beam_search
from paddle_tpu.nn.graph import Network, ParamAttr
from paddle_tpu.nn.recurrent import bidirectional_gru


@dataclasses.dataclass
class Seq2SeqModel:
    src_vocab: int
    trg_vocab: int
    embed_dim: int = 512
    hidden_dim: int = 512
    bos_id: int = 0
    eos_id: int = 1

    def __post_init__(self):
        self.src = L.Data("source_ids", shape=(self.src_vocab,), is_seq=True)
        self.trg = L.Data("target_ids", shape=(self.trg_vocab,), is_seq=True)
        self.label = L.Data("label_ids", shape=(self.trg_vocab,), is_seq=True)
        # LOGICAL sharding axes (ROADMAP item 3c): the embedding tables and
        # the output projection — the parameters that dominate this model's
        # bytes — declare ("vocab", "embed") / ("embed", "vocab"); the
        # deployment's rules table (parallel/rules.py DEFAULT_RULES) decides
        # whether that shards them over a 'model' mesh axis or replicates
        # (the data-only CPU mesh) — no mesh-axis names in model code
        src_emb = L.Embedding(
            self.src,
            self.embed_dim,
            vocab_size=self.src_vocab,
            param_attr=ParamAttr(logical_axes=("vocab", "embed")),
            name="src_emb",
        )
        self.encoder = bidirectional_gru(src_emb, self.hidden_dim, name="enc")
        self.trg_emb_layer = L.Embedding(
            self.trg,
            self.embed_dim,
            vocab_size=self.trg_vocab,
            param_attr=ParamAttr(
                name="trg_emb_table", logical_axes=("vocab", "embed")
            ),
            name="trg_emb",
        )
        self.decoder = AttentionDecoder(
            self.encoder, self.trg_emb_layer, self.hidden_dim, name="decoder"
        )
        self.logits = L.Fc(
            self.decoder,
            self.trg_vocab,
            act=None,
            param_attr=ParamAttr(name="out_w", logical_axes=("embed", "vocab")),
            bias_attr=ParamAttr(name="out_b", logical_axes=("vocab",)),
            name="out",
        )
        self.cost = C.ClassificationCost(self.logits, self.label, name="cost")

    # -- generation ----------------------------------------------------------
    def build_generator(self, beam_size: int = 4, max_len: int = 50):
        """Returns a jitted fn(params, states, src_ids, src_lengths) →
        (sequences [B, K, max_len], scores [B, K])."""
        enc_net = Network(self.encoder)

        def generate(params, states, src_ids, src_lengths):
            outs, _ = enc_net.apply(
                params,
                states,
                {"source_ids": src_ids, "source_ids.lengths": src_lengths},
                train=False,
            )
            enc = outs[self.encoder.name]
            return beam_search(
                self.decoder,
                params,
                enc.value,
                enc.lengths,
                params["trg_emb_table"],
                params["out_w"],
                params["out_b"],
                bos_id=self.bos_id,
                eos_id=self.eos_id,
                beam_size=beam_size,
                max_len=max_len,
            )

        return jax.jit(generate)


def seq2seq(
    src_vocab: int = 30000,
    trg_vocab: int = 30000,
    embed_dim: int = 512,
    hidden_dim: int = 512,
) -> Seq2SeqModel:
    return Seq2SeqModel(src_vocab, trg_vocab, embed_dim, hidden_dim)
