"""LeNet-style MNIST convnet — BASELINE config #1.

Mirrors v1_api_demo/mnist/light_mnist.py (conv-pool ×2 + fc) built on the new
layer API; input NHWC [B, 28, 28, 1]."""

from __future__ import annotations

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L


def lenet(num_classes: int = 10):
    """Returns (data_layer, label_layer, logits, cost)."""
    img = L.Data("pixel", shape=(28, 28, 1))
    label = L.Data("label", shape=())
    conv1 = L.Conv2D(img, num_filters=32, filter_size=5, padding=2, act="relu", name="conv1")
    pool1 = L.Pool2D(conv1, 2, "max", name="pool1")
    conv2 = L.Conv2D(pool1, num_filters=64, filter_size=5, padding=2, act="relu", name="conv2")
    pool2 = L.Pool2D(conv2, 2, "max", name="pool2")
    flat = L.Reshape(pool2, (7 * 7 * 64,), name="flatten")
    fc1 = L.Fc(flat, 128, act="relu", name="fc1")
    logits = L.Fc(fc1, num_classes, act=None, name="logits")
    cost = C.ClassificationCost(logits, label, name="cost")
    return img, label, logits, cost
