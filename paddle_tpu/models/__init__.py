"""Model zoo covering the BASELINE configs (BASELINE.json 'configs') and the
reference's benchmark models (benchmark/paddle/image/{alexnet,googlenet,vgg,
smallnet_mnist_cifar}.py, v1_api_demo/mnist, v1_api_demo/model_zoo/resnet)."""

from paddle_tpu.models.lenet import lenet  # noqa: F401
from paddle_tpu.models.resnet import resnet, resnet50  # noqa: F401
from paddle_tpu.models.vgg import vgg16, vgg19  # noqa: F401
from paddle_tpu.models.alexnet import alexnet  # noqa: F401
from paddle_tpu.models.googlenet import googlenet  # noqa: F401
from paddle_tpu.models.seq2seq import seq2seq, Seq2SeqModel  # noqa: F401
from paddle_tpu.models.text_lstm import text_lstm  # noqa: F401
from paddle_tpu.models.ssd import ssd  # noqa: F401
from paddle_tpu.models.ctr import ctr_wide_deep  # noqa: F401
from paddle_tpu.models.ocr_crnn import ocr_crnn  # noqa: F401
