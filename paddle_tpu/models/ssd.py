"""SSD-style single-shot detector over a small VGG-ish backbone.

Covers the reference's detection capability (PriorBox/MultiBoxLoss/
DetectionOutput layers, demo config in the vein of the SSD paper the
reference cites in PriorBox.cpp). Multi-scale heads: each scale contributes
a (loc conv, conf conv, priorbox) triple concatenated along the prior axis."""

from __future__ import annotations

from typing import Sequence, Tuple

from paddle_tpu.nn import layers as L
from paddle_tpu.nn import detection_layers as D


def ssd(
    image_size: int = 96,
    num_classes: int = 21,
    widths: Sequence[int] = (32, 64, 128),
):
    """Returns (image, gt_boxes, gt_labels, cost_layer, detection_out)."""
    img = L.Data("image", shape=(image_size, image_size, 3))
    gtb = L.Data("gt_boxes", shape=(None, 4))
    gtl = L.Data("gt_labels", shape=(None,))

    x = img
    feats = []
    for i, w in enumerate(widths):
        x = L.Conv2D(x, w, 3, padding=1, act="relu", name=f"conv{i}a")
        x = L.Conv2D(x, w, 3, padding=1, act="relu", name=f"conv{i}b")
        x = L.Pool2D(x, 2, "max", name=f"pool{i}")
        feats.append(x)

    k = 4  # 1 min-size + 1 geometric-mean + 2 aspect-ratio priors per cell
    locs, confs, pbs = [], [], []
    # anchor scales spread over 0.15..0.9 of the image, one band per head
    bands = [0.15 + (0.9 - 0.15) * i / len(feats) for i in range(len(feats) + 1)]
    scale_min = [image_size * s for s in bands[:-1]]
    scale_max = [image_size * s for s in bands[1:]]
    for i, f in enumerate(feats):
        locs.append(
            L.Conv2D(f, 4 * k, 3, padding=1, act=None, name=f"loc{i}")
        )
        confs.append(
            L.Conv2D(f, num_classes * k, 3, padding=1, act=None, name=f"conf{i}")
        )
        pbs.append(
            D.PriorBox(
                f,
                (image_size, image_size),
                [scale_min[i]],
                [scale_max[i]],
                [2.0],
                name=f"pb{i}",
            )
        )

    cost = D.MultiBoxLoss(
        locs, confs, pbs, gtb, gtl, num_classes=num_classes, name="mbox_loss"
    )
    out = D.DetectionOutput(
        locs, confs, pbs, num_classes=num_classes, name="detection"
    )
    return img, gtb, gtl, cost, out
