"""AlexNet — parity with benchmark/paddle/image/alexnet.py (the headline
GPU benchmark model, BASELINE.md rows 1 and 4)."""

from __future__ import annotations

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L


def alexnet(num_classes: int = 1000, image_size: int = 224):
    img = L.Data("image", shape=(image_size, image_size, 3))
    label = L.Data("label", shape=())
    x = L.Conv2D(img, 64, 11, stride=4, padding=2, act="relu", name="conv1")
    x = L.CrossMapNorm(x, size=5, name="norm1")
    x = L.Pool2D(x, 3, "max", stride=2, name="pool1")
    x = L.Conv2D(x, 192, 5, padding=2, act="relu", name="conv2")
    x = L.CrossMapNorm(x, size=5, name="norm2")
    x = L.Pool2D(x, 3, "max", stride=2, name="pool2")
    x = L.Conv2D(x, 384, 3, padding=1, act="relu", name="conv3")
    x = L.Conv2D(x, 256, 3, padding=1, act="relu", name="conv4")
    x = L.Conv2D(x, 256, 3, padding=1, act="relu", name="conv5")
    x = L.Pool2D(x, 3, "max", stride=2, name="pool5")
    x = L.Reshape(x, (-1,), name="flatten")
    x = L.Fc(x, 4096, act="relu", name="fc6")
    x = L.Dropout(x, 0.5, name="drop6")
    x = L.Fc(x, 4096, act="relu", name="fc7")
    x = L.Dropout(x, 0.5, name="drop7")
    logits = L.Fc(x, num_classes, act=None, name="logits")
    cost = C.ClassificationCost(logits, label, name="cost")
    return img, label, logits, cost
