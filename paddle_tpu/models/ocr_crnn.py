"""OCR CRNN + CTC (BASELINE config #5; the reference composes this from
ExpandConvLayer + BlockExpandLayer (im2seq) + bidirectional lstmemory +
CTCLayer/WarpCTCLayer — v1 demo 'ocr' pattern, SURVEY §2.1 hl_sequence ops).

Conv stack halves height to 1-ish, BlockExpand turns the feature map into a
width-major sequence, a bidirectional LSTM reads it, and CTC aligns the
frame-wise class posteriors to the unsegmented label string."""

from __future__ import annotations

from paddle_tpu.nn import layers as L
from paddle_tpu.nn import struct_costs as SC
from paddle_tpu.nn.recurrent import bidirectional_lstm


def ocr_crnn(
    image_height: int = 32,
    image_width: int = 128,
    num_channels: int = 1,
    num_classes: int = 80,  # charset size; CTC blank is class 0
    rnn_hidden: int = 96,
):
    """Returns (image, label, frame_logits, cost). label: int sequence."""
    img = L.Data("image", shape=(image_height, image_width, num_channels))
    label = L.Data("label", shape=(), is_seq=True)

    x = L.Conv2D(img, 32, 3, padding=1, act="relu", name="c1")
    x = L.Pool2D(x, 2, "max", name="p1")             # H/2, W/2
    x = L.Conv2D(x, 64, 3, padding=1, act="relu", name="c2")
    x = L.Pool2D(x, 2, "max", name="p2")             # H/4, W/4
    x = L.Conv2D(x, 128, 3, padding=1, act="relu", name="c3")
    x = L.BatchNorm(x, act="relu", name="bn3")
    # pool height only: keep width (time) resolution
    x = L.Pool2D(x, (2, 1), "max", stride=(2, 1), name="p3")  # H/8, W/4

    # im2seq: each width position's full-height column becomes one timestep
    seq = L.BlockExpand(x, block_x=1, block_y=image_height // 8, name="im2seq")
    rnn = bidirectional_lstm(seq, rnn_hidden, name="blstm")
    logits = L.Fc(rnn, num_classes + 1, act=None, name="frame_logits")
    cost = SC.CTCCost(logits, label, blank=0, name="cost")
    return img, label, logits, cost
