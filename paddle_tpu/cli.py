"""`paddle` CLI — TrainerMain parity.

Reference: the `paddle train` entry (paddle/scripts/submit_local.sh.in:96-116 →
paddle_trainer, paddle/trainer/TrainerMain.cpp:32) driven by gflags
(utils/Flags.h:19-43), plus `--job=time` benchmarking (TrainerBenchmark.cpp)
and model tools (MergeModel.cpp, python/paddle/utils/dump_config.py).

Usage:
    python -m paddle_tpu train --config=conf.py [--config_args=k=v,...]
        [--num_passes=N] [--save_dir=DIR] [--trainer_count=N] [--use_tpu=1]
        [--init_model_path=DIR] [--start_pass=N] [--log_period=N] [--job=train|test|time]
        [--auto_resume=1] [--divergence_policy=skip_batch|rollback|raise]
        [--shard_update=zero1|zero2|zero3] [--grad_compression=none|bf16|int8]
        [--precision=f32|bf16] [--remat=none|dots|conv_only|full]
        [--guard_check_every=N] [--steps_per_dispatch=K] [--async_checkpoint=0|1]
        [--keep_last_n=N] [--faults=SPEC]
        [--master_endpoints=a:p1,b:p2] [--preempt_grace_s=S] [--elastic=1]
        [--profile=pass:N] [--profile_dir=DIR]
    python -m paddle_tpu dump_config --config=conf.py
    python -m paddle_tpu merge_model --config=conf.py --model_dir=DIR --output=FILE
    python -m paddle_tpu serve [--port=N] [--demo | --load=model.npz]
        [--config=conf.py --model_dir=DIR] [--max_slots=N] [--page_size=N]
        [--prefill_buckets=16,32,64] [--max_new_limit=N] [--max_queue=N]
        [--tenant_tokens=CAP] [--tenant_tokens_per_s=R] [--tenant_concurrent=N]
        [--lease_s=S] [--require_register=0|1]
    python -m paddle_tpu version
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Any, Callable, List, Optional

from paddle_tpu import proto


def _str2bool(v: str) -> bool:
    return str(v).lower() in ("1", "true", "yes", "on")


def _shard_update_mode(v: str):
    """--shard_update value: bools stay the zero1 alias (back-compat),
    zero1/zero2/zero3 name the ZeRO mode explicitly."""
    s = str(v).strip().lower()
    if s in ("zero1", "zero2", "zero3"):
        return s
    if s in ("1", "true", "yes", "on"):
        return "zero1"
    if s in ("0", "false", "no", "off", "none", ""):
        return False
    raise argparse.ArgumentTypeError(
        f"--shard_update must be a boolean or one of zero1/zero2/zero3, "
        f"got {v!r}"
    )


def _train_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", required=True, help="config script path")
    p.add_argument("--config_args", default="", help="k=v,... passed to get_config_arg")
    p.add_argument("--use_tpu", type=_str2bool, default=True)
    p.add_argument("--use_gpu", type=_str2bool, default=None, help="v1 alias of --use_tpu")
    p.add_argument("--trainer_count", type=int, default=1)
    p.add_argument("--num_passes", type=int, default=1)
    p.add_argument("--save_dir", default=None)
    p.add_argument("--init_model_path", default=None)
    p.add_argument("--start_pass", type=int, default=0)
    p.add_argument("--log_period", type=int, default=100)
    p.add_argument("--test_period", type=int, default=0)
    p.add_argument("--saving_period", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dtype", default=None, choices=[None, "float32", "bfloat16"])
    p.add_argument(
        "--precision", default=None, choices=[None, "f32", "bf16"],
        help="mixed-precision policy for THIS trainer's compiled step: bf16 "
             "casts dot/conv inputs to bfloat16 (the MXU-native path) while "
             "parameters stay float32 masters in the optimizer and in "
             "checkpoints — a bf16-trained checkpoint resumes bitwise into an "
             "f32 run and vice versa. Softmax/xent, batch-norm statistics, "
             "cost averaging and the divergence guard stay f32 regardless. "
             "Default: f32 (or the process-wide --dtype policy when set)",
    )
    p.add_argument(
        "--remat", default=None,
        choices=[None, "none", "dots", "conv_only", "full"],
        help="backward rematerialization policy: 'dots' keeps matmul/conv "
             "outputs and recomputes the elementwise rest (frees activation "
             "residual HBM for larger per-chip batch), 'conv_only' keeps "
             "only tagged conv outputs, 'full' recomputes the whole forward. "
             "Recomputation replays the same ops, so the applied updates "
             "never change — only step time and residual memory",
    )
    p.add_argument("--job", default="train", choices=["train", "test", "time"])
    p.add_argument("--num_batches", type=int, default=20, help="--job=time batches")
    p.add_argument(
        "--prefetch_depth", type=int, default=2,
        help="device-resident batches to prefetch ahead of the train step "
             "(0 disables the async input pipeline)",
    )
    p.add_argument(
        "--compile_cache", default=None,
        help="persistent XLA compilation cache dir "
             "(default: $PADDLE_TPU_COMPILE_CACHE, unset = off)",
    )
    p.add_argument(
        "--steps_per_dispatch", type=int, default=1,
        help="train steps fused into one compiled device dispatch "
             "(lax.scan over K prefetcher-stacked batches); events, the "
             "log line and chaos sites then fire per dispatch, not per "
             "batch. 1 = one dispatch per batch",
    )
    p.add_argument(
        "--shard_update", type=_shard_update_mode, default=False,
        help="ZeRO-sharded weight update over the mesh data axis. "
             "zero1 (or 1/true, the back-compat alias): reduce-scatter "
             "grads, shard-local optimizer step on 1/N of the optimizer "
             "state (resident sharded — ~N x less opt-state HBM per chip), "
             "all-gather updated params. zero2: zero1 fused across the "
             "--steps_per_dispatch window — one scatter/gather per dispatch "
             "(~K x fewer grad-leg bytes; gradient-accumulation semantics). "
             "zero3: params themselves live data-axis-sharded (~N x less "
             "param HBM per chip), gathered layer-by-layer on demand inside "
             "the step and re-gathered in the backward. Needs "
             "--trainer_count > 1 to matter",
    )
    p.add_argument(
        "--grad_compression", default="none",
        choices=["none", "bf16", "int8"],
        help="quantize the sharded update's collective payloads: bf16 "
             "halves both legs (~2x fewer collective bytes/step); int8 "
             "block-scales the gradient leg with an error-feedback "
             "residual in the train state (~2.7x total); under "
             "--shard_update=zero3 int8 instead quantizes INSIDE the "
             "on-demand param all-gather (the hot leg there, ~3.75x) with "
             "a master-tracking EF residual. Requires --shard_update",
    )
    p.add_argument(
        "--guard_check_every", type=int, default=16,
        help="steps between divergence-guard polls of the device-resident "
             "diverged counter (reaction latency vs throughput; 1 = react "
             "at the offending batch like the old per-step sync). Only "
             "meaningful with --divergence_policy",
    )
    p.add_argument(
        "--async_checkpoint", type=_str2bool, default=True,
        help="write pass/drain checkpoints on a background thread after a "
             "non-blocking device→host fetch (zero-stall); 0 = synchronous "
             "writes on the training thread",
    )
    p.add_argument(
        "--auto_resume", type=_str2bool, default=False,
        help="on startup, resume from the newest CRC-valid checkpoint under "
             "--save_dir (corrupt/partial pass dirs are skipped)",
    )
    p.add_argument(
        "--divergence_policy", default=None,
        choices=["skip_batch", "rollback", "raise"],
        help="react to a NaN/Inf step cost: skip the batch, roll back to the "
             "last checkpoint with the LR halved, or raise (default: guard off)",
    )
    p.add_argument(
        "--keep_last_n", type=int, default=0,
        help="retain only the newest N pass checkpoints under --save_dir "
             "(0 = keep all)",
    )
    p.add_argument(
        "--faults", default=None,
        help="chaos-injection spec, e.g. 'feeder_raise:0.01,nan_loss:step=37' "
             "(overrides $PADDLE_TPU_FAULTS; see paddle_tpu/core/faults.py)",
    )
    p.add_argument(
        "--master_endpoints", default=None,
        help="pull training data from an elastic task master instead of the "
             "config's provider: 'host:port' or a failover list "
             "'a:p1,b:p2' (primary + standby); shards hold pickled "
             "provider-format samples",
    )
    p.add_argument(
        "--profile", default=None, metavar="pass:N",
        help="capture a jax.profiler trace of pass N and dump per-executable "
             "HLO cost analysis (top-k FLOP/byte buckets) as profile.json — "
             "the ROADMAP 'top-3 HLO cost buckets' target list. With "
             "--job=time the buckets land in the printed JSON line instead",
    )
    p.add_argument(
        "--profile_dir", default=None,
        help="where the jax.profiler trace + profile.json go "
             "(default: <save_dir>/profile, else /tmp/paddle_tpu_profile)",
    )
    p.add_argument(
        "--preempt_grace_s", type=float, default=30.0,
        help="drain budget after a SIGTERM/SIGINT preemption notice: finish "
             "the step and checkpoint within this many seconds, then exit "
             "with code 77 (preempt.EXIT_PREEMPTED) so a supervisor restart "
             "with --auto_resume=1 continues from the drained batch boundary",
    )
    p.add_argument(
        "--elastic", type=_str2bool, default=False,
        help="join the master's elastic-resize plane (needs "
             "--master_endpoints and --trainer_count > 1): a `resize` epoch "
             "announced by the master drains this trainer at a batch "
             "boundary, re-shards params/optimizer state from the canonical "
             "layout onto the new mesh data-axis size, and resumes the "
             "interrupted pass in place (see README 'Elastic resize')",
    )


# Names injected into legacy provider modules: the reference embedded
# Python 2, so providers in the wild use py2 builtins. A compat shim at module
# load is what lets those files run unmodified under py3.
_PY2_SHIMS = {"xrange": range, "unicode": str, "long": int, "basestring": str}


def _load_provider_module(name: str, config_dir: str = ""):
    """Import a provider module, preferring the config script's directory
    (PyDataProvider2.cpp loads module.obj next to the config), with py2
    builtin shims injected for legacy providers."""
    path = os.path.join(config_dir or ".", name + ".py") if name else None
    if path and os.path.exists(path):
        import importlib.util

        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        mod.__dict__.update(_PY2_SHIMS)
        sys.modules.setdefault(name, mod)
        spec.loader.exec_module(mod)
        return mod
    if config_dir and config_dir not in sys.path:
        sys.path.insert(0, config_dir)
    mod = importlib.import_module(name)
    for k, v in _PY2_SHIMS.items():
        mod.__dict__.setdefault(k, v)
    return mod


def _load_provider(dc: proto.DataConfig):
    """DataConfig → (provider, file_list, args) — the PyDataProvider2 load
    path (gserver/dataproviders/PyDataProvider2.cpp:195 loads module.obj),
    or the builtin ProtoData provider for binary shards
    (REGISTER_DATA_PROVIDER proto/proto_sequence, ProtoDataProvider.cpp:31)."""
    if (dc.type or "").startswith("proto"):
        from paddle_tpu.data.proto_data import (
            make_proto_provider, resolve_data_path,
        )

        # one provider per DataConfig: bind_provider_types and _make_reader
        # both land here, and the provider caches all decoded shards
        provider = getattr(dc, "_builtin_provider", None)
        if provider is None:
            provider = make_proto_provider(dc)
            dc._builtin_provider = provider
        files: List[str] = []
        flist = resolve_data_path(dc.files, dc.config_dir or "") or dc.files
        if flist and os.path.exists(flist):
            with open(flist) as f:
                files = [ln.strip() for ln in f if ln.strip()]
        elif flist:
            files = [flist]
        return provider, files, None
    mod = _load_provider_module(dc.load_data_module, dc.config_dir)
    provider = getattr(mod, dc.load_data_object)
    files: List[str] = []
    flist = dc.files
    if flist and not os.path.exists(flist) and dc.config_dir:
        cand = os.path.join(dc.config_dir, flist)
        if os.path.exists(cand):
            flist = cand
    if flist and os.path.exists(flist):
        with open(flist) as f:
            files = [ln.strip() for ln in f if ln.strip()]
    elif flist:
        files = [flist]
    args = json.loads(dc.load_data_args) if dc.load_data_args else None
    return provider, files, args


def bind_provider_types(topology, dc: proto.DataConfig):
    """Bind the provider's input_types to the topology's data layers — the
    runtime slot binding PyDataProvider2.cpp does. Returns a feeding map
    {layer_name: slot_index} (sample tuples arrive in slot order).

    Dict input_types bind by name. List input_types bind positionally over
    the data layers in declaration order, except when the declared sizes are
    incompatible (e.g. GoogleNet declares the label layer first while the
    provider yields (image, label)) — then slots match by kind and size the
    way DataProviderConverter reconciles Arguments."""
    provider, files, args = _load_provider(dc)
    kwargs = dict(args) if isinstance(args, dict) else {}
    settings = provider.make_settings(obj=None, file_list=files, **kwargs)
    types = settings.input_types
    if types is None:
        return None
    layers = list(topology.data_layers().values())
    # Inputs("a", "b", ...) in the config pins the slot order (the reference
    # feeds inArgs in Inputs order, not graph order — chunking.conf's label
    # slot is last by Inputs but an early cost dependency topologically)
    declared = getattr(topology, "declared_inputs", None)
    if declared:
        by_name = {l.name: l for l in layers}
        picked = [by_name[n] for n in declared if n in by_name]
        if len(picked) == len(layers):
            layers = picked

    def apply_spec(layer, spec):
        from paddle_tpu.nn.graph import record_layers
        from paddle_tpu.v2.layer import data as _v2_data

        with record_layers([]):  # shape probe only — keep out of the graph
            tmpl = _v2_data(layer.name + ".__tmpl__", spec)
        layer.data_type = spec
        layer.shape = tmpl.shape
        layer.is_seq = tmpl.is_seq

    if isinstance(types, dict):
        feeding = {}
        for i, (lname, spec) in enumerate(types.items()):
            layer = topology.data_layers().get(lname)
            if layer is None:
                raise ValueError(f"provider input_types names unknown layer {lname!r}")
            apply_spec(layer, spec)
            feeding[lname] = i
        return feeding

    types = list(types)
    if len(types) != len(layers):
        raise ValueError(
            f"provider declares {len(types)} slots but the config has "
            f"{len(layers)} data layers"
        )

    def declared_size(layer):
        size = getattr(layer, "_v1_size", None)
        if size is None and getattr(layer, "shape", None):
            size = 1
            for d in layer.shape:
                size *= int(d)
        return size

    def compatible(layer, spec) -> bool:
        if spec.kind.startswith("dense") and not isinstance(spec.dim, tuple):
            return declared_size(layer) in (None, int(spec.dim))
        return True

    order = list(layers)
    if not all(compatible(l, s) for l, s in zip(order, types)):
        # declaration order mismatches the slot order — rebind dense slots
        # to the layers whose declared size matches, then fill the rest
        remaining = list(layers)
        order = []
        for spec in types:
            pick = next((l for l in remaining if compatible(l, spec)), remaining[0])
            remaining.remove(pick)
            order.append(pick)
    for layer, spec in zip(order, types):
        apply_spec(layer, spec)
    return {layer.name: i for i, layer in enumerate(order)}


def _make_reader(dc: proto.DataConfig, batch_size: int, is_train: bool = True) -> Callable:
    provider, files, args = _load_provider(dc)
    kwargs = dict(args) if isinstance(args, dict) else {}
    # @provider batching knobs (PyDataProvider2.py): calc_batch_size gives a
    # per-sample cost (e.g. token count); can_over_batch_size controls whether
    # the overflowing sample stays in the current batch or starts the next
    calc = getattr(provider, "calc_batch_size", None)
    can_over = getattr(provider, "can_over_batch_size", True)

    def reader():
        batch: List[Any] = []
        acc = 0
        for sample in provider(
            obj=None, file_list=files or None, is_train=is_train, **kwargs
        ):
            cost = int(calc(sample)) if calc is not None else 1
            if batch and not can_over and acc + cost > batch_size:
                yield batch
                batch, acc = [], 0
            batch.append(sample)
            acc += cost
            if acc >= batch_size:
                yield batch
                batch, acc = [], 0
        if batch:
            yield batch

    return reader


def cmd_train(args: argparse.Namespace) -> int:
    use_tpu = args.use_gpu if args.use_gpu is not None else args.use_tpu
    if not use_tpu:
        # must happen before ANY jax import (jax reads JAX_PLATFORMS at
        # import time); paddle_tpu.trainer/parallel import jax at module top.
        # If something (e.g. a sitecustomize plugin) already imported jax,
        # force the config back the way tests/conftest.py does.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if "jax" in sys.modules:
            sys.modules["jax"].config.update("jax_platforms", "cpu")

    from paddle_tpu.core import init_ctx
    from paddle_tpu.config import build_optimizer, parse_config
    from paddle_tpu.metrics.evaluators import EVALUATORS
    from paddle_tpu.trainer.trainer import SGDTrainer

    init_ctx.init(
        use_tpu=use_tpu,
        trainer_count=args.trainer_count,
        log_period=args.log_period,
        seed=args.seed,
        **({"dtype_policy": args.dtype} if args.dtype else {}),
        **({"compile_cache": args.compile_cache} if args.compile_cache else {}),
    )

    if args.faults:
        from paddle_tpu.core import faults

        faults.get().configure(args.faults)

    # SIGTERM/SIGINT (cloud preemption notice) → drain at the next batch
    # boundary, checkpoint, exit with preempt.EXIT_PREEMPTED (see below)
    from paddle_tpu.core import preempt

    preempt.install(grace_s=args.preempt_grace_s)

    pc = parse_config(args.config, args.config_args, emit_proto=False)
    oc = pc.trainer_config.opt_config
    bundle = build_optimizer(oc)

    parallel = None
    if args.trainer_count > 1:
        from paddle_tpu.parallel import DataParallel, make_mesh

        parallel = DataParallel(make_mesh({"data": args.trainer_count}))
    elif args.shard_update or args.grad_compression != "none":
        import logging

        logging.getLogger("paddle_tpu.cli").warning(
            "--shard_update/--grad_compression need --trainer_count > 1 "
            "(no data axis to shard over); ignoring them"
        )
        args.shard_update, args.grad_compression = False, "none"

    # Outputs() may mix training costs with plain fetch layers
    # (sample_trainer_config_qb_rnn.conf: Outputs("cost", "qb_rnnlast_left"));
    # only cost layers join the objective, the rest ride as extra outputs
    cost_outputs = [l for l in pc.outputs if getattr(l, "is_cost", False)]
    fetch_outputs = [l for l in pc.outputs if not getattr(l, "is_cost", False)]
    if not cost_outputs:
        cost_outputs, fetch_outputs = pc.outputs, []

    # evaluator outputs must be network outputs so the step returns them
    extra_layers, seen = list(fetch_outputs), {l.name for l in cost_outputs}
    seen |= {l.name for l in fetch_outputs}
    eval_objs = []
    net_layers = pc.topology.network.layers_by_name
    for ec in pc.context.evaluators:
        ins = [net_layers[n] for n in ec.input_layers if n in net_layers]
        for l in ins:
            if l.name not in seen:
                seen.add(l.name)
                extra_layers.append(l)
        eval_objs.append((ec, [l.name for l in ins]))

    trainer = SGDTrainer(
        cost_outputs,
        bundle.optimizer,
        extra_outputs=extra_layers,
        schedule=bundle.schedule,
        model_average=bundle.model_average,
        parallel=parallel,
        seed=args.seed,
        remat=args.remat,
        precision=args.precision,
        divergence_policy=args.divergence_policy,
        guard_check_every=args.guard_check_every,
        shard_update=args.shard_update,
        grad_compression=args.grad_compression,
    )
    batch_size = oc.batch_size or 32

    if (
        pc.trainer_config.data_config is None
        and args.job != "test"
        and not args.master_endpoints
    ):
        # --master_endpoints replaces the provider as the sample source, so a
        # config without local data sources is legitimate there
        print("config declares no data sources (define_py_data_sources2)", file=sys.stderr)
        return 2

    # bind the provider's input_types to the data layers (the runtime slot
    # binding PyDataProvider2.cpp performs) before building the feeder
    feeding = None
    bind_dc = pc.trainer_config.data_config or pc.trainer_config.test_data_config
    if bind_dc is not None:
        # hard-fail like PyDataProvider2's slot binding: a mis-bound provider
        # would otherwise train on garbage (VERDICT r2 weak #8)
        feeding = bind_provider_types(pc.topology, bind_dc)
    feeder = pc.topology.make_feeder(feeding)
    reader = (
        _make_reader(pc.trainer_config.data_config, batch_size)
        if pc.trainer_config.data_config
        else None
    )
    if args.master_endpoints:
        # elastic-cluster data path: this trainer is a stateless consumer of
        # the shared task queue; the endpoint list gives it a standby to fail
        # over to when the primary master dies mid-pass
        from paddle_tpu.data import reader as rd
        from paddle_tpu.runtime.master import cluster_reader

        reader = rd.batch(cluster_reader(args.master_endpoints), batch_size)
    test_reader = (
        _make_reader(pc.trainer_config.test_data_config, batch_size, is_train=False)
        if pc.trainer_config.test_data_config
        else None
    )

    # --profile pass:N (obs pillar 3): validate the spec up front; the
    # PassProfiler wraps the event handler to capture exactly that pass
    profiler = None
    profile_dir = None
    if args.profile:
        from paddle_tpu.obs import profile as obs_profile

        profile_dir = args.profile_dir or (
            os.path.join(args.save_dir, "profile")
            if args.save_dir
            else "/tmp/paddle_tpu_profile"
        )
        try:
            profiler = obs_profile.PassProfiler.from_spec(
                args.profile, logdir=profile_dir
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2

    if args.init_model_path:
        first = next(iter(reader() if reader else test_reader()))
        batch = feeder(first)
        if parallel is not None:
            batch = parallel.shard_batch(batch)
        trainer.init_state(batch)
        trainer.load(args.init_model_path, args.start_pass - 1 if args.start_pass else None)

    if args.job == "time":
        return _job_time(
            trainer, reader, feeder, args.num_batches,
            profile=args.profile, profile_dir=profile_dir,
        )
    if args.job == "test":
        if test_reader is None:
            print("--job=test needs a test data source", file=sys.stderr)
            return 2
        res = trainer.test(test_reader, feeder)
        print(json.dumps({"test_cost": res["cost"], "samples": res["samples"]}))
        return 0

    # evaluator accumulation through the event stream (Evaluator::start/eval/
    # finish per pass, Evaluator.h:42)
    from paddle_tpu.trainer.events import BeginPass, EndIteration, EndPass

    def _make_evaluator(ec):
        kw = {}
        if ec.type == "chunk":
            kw = dict(scheme=ec.chunk_scheme or "IOB",
                      num_chunk_types=ec.num_chunk_types or 1,
                      excluded_chunk_types=ec.excluded_chunk_types)
        elif ec.type == "precision_recall":
            kw = dict(positive_label=(
                None if ec.positive_label in (-1, None) else ec.positive_label))
        elif ec.type == "max_id_printer":
            kw = dict(num_results=ec.num_results)
        elif ec.type == "seq_text_printer":
            # resolve the config's relative result/dict paths against the
            # config directory with generation.py's own helper — training
            # from another cwd must not break dict loading or scatter result
            # files. Only an explicitly configured result_file follows the
            # config dir; the fallback stays cwd-relative so a config on a
            # read-only tree still trains.
            from paddle_tpu.trainer.generation import _resolve

            base = (bind_dc.config_dir if bind_dc is not None else None) or (
                os.path.dirname(os.path.abspath(args.config))
            )
            kw = dict(
                result_file=(
                    _resolve(ec.result_file, base)
                    if ec.result_file
                    else "generated_sequences.txt"
                ),
                dict_file=_resolve(ec.dict_file, base),
                delimited=ec.delimited,
            )
        return EVALUATORS.get(ec.type)(**kw)

    active = [
        (_make_evaluator(ec), names) for ec, names in eval_objs
    ] if eval_objs else []
    if active and args.steps_per_dispatch > 1:
        # fused dispatches return no per-batch extra outputs, so evaluator
        # update() would never run — producing stats over zero samples.
        # Losing the user's requested metrics silently is worse than losing
        # the fusion win; fall back loudly.
        import logging

        logging.getLogger("paddle_tpu.cli").warning(
            "config declares %d evaluator(s), which need per-batch network "
            "outputs — --steps_per_dispatch=%d would starve them; falling "
            "back to steps_per_dispatch=1 (drop the evaluators to keep the "
            "fused dispatch)", len(active), args.steps_per_dispatch,
        )
        args.steps_per_dispatch = 1

    def handler(event):
        if isinstance(event, BeginPass):
            for ev, _ in active:
                ev.start()
        elif isinstance(event, EndIteration) and active:
            for ev, names in active:
                vals = [event.metrics.get(n) for n in names]
                if vals and vals[0] is not None:
                    kw = {"output": vals[0]}
                    if len(vals) > 1:
                        kw["label"] = vals[1]
                    if len(vals) > 2:
                        kw["weight"] = vals[2]
                    try:
                        ev.update(**kw)
                    except Exception as e:  # metric failure must not kill training
                        import logging

                        logging.getLogger("paddle_tpu.cli").warning(
                            "evaluator %s failed: %s", type(ev).__name__, e
                        )
        elif isinstance(event, EndPass):
            stats = {type(ev).__name__: ev.finish() for ev, _ in active}
            line = f"pass {event.pass_id}: avg_cost={event.metrics['avg_cost']:.6f}"
            if "test_cost" in event.metrics:
                line += f" test_cost={event.metrics['test_cost']:.6f}"
            for k, v in stats.items():
                line += f" {k}={v}"
            print(line)

    if profiler is not None:
        handler = profiler.wrap(handler)
    # the cost report lowers the step against one feed-ready batch; grab it
    # from the PRE-prefetch reader so no worker thread outlives the report
    profile_reader = reader

    if args.prefetch_depth > 0 and reader is not None:
        # run the feeder + batch sharding + H2D on a background thread so
        # host input prep overlaps the donated compiled step; with
        # --steps_per_dispatch=K the worker also stacks K batches into one
        # fused-dispatch payload (one device put per K steps)
        from paddle_tpu.data.pipeline import DevicePrefetcher

        reader = DevicePrefetcher(
            reader, feeder, parallel=parallel,
            prefetch_depth=args.prefetch_depth,
            stack_k=args.steps_per_dispatch,
        )

    from paddle_tpu.trainer.trainer import Preempted

    resize_client = None
    resize_barrier = None
    if args.elastic:
        if not args.master_endpoints or parallel is None:
            print(
                "--elastic needs --master_endpoints (the resize plane rides "
                "the master heartbeats) and --trainer_count > 1 (a mesh to "
                "re-shape); continuing without elastic resize",
                file=sys.stderr,
            )
        else:
            from paddle_tpu.runtime.master import ResizeClient

            try:
                resize_client = ResizeClient(args.master_endpoints)
                resize_barrier = resize_client.barrier
            except ConnectionError as e:
                # same degrade contract as the misconfiguration branch
                # above: an unreachable master must not abort training (a
                # supervisor loop with --auto_resume restarts into the
                # current mesh and re-attaches when the master returns)
                print(
                    f"--elastic: master unreachable ({e}); continuing "
                    "without elastic resize",
                    file=sys.stderr,
                )

    try:
        trainer.train(
            reader,
            num_passes=args.num_passes,
            event_handler=handler,
            feeder=feeder,
            test_reader=test_reader,
            save_dir=args.save_dir,
            log_period=args.log_period,
            auto_resume=args.auto_resume,
            keep_last_n=args.keep_last_n or None,
            steps_per_dispatch=args.steps_per_dispatch,
            async_checkpoint=args.async_checkpoint,
            resize_barrier=resize_barrier,
        )
    except Preempted as p:
        # distinct exit code: a supervisor restarting with --auto_resume=1
        # continues bitwise-identically from the drained batch boundary
        where = (
            f"checkpoint saved to {p.checkpoint_dir}"
            if p.checkpoint_dir
            else "no mid-pass checkpoint (no --save_dir or grace expired)"
        )
        print(
            f"preempted ({p.reason}): drained at pass {p.pass_id} batch "
            f"{p.batches_done}; {where}; restart with --auto_resume=1 to "
            f"continue", file=sys.stderr,
        )
        return preempt.EXIT_PREEMPTED
    finally:
        if resize_client is not None:
            resize_client.close()

    if profiler is not None:
        from paddle_tpu.obs import profile as obs_profile

        report = {
            "profile": args.profile,
            "trace_dir": profile_dir,
            "captured": profiler.captured,
        }
        try:
            raw = (
                next(iter(profile_reader()), None)
                if profile_reader is not None
                else None
            )
            if raw is not None and trainer.state is not None:
                batch = (
                    feeder(raw)
                    if feeder is not None and not isinstance(raw, dict)
                    else raw
                )
                if parallel is not None:
                    batch = parallel.shard_batch(batch)
                report.update(obs_profile.trainer_cost_report(trainer, batch))
        except Exception as e:  # the report must not fail a finished run
            import logging

            logging.getLogger("paddle_tpu.cli").warning(
                "HLO cost report failed: %r", e
            )
            report["error"] = repr(e)[-400:]
        path = obs_profile.write_report(
            report, os.path.join(profile_dir, "profile.json")
        )
        print(json.dumps({"profile_json": path,
                          "trace_dir": profile_dir if profiler.captured else None}))
    return 0


def _job_time(
    trainer, reader, feeder, num_batches: int,
    profile: Optional[str] = None, profile_dir: Optional[str] = None,
) -> int:
    """--job=time (TrainerBenchmark.cpp): time num_batches hot-loop batches.
    With --profile, the timed window is captured as a jax.profiler trace and
    the step's top-k HLO cost buckets join the printed bench JSON line."""
    import jax

    it = iter(reader())
    batches = []
    for _ in range(num_batches):
        try:
            batches.append(feeder(next(it)))
        except StopIteration:
            break
    if not batches:
        print("no data", file=sys.stderr)
        return 2
    if trainer.parallel is not None:
        batches = [trainer.parallel.shard_batch(b) for b in batches]
    trainer.init_state(batches[0])
    step = trainer._make_step()
    state = trainer.state
    lowered = None
    if profile:
        # lower BEFORE the donated executions below delete the state buffers;
        # AOT compile for the cost report happens after timing
        lowered = step.lower(state, batches[0])
    state, cost, _ = step(state, batches[0])  # compile
    jax.block_until_ready(cost)
    if profile:
        from paddle_tpu.core import stats as _stats

        _stats.profiler_start(profile_dir or "/tmp/paddle_tpu_profile")
    t0 = time.time()
    for b in batches:
        state, cost, _ = step(state, b)
    jax.block_until_ready(cost)
    dt = (time.time() - t0) / len(batches)
    out = {"ms_per_batch": dt * 1e3, "batches": len(batches)}
    if profile:
        from paddle_tpu.core import stats as _stats
        from paddle_tpu.obs import profile as obs_profile

        _stats.profiler_stop()
        out["trace_dir"] = profile_dir or "/tmp/paddle_tpu_profile"
        try:
            out["hlo_cost"] = obs_profile.compiled_cost_report(
                lowered.compile()
            )
        except Exception as e:  # the timing line must survive a backend
            # that cannot cost-analyze (bench.py's discipline)
            out["hlo_cost_error"] = repr(e)[-300:]
    print(json.dumps(out))
    return 0


def cmd_dump_config(args: argparse.Namespace) -> int:
    from paddle_tpu.config import parse_config

    pc = parse_config(args.config, args.config_args)
    sys.stdout.write(proto.to_text(pc.trainer_config))
    return 0


def cmd_merge_model(args: argparse.Namespace) -> int:
    from paddle_tpu.capi.merge_model import merge_model

    out = merge_model(args.config, args.model_dir, args.output, args.config_args)
    print(out)
    return 0


def _serve_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="0 picks a free port")
    p.add_argument(
        "--demo", action="store_true",
        help="serve the built-in seeded demo LM (smoke/bench mode)",
    )
    p.add_argument("--load", default=None, help="ServableLM .npz to serve")
    p.add_argument(
        "--config", default=None,
        help="v1 config script: serve whole-request generation through a "
             "long-lived GenerationSession (RPC method generate_config)",
    )
    p.add_argument("--model_dir", default=None, help="params for --config")
    p.add_argument("--config_args", default="")
    p.add_argument("--max_slots", type=int, default=8,
                   help="concurrent decode slots = the continuous batch width")
    p.add_argument("--page_size", type=int, default=16,
                   help="tokens per KV page")
    p.add_argument("--num_pages", type=int, default=0,
                   help="KV page pool size (0 = worst case for max_slots)")
    p.add_argument("--prefill_buckets", default="16,32,64",
                   help="padded prompt lengths; one prefill compile each")
    p.add_argument("--prefill_chunk", type=int, default=0,
                   help="chunked prefill (0 = off): prompts longer than this "
                        "commit their KV one C-token chunk per engine step, "
                        "interleaved with decode, so a long prompt joining "
                        "mid-stream never stalls running streams' inter-token "
                        "latency; also lifts the bucket cap on prompt length "
                        "(any prompt up to the model's max_len is admissible)")
    p.add_argument("--speculate_k", type=int, default=0,
                   help="prompt-lookup speculative decoding (0 = off): draft "
                        "up to K continuation tokens per request per step "
                        "from the request's own committed n-grams and score "
                        "them all in ONE fixed-shape [1,K+1] verify call — "
                        "the matched prefix commits, the first divergent "
                        "token comes free from the verify logits, so "
                        "high-overlap streams advance several tokens per "
                        "step; tokens are identical to --speculate_k 0 "
                        "(greedy AND seeded sampling: acceptance replays "
                        "through the per-emitted-token key fold)")
    p.add_argument("--prefix_cache", action="store_true",
                   help="shared-prefix KV cache (needs --prefill_chunk): "
                        "committed prompt pages index by tenant-namespaced "
                        "token hash at page granularity; a new request "
                        "aliases its cached prefix pages read-only "
                        "(refcounted, copy-on-write at the first divergent "
                        "page) and prefills only its own suffix — tokens "
                        "stay bitwise-identical to cache-off, TTFT drops by "
                        "the shared fraction")
    p.add_argument("--prefix_cache_pages", type=int, default=0,
                   help="cap on cached prefix pages (0 = bounded only by "
                        "the pool; unreferenced cached pages LRU-evict "
                        "under pool pressure either way)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="default sampling temperature for requests that do "
                        "not set one (0 = greedy argmax); sampling is "
                        "on-device through a per-request seeded key, so "
                        "engine-crash replay regenerates identical tokens")
    p.add_argument("--top_k", type=int, default=0,
                   help="default top-k truncation for requests that do not "
                        "set one (0 = off)")
    p.add_argument("--tp", type=int, default=0,
                   help="tensor-parallel size (0/1 = single chip): shard "
                        "params and the KV page pool over the mesh 'model' "
                        "axis via the named sharding rules "
                        "(parallel/rules.py); needs n_heads and vocab "
                        "divisible by N, and N devices visible; tokens are "
                        "identical to single-chip serving")
    p.add_argument("--max_new_limit", type=int, default=64)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--tenant_tokens", type=float, default=0.0,
                   help="per-tenant token-bucket capacity (0 = unlimited)")
    p.add_argument("--tenant_tokens_per_s", type=float, default=0.0)
    p.add_argument("--tenant_concurrent", type=int, default=0,
                   help="per-tenant concurrent-request cap (0 = unlimited)")
    p.add_argument("--default_deadline_s", type=float, default=0.0,
                   help="total-latency deadline for requests that do not set "
                        "one (0 = none): expired requests are cancelled with "
                        "reason 'deadline' and their KV pages recycled; also "
                        "arms load-aware shedding (doomed requests rejected "
                        "at admission with retry_after_ms)")
    p.add_argument("--default_ttft_deadline_s", type=float, default=0.0,
                   help="time-to-first-token deadline default (0 = none); "
                        "misses are counted (the client-hedging signal), "
                        "not fatal")
    p.add_argument("--engine_restart_max", type=int, default=3,
                   help="engine crash/stall recoveries before the server "
                        "gives up and fails outstanding requests "
                        "('engine_error')")
    p.add_argument("--engine_stall_timeout_s", type=float, default=10.0,
                   help="supervisor stall watchdog: no decode-step progress "
                        "for this long with work pending restarts the engine")
    p.add_argument("--lease_s", type=float, default=30.0,
                   help="tenant lease; silent clients are evicted and their "
                        "queued requests cancelled")
    p.add_argument("--require_register", type=_str2bool, default=False,
                   help="reject requests without a registered tenant lease")
    p.add_argument(
        "--master_endpoints", default=None,
        help="routing master to health-check: its snapshot_failures / lease "
             "evictions / live+evicted trainer counts are forwarded in this "
             "server's stats() so deployments see control-plane degradation",
    )
    p.add_argument(
        "--router_endpoints", default=None,
        help="join a serving-router fleet (ISSUE 15) as a replica: register "
             "this server's endpoint with the router at host:port and renew "
             "the lease with load-snapshot heartbeats; a wedged engine "
             "self-fences so the router fails in-flight work over to a "
             "survivor. Pass a comma-separated primary,standby list "
             "(ISSUE 18): after consecutive heartbeat connection failures "
             "the agent rotates to the standby router and re-registers, "
             "whose takeover sweep re-adopts this replica's in-flight work",
    )
    p.add_argument(
        "--advertise_host", default=None,
        help="hostname the router should dial this replica back on "
             "(defaults to --host; set it when serving behind NAT/containers)",
    )
    p.add_argument("--stall_fence_s", type=float, default=5.0,
                   help="replica self-fence: with work pending and no engine "
                        "progress for this long (between steps), heartbeats "
                        "to the router stop so its lease can lapse")
    p.add_argument("--exit_on_drain", action="store_true",
                   help="exit cleanly when a router-ordered planned drain "
                        "completes (the autoscaler's spawn/drain replica "
                        "lifecycle, ISSUE 17)")
    # demo model shape knobs (ignored with --load)
    p.add_argument("--max_len", type=int, default=0,
                   help="demo model position-embedding capacity (0 = largest "
                        "bucket + max_new_limit); raise it with "
                        "--prefill_chunk so chunked prefill has headroom for "
                        "prompts beyond the buckets")
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--n_layers", type=int, default=2)
    p.add_argument("--d_model", type=int, default=32)
    p.add_argument("--n_heads", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)


def cmd_serve(args: argparse.Namespace) -> int:
    """Long-lived serving process: load once, serve until SIGTERM/SIGINT."""
    import signal as _signal
    import threading

    from paddle_tpu.serving.quota import TenantQuotas
    from paddle_tpu.serving.server import ServingServer

    quotas = None
    if args.tenant_tokens_per_s > 0 and args.tenant_tokens <= 0:
        # a refill rate without a bucket capacity is a no-op; saying nothing
        # would leave the operator believing rate limiting is on
        print(
            "--tenant_tokens_per_s needs --tenant_tokens (the bucket "
            "capacity); no token quota will be enforced", file=sys.stderr,
        )
    elif args.tenant_tokens > 0 and args.tenant_tokens_per_s <= 0:
        # the inverse surprise: a bucket that never refills is a LIFETIME
        # cap, not the documented rate limit — permanent lockout once drained
        print(
            "--tenant_tokens without --tenant_tokens_per_s never refills: "
            "each tenant gets a one-time lifetime budget of "
            f"{args.tenant_tokens:.0f} tokens", file=sys.stderr,
        )
    if args.tenant_tokens > 0 or args.tenant_concurrent > 0:
        quotas = TenantQuotas(
            token_capacity=args.tenant_tokens or None,
            tokens_per_s=args.tenant_tokens_per_s,
            max_concurrent=args.tenant_concurrent or None,
        )

    session = None
    if args.demo or args.load:
        from paddle_tpu.serving.session import ServingSession, make_demo_session

        buckets = tuple(
            int(b) for b in args.prefill_buckets.split(",") if b.strip()
        )
        session_kw = dict(
            max_slots=args.max_slots,
            page_size=args.page_size,
            num_pages=args.num_pages or None,
            prefill_buckets=buckets,
            prefill_chunk=args.prefill_chunk or None,
            prefix_cache=args.prefix_cache,
            prefix_cache_pages=args.prefix_cache_pages or None,
            speculate_k=args.speculate_k,
            default_temperature=args.temperature,
            default_top_k=args.top_k,
            max_new_limit=args.max_new_limit,
            max_queue=args.max_queue,
            quotas=quotas,
            default_deadline_s=args.default_deadline_s or None,
            default_ttft_deadline_s=args.default_ttft_deadline_s or None,
            engine_restart_max=args.engine_restart_max,
            engine_stall_timeout_s=args.engine_stall_timeout_s,
        )
        if args.load:
            from paddle_tpu.serving.model import ServableLM

            mesh = None
            if args.tp and args.tp > 1:
                from paddle_tpu.parallel.rules import make_tp_mesh

                mesh = make_tp_mesh(args.tp)
            model, params = ServableLM.load(args.load, mesh=mesh)
            session = ServingSession(model, params, **session_kw)
        else:
            session = make_demo_session(
                vocab=args.vocab, n_layers=args.n_layers,
                d_model=args.d_model, n_heads=args.n_heads, seed=args.seed,
                max_len=args.max_len or None, tp=args.tp,
                **session_kw,
            )

    gen_session = None
    if args.config:
        from paddle_tpu.config import parse_config
        from paddle_tpu.trainer.generation import GenerationSession

        pc = parse_config(args.config, args.config_args, emit_proto=False)
        gen_session = GenerationSession(
            pc, model_dir=args.model_dir,
            base_dir=os.path.dirname(os.path.abspath(args.config)),
        )

    if session is None and gen_session is None:
        print(
            "serve needs a model: --demo, --load=model.npz, or "
            "--config=conf.py [--model_dir=DIR]", file=sys.stderr,
        )
        return 2

    stop_evt = threading.Event()
    server = ServingServer(
        session=session, gen_session=gen_session,
        host=args.host, port=args.port, lease_s=args.lease_s,
        require_register=args.require_register,
        master_endpoints=args.master_endpoints,
        router_endpoints=args.router_endpoints,
        advertise_host=args.advertise_host,
        stall_fence_s=args.stall_fence_s,
        # autoscaler spawn/drain lifecycle (ISSUE 17): a router-ordered
        # drain completing shuts this process down cleanly, releasing the
        # chip the controller reclaimed
        on_drained=(stop_evt.set if args.exit_on_drain else None),
    ).start()
    _signal.signal(_signal.SIGTERM, lambda *_: stop_evt.set())
    _signal.signal(_signal.SIGINT, lambda *_: stop_evt.set())
    print(json.dumps({"role": "serve", "address": list(server.address)}),
          flush=True)
    stop_evt.wait()
    server.stop()
    if session is not None:
        print(json.dumps({"final_stats": session.stats()}), flush=True)
    return 0


def cmd_version(_args: argparse.Namespace) -> int:
    from paddle_tpu import __version__

    print(f"paddle-tpu {__version__}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="paddle_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_train = sub.add_parser("train", help="train/test/benchmark a config")
    _train_args(p_train)
    p_train.set_defaults(fn=cmd_train)

    p_dump = sub.add_parser("dump_config", help="print TrainerConfig text")
    p_dump.add_argument("--config", required=True)
    p_dump.add_argument("--config_args", default="")
    p_dump.set_defaults(fn=cmd_dump_config)

    p_merge = sub.add_parser("merge_model", help="fold config+params into one file")
    p_merge.add_argument("--config", required=True)
    p_merge.add_argument("--model_dir", required=True)
    p_merge.add_argument("--output", required=True)
    p_merge.add_argument("--config_args", default="")
    p_merge.set_defaults(fn=cmd_merge_model)

    p_serve = sub.add_parser(
        "serve", help="continuous-batching inference server"
    )
    _serve_args(p_serve)
    p_serve.set_defaults(fn=cmd_serve)

    p_ver = sub.add_parser("version")
    p_ver.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
