"""RecordIO chunked dataset files (csrc/recordio.cc; Go recordio parity).

The v2 dataset pipeline's `convert` (python/paddle/v2/dataset/common.py)
shards datasets into recordio chunks that the elastic master hands out as
tasks. Native reader/writer via ctypes with a pure-Python implementation of
the SAME on-disk format as fallback (and as the cross-check oracle in tests,
the CPU-reference idiom of SURVEY §4)."""

from __future__ import annotations

import ctypes as C
import os
import pickle
import struct
import zlib
from typing import Any, Callable, Iterable, Iterator, List, Optional

from paddle_tpu.runtime import native

_MAGIC = 0x50545243  # "PTRC"
_HEAD = struct.Struct("<IIII")
_LEN = struct.Struct("<I")


class Writer:
    """Writes length-prefixed records into CRC-checked chunks."""

    def __init__(self, path: str, chunk_records: int = 1000, chunk_bytes: int = 8 << 20):
        self._native = None
        self._py = None
        L = native.lib()
        if L is not None:
            h = L.pt_recordio_writer_open(
                path.encode(), chunk_records, chunk_bytes
            )
            if not h:
                raise OSError(f"cannot open {path} for writing")
            self._native = (L, h)
        else:
            self._py = _PyWriter(path, chunk_records, chunk_bytes)

    def write(self, record: bytes) -> None:
        if self._native is not None:
            L, h = self._native
            rc = L.pt_recordio_write(h, record, len(record))
            if rc == -2:
                raise ValueError(
                    f"record of {len(record)} bytes exceeds the recordio "
                    f"format limit ({MAX_CHUNK_BYTES} bytes per chunk)"
                )
            if rc != 0:
                raise OSError("recordio write failed")
        else:
            self._py.write(record)

    def close(self) -> None:
        if self._native is not None:
            L, h = self._native
            self._native = None
            if L.pt_recordio_writer_close(h) != 0:
                raise OSError("recordio close failed")
        elif self._py is not None:
            self._py.close()
            self._py = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Reader:
    """Iterates records; corrupt chunks are skipped and counted."""

    def __init__(self, path: str):
        self.path = path
        self._native = None
        self._py = None
        L = native.lib()
        if L is not None:
            h = L.pt_recordio_reader_open(path.encode())
            if not h:
                raise OSError(f"cannot open {path}")
            self._native = (L, h)
        else:
            self._py_error_box = [0]
            self._py = _py_read(path, self._py_error_box)

    def __iter__(self) -> Iterator[bytes]:
        if self._native is not None:
            L, h = self._native
            out = C.c_void_p()
            while True:
                n = L.pt_recordio_next(h, C.byref(out))
                if n < 0:
                    return
                yield C.string_at(out.value, n)
        else:
            yield from self._py

    @property
    def errors(self) -> int:
        if self._native is not None:
            L, h = self._native
            return int(L.pt_recordio_errors(h))
        return self._py_error_box[0]

    def close(self) -> None:
        if self._native is not None:
            L, h = self._native
            self._native = None
            L.pt_recordio_reader_close(h)


# -- pure-Python same-format implementation ---------------------------------


# shared format limit — keep in sync with kMaxChunkBytes in csrc/recordio.cc:
# writers reject records the format cannot represent; readers treat a larger
# data_len as corruption
MAX_CHUNK_BYTES = 1 << 30


class _PyWriter:
    def __init__(self, path: str, chunk_records: int, chunk_bytes: int):
        self.f = open(path, "wb")
        self.chunk_records = chunk_records
        self.chunk_bytes = chunk_bytes
        self.pending: List[bytes] = []
        self.pending_bytes = 0

    def write(self, record: bytes) -> None:
        if len(record) + _LEN.size > MAX_CHUNK_BYTES:
            raise ValueError(
                f"record of {len(record)} bytes exceeds the recordio format "
                f"limit ({MAX_CHUNK_BYTES} bytes per chunk)"
            )
        self.pending.append(record)
        self.pending_bytes += len(record)
        if (
            len(self.pending) >= self.chunk_records
            or self.pending_bytes >= self.chunk_bytes
        ):
            self._flush()

    def _flush(self) -> None:
        if not self.pending:
            return
        data = b"".join(_LEN.pack(len(r)) + r for r in self.pending)
        self.f.write(
            _HEAD.pack(_MAGIC, len(self.pending), len(data), zlib.crc32(data))
        )
        self.f.write(data)
        self.pending, self.pending_bytes = [], 0

    def close(self) -> None:
        self._flush()
        self.f.close()


def _py_read(path: str, error_box: Optional[List[int]] = None) -> Iterator[bytes]:
    """Same skip-and-count corrupt-chunk semantics as the native reader;
    error_box[0] (when given) accumulates the bad-chunk count."""

    def bad() -> None:
        if error_box is not None:
            error_box[0] += 1

    with open(path, "rb") as f:
        while True:
            head = f.read(_HEAD.size)
            if len(head) < _HEAD.size:
                return
            magic, n_rec, data_len, crc = _HEAD.unpack(head)
            if magic != _MAGIC:
                bad()  # framing lost: stop rather than scan (native parity)
                return
            if data_len > MAX_CHUNK_BYTES:
                bad()  # over format limit: corruption (native parity)
                return
            data = f.read(data_len)
            if len(data) < data_len:
                bad()
                return
            if zlib.crc32(data) != crc:
                bad()
                continue  # skip corrupt chunk
            off = 0
            for _ in range(n_rec):
                (ln,) = _LEN.unpack_from(data, off)
                off += _LEN.size
                yield data[off : off + ln]
                off += ln


# -- dataset conversion (python/paddle/v2/dataset convert parity) -----------


def convert(
    output_dir: str,
    reader: Callable[[], Iterable[Any]],
    records_per_file: int = 4096,
    prefix: str = "shard",
    serialize: Callable[[Any], bytes] = lambda s: pickle.dumps(s, protocol=4),
) -> List[str]:
    """Shard a sample reader into recordio files; returns the shard paths."""
    os.makedirs(output_dir, exist_ok=True)
    paths: List[str] = []
    w: Optional[Writer] = None
    count = 0
    for sample in reader():
        if w is None:
            p = os.path.join(output_dir, f"{prefix}-{len(paths):05d}.recordio")
            paths.append(p)
            w = Writer(p)
        w.write(serialize(sample))
        count += 1
        if count >= records_per_file:
            w.close()
            w, count = None, 0
    if w is not None:
        w.close()
    return paths


def read_shards(
    paths: Iterable[str],
    deserialize: Callable[[bytes], Any] = pickle.loads,
) -> Iterator[Any]:
    for p in paths:
        r = Reader(p)
        try:
            for rec in r:
                yield deserialize(rec)
        finally:
            r.close()
