"""Native runtime (C++ csrc/ via ctypes): host memory pool, recordio dataset
shards, elastic task master. SURVEY §2.1 paddle/memory, §2.2 go/master +
recordio, §5 failure detection / checkpointed task queues."""

from paddle_tpu.runtime.native import available
from paddle_tpu.runtime import recordio
from paddle_tpu.runtime.master import (
    MasterClient,
    MasterServer,
    TaskMaster,
    cluster_reader,
)

__all__ = [
    "available", "recordio", "TaskMaster", "MasterServer", "MasterClient",
    "cluster_reader",
]


def HostPool(*args, **kwargs):
    from paddle_tpu.runtime.allocator import HostPool as _HostPool

    return _HostPool(*args, **kwargs)
