"""Host memory pool over the native buddy allocator (paddle/memory parity,
memory.cc:61 GetGPUBuddyAllocator / detail/buddy_allocator.h:33).

Serves numpy staging buffers for the feed path: `pool.ndarray(shape, dtype)`
returns an array backed by pool memory so repeated batch assembly reuses the
same arena instead of churning the Python heap.

Safety: `release(arr)` only MARKS the block releasable — the underlying
pt_pool_free happens when the last numpy view over the block is garbage
collected (weakref finalizer on the base array), so a released-but-still-
referenced buffer can never be handed out again while readable (no
use-after-free). `close()` refuses while any view is alive."""

from __future__ import annotations

import ctypes as C
import weakref
from typing import Dict, Sequence

import numpy as np

from paddle_tpu.runtime import native


class HostPool:
    def __init__(self, total_bytes: int = 256 << 20, min_block: int = 256):
        L = native.lib()
        if L is None:
            raise RuntimeError("native runtime unavailable (g++ build failed?)")
        self._lib = L
        self._pool = L.pt_pool_create(min_block, total_bytes)
        if not self._pool:
            raise MemoryError(f"cannot create {total_bytes}-byte host pool")
        self._live: Dict[int, int] = {}  # addr -> nbytes
        # addr -> finalizer on the base view; present only for ndarray() blocks
        self._viewed: Dict[int, weakref.finalize] = {}
        self._releasable: set = set()  # release()d, awaiting view death

    def alloc(self, nbytes: int) -> int:
        addr = self._lib.pt_pool_alloc(self._pool, nbytes)
        if not addr:
            raise MemoryError(f"host pool exhausted allocating {nbytes} bytes")
        self._live[addr] = nbytes
        return addr

    def free(self, addr: int) -> None:
        if addr in self._viewed:
            raise ValueError(
                f"block {addr:#x} is backing a numpy view; use release(arr)"
            )
        if self._lib.pt_pool_free(self._pool, addr) != 0:
            raise ValueError(f"invalid free of {addr:#x}")
        self._live.pop(addr, None)
        # never let a stale releasable flag survive address reuse
        self._releasable.discard(addr)

    def ndarray(self, shape: Sequence[int], dtype=np.float32) -> np.ndarray:
        """A numpy array over pool memory. Call release(arr) when done; the
        block returns to the pool once every view of it has been collected."""
        dt = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dt.itemsize
        addr = self.alloc(max(nbytes, 1))
        buf = (C.c_char * nbytes).from_address(addr)
        base = np.frombuffer(buf, dtype=dt)
        base.flags.writeable = True
        # every derived view (reshape below, user slices) keeps `base` alive
        # through its .base chain, so this fires only when no view remains
        self._viewed[addr] = weakref.finalize(base, self._on_views_dead, addr)
        return base.reshape(shape)

    def _on_views_dead(self, addr: int) -> None:
        try:
            self._viewed.pop(addr, None)
            if addr in self._releasable:
                self._releasable.discard(addr)
                if self._pool:
                    self._lib.pt_pool_free(self._pool, addr)
                self._live.pop(addr, None)
        except Exception:
            pass  # interpreter shutdown

    def release(self, arr: np.ndarray) -> None:
        """Mark the block backing `arr` for return to the pool. The actual
        free is deferred until all views die (CPython refcounting makes that
        immediate once the caller drops its reference)."""
        addr = arr.__array_interface__["data"][0]
        if addr not in self._live:
            raise ValueError("array was not allocated from this pool")
        if addr not in self._viewed:
            # raw alloc() block (no tracked view -> nothing would ever fire
            # the deferred free): the caller owns its lifetime via free()
            raise ValueError(
                f"block {addr:#x} was not created by ndarray(); use free(addr)"
            )
        if addr in self._releasable:
            raise ValueError(f"double release of block {addr:#x}")
        self._releasable.add(addr)

    def stats(self) -> Dict[str, int]:
        out = (C.c_uint64 * 5)()
        self._lib.pt_pool_stats(self._pool, out)
        return {
            "arena_bytes": out[0],
            "in_use": out[1],
            "peak": out[2],
            "n_allocs": out[3],
            "n_frees": out[4],
        }

    def close(self) -> None:
        if self._pool:
            if self._viewed:
                raise RuntimeError(
                    f"cannot close host pool: {len(self._viewed)} numpy "
                    f"view(s) still alive over pool memory"
                )
            self._lib.pt_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            # never munmap under live views even during teardown — leaking at
            # process end beats a segfault
            if getattr(self, "_viewed", None):
                return
            self.close()
        except Exception:
            pass
