"""Host memory pool over the native buddy allocator (paddle/memory parity,
memory.cc:61 GetGPUBuddyAllocator / detail/buddy_allocator.h:33).

Serves numpy staging buffers for the feed path: `pool.ndarray(shape, dtype)`
returns an array backed by pool memory so repeated batch assembly reuses the
same arena instead of churning the Python heap."""

from __future__ import annotations

import ctypes as C
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.runtime import native


class HostPool:
    def __init__(self, total_bytes: int = 256 << 20, min_block: int = 256):
        L = native.lib()
        if L is None:
            raise RuntimeError("native runtime unavailable (g++ build failed?)")
        self._lib = L
        self._pool = L.pt_pool_create(min_block, total_bytes)
        if not self._pool:
            raise MemoryError(f"cannot create {total_bytes}-byte host pool")
        self._live: Dict[int, int] = {}  # addr -> nbytes

    def alloc(self, nbytes: int) -> int:
        addr = self._lib.pt_pool_alloc(self._pool, nbytes)
        if not addr:
            raise MemoryError(f"host pool exhausted allocating {nbytes} bytes")
        self._live[addr] = nbytes
        return addr

    def free(self, addr: int) -> None:
        if self._lib.pt_pool_free(self._pool, addr) != 0:
            raise ValueError(f"invalid free of {addr:#x}")
        self._live.pop(addr, None)

    def ndarray(self, shape: Sequence[int], dtype=np.float32) -> np.ndarray:
        """A numpy array over pool memory. Call release(arr) when done."""
        dt = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dt.itemsize
        addr = self.alloc(max(nbytes, 1))
        buf = (C.c_char * nbytes).from_address(addr)
        arr = np.frombuffer(buf, dtype=dt).reshape(shape)
        arr.flags.writeable = True
        self._live[addr] = nbytes
        return arr

    def release(self, arr: np.ndarray) -> None:
        # the view's data pointer is the pool block's base address
        addr = arr.__array_interface__["data"][0]
        if addr not in self._live:
            raise ValueError("array was not allocated from this pool")
        self.free(addr)

    def stats(self) -> Dict[str, int]:
        out = (C.c_uint64 * 5)()
        self._lib.pt_pool_stats(self._pool, out)
        return {
            "arena_bytes": out[0],
            "in_use": out[1],
            "peak": out[2],
            "n_allocs": out[3],
            "n_frees": out[4],
        }

    def close(self) -> None:
        if self._pool:
            self._lib.pt_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
