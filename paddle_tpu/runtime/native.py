"""ctypes bindings for libpaddle_tpu_rt (csrc/).

The reference binds its native core via SWIG/pybind11; here the C ABI +
ctypes avoids a binding-generator dependency (pybind11 is not in the image)
while keeping the runtime genuinely native."""

from __future__ import annotations

import ctypes as C
from typing import Optional

from paddle_tpu.runtime.build import ensure_built

_lib: Optional[C.CDLL] = None
_tried = False


def lib() -> Optional[C.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    so = ensure_built()
    if so is None:
        return None
    L = C.CDLL(so)
    # allocator
    L.pt_pool_create.restype = C.c_void_p
    L.pt_pool_create.argtypes = [C.c_size_t, C.c_size_t]
    L.pt_pool_alloc.restype = C.c_void_p
    L.pt_pool_alloc.argtypes = [C.c_void_p, C.c_size_t]
    L.pt_pool_free.restype = C.c_int
    L.pt_pool_free.argtypes = [C.c_void_p, C.c_void_p]
    L.pt_pool_stats.restype = None
    L.pt_pool_stats.argtypes = [C.c_void_p, C.POINTER(C.c_uint64)]
    L.pt_pool_destroy.restype = None
    L.pt_pool_destroy.argtypes = [C.c_void_p]
    # recordio
    L.pt_recordio_writer_open.restype = C.c_void_p
    L.pt_recordio_writer_open.argtypes = [C.c_char_p, C.c_int, C.c_size_t]
    L.pt_recordio_write.restype = C.c_int
    L.pt_recordio_write.argtypes = [C.c_void_p, C.c_char_p, C.c_uint64]
    L.pt_recordio_writer_close.restype = C.c_int
    L.pt_recordio_writer_close.argtypes = [C.c_void_p]
    L.pt_recordio_reader_open.restype = C.c_void_p
    L.pt_recordio_reader_open.argtypes = [C.c_char_p]
    L.pt_recordio_next.restype = C.c_int64
    L.pt_recordio_next.argtypes = [C.c_void_p, C.POINTER(C.c_void_p)]
    L.pt_recordio_errors.restype = C.c_uint64
    L.pt_recordio_errors.argtypes = [C.c_void_p]
    L.pt_recordio_reader_close.restype = None
    L.pt_recordio_reader_close.argtypes = [C.c_void_p]
    # master
    L.pt_master_create.restype = C.c_void_p
    L.pt_master_create.argtypes = [C.c_double, C.c_int]
    L.pt_master_set_dataset.restype = None
    L.pt_master_set_dataset.argtypes = [C.c_void_p, C.c_char_p, C.c_int]
    L.pt_master_get_task.restype = C.c_int64
    L.pt_master_get_task.argtypes = [C.c_void_p, C.c_char_p, C.c_int64]
    L.pt_master_task_finished.restype = C.c_int
    L.pt_master_task_finished.argtypes = [C.c_void_p, C.c_int64]
    L.pt_master_task_failed.restype = C.c_int
    L.pt_master_task_failed.argtypes = [C.c_void_p, C.c_int64]
    L.pt_master_pass_finished.restype = C.c_int
    L.pt_master_pass_finished.argtypes = [C.c_void_p, C.c_int]
    L.pt_master_stats.restype = None
    L.pt_master_stats.argtypes = [C.c_void_p, C.POINTER(C.c_int64)]
    L.pt_master_snapshot.restype = C.c_int
    L.pt_master_snapshot.argtypes = [C.c_void_p, C.c_char_p]
    L.pt_master_restore.restype = C.c_int
    L.pt_master_restore.argtypes = [C.c_void_p, C.c_char_p]
    L.pt_master_destroy.restype = None
    L.pt_master_destroy.argtypes = [C.c_void_p]
    _lib = L
    return _lib


def available() -> bool:
    return lib() is not None
