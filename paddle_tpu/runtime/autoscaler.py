"""Goodput-driven autoscaler: one resource plane for training + serving.

ISSUE 17 closes the obs→resize loop ROADMAP item 3 describes: every signal
and every lever already exists — fleet metrics aggregation (PR 7), live
elastic resize epochs (PR 8), the load estimator's queue-wait / shed /
deadline-miss signals (PR 10), planned replica drain (PR 15) — and this
module is the controller that connects them, so training borrows chips from
an idle serving fleet and hands them back under load.

Architecture (three pieces, separable on purpose):

  * `ScaleDecider` — the PURE decision engine. No RPCs, no clock reads, no
    threads: every input (including `now`) is passed in, so the hysteresis /
    cooldown / flap-suppression / backoff behavior is deterministic and
    unit-testable from synthetic metric streams (tests/test_autoscaler.py).
  * `ReplicaSpawner` — the serving GROW lever: launches a real
    `python -m paddle_tpu serve --router_endpoints ...` subprocess that
    registers itself with the router (fire-and-forget: the controller never
    blocks on a spawn; the new replica shows up in the next observed
    snapshot or it doesn't). Drills substitute an in-process spawner
    through the same one-method seam.
  * `AutoscalerController` — the reconcile loop: observe → decide →
    actuate, once per tick, on its own thread.

Robustness contract (the tentpole's point):

  * STATELESS-RECONCILING: the controller journals nothing. Desired state
    is re-derived every tick from OBSERVED state — the router's replica
    views, the master's resize-epoch info (whose `world` IS the current
    training world, seeded via `MasterServer(initial_world=)`). Kill the
    controller mid-epoch and restart it: the fresh instance adopts the
    in-flight epoch from `stats()["resize"]` (resize_busy gates the train
    lever) and starts from a conservative post-start quiet period, so the
    restart changes no outcome.
  * HEARTBEAT-PIGGYBACK DISCIPLINE ("RPC Considered Harmful", PAPERS.md):
    the controller adds ZERO RPCs to any hot path. Serving signals ride
    replica→router heartbeats (fleet.LOAD_KEYS) and training signals ride
    trainer→master heartbeats (the TTL'd fleet aggregate); the controller
    polls the two existing `stats` endpoints once per tick — a cold path —
    and every lever it pulls (drain / resize / spawn) is a per-DECISION
    call, rate-limited by cooldowns. The decision path itself
    (`ScaleDecider.decide`) makes no calls at all; tests/test_lint_hotloop
    pins both sides.
  * DEGRADED MODE IS TODAY'S STATIC FLEET: an unreachable router or master
    leaves the last observed snapshot cached and suppresses actuation; a
    dead controller simply stops pulling levers. Serving and training
    liveness never depend on this process — the seeded `controller_kill` /
    `scale_decision_stall` fault sites (core/faults.py) drill exactly that.
  * BACKOFF, NOT HOT RETRY: a resize the master rejects (an epoch already
    in flight) or that times out backs the train lever off exponentially;
    a completed epoch resets the backoff.

Gate: `benchmarks/chaos_bench.py --mode autoscale` (idle → 2× burst → idle
offered-load schedule, controller killed + restarted mid-epoch; goodput
retention, chips-used, zero lost requests, exactly-once task accounting).

CLI:
  python -m paddle_tpu.runtime.autoscaler serve \
      --router HOST:PORT --master HOST:PORT --chips 8 [--tick_s 1.0] ...
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.core import faults
from paddle_tpu.core import stats as core_stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace
from paddle_tpu.runtime.election import mint_instance_token, watch_primary
from paddle_tpu.runtime.master import EndpointsLike, MasterClient

import logging

log = logging.getLogger("paddle_tpu.runtime.autoscaler")


class ScaleConfig:
    """Thresholds and rate limits for the decision engine. Everything is a
    plain attribute so tests and the CLI can pin exact values."""

    def __init__(
        self,
        *,
        chips_total: int = 8,
        chips_per_replica: int = 1,
        min_replicas: int = 1,
        max_replicas: int = 8,
        train_min_world: int = 0,
        train_max_world: int = 8,
        # hysteresis band on the router's fleet queue-wait estimate, plus
        # shed/deadline-miss deltas (any shed tick counts as pressure)
        high_wait_s: float = 0.5,
        low_wait_s: float = 0.05,
        high_ticks: int = 2,
        low_ticks: int = 4,
        # per-lever cooldowns: minimum spacing between two actions on the
        # same lever ('serving' = spawn/drain, 'train' = resize)
        serving_cooldown_s: float = 8.0,
        train_cooldown_s: float = 10.0,
        # flap suppressor: an action REVERSING the lever's previous
        # direction inside this window is suppressed outright — oscillating
        # load cannot thrash resize epochs faster than the window
        flap_window_s: float = 20.0,
        # post-start quiet period: a (re)started controller observes for
        # this long before its first action — the stateless-reconcile
        # discipline's substitute for a journal of recent actions
        startup_quiet_s: float = 2.0,
        # backoff after a rejected/timed-out resize: base doubling, capped
        backoff_base_s: float = 5.0,
        backoff_max_s: float = 120.0,
        resize_timeout_s: float = 60.0,
        drain_deadline_s: float = 30.0,
    ):
        self.chips_total = int(chips_total)
        self.chips_per_replica = int(chips_per_replica)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.train_min_world = int(train_min_world)
        self.train_max_world = int(train_max_world)
        self.high_wait_s = float(high_wait_s)
        self.low_wait_s = float(low_wait_s)
        self.high_ticks = int(high_ticks)
        self.low_ticks = int(low_ticks)
        self.serving_cooldown_s = float(serving_cooldown_s)
        self.train_cooldown_s = float(train_cooldown_s)
        self.flap_window_s = float(flap_window_s)
        self.startup_quiet_s = float(startup_quiet_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.resize_timeout_s = float(resize_timeout_s)
        self.drain_deadline_s = float(drain_deadline_s)

    def cooldown_s(self, lever: str) -> float:
        return (self.train_cooldown_s if lever == "train"
                else self.serving_cooldown_s)


class Action:
    """One lever pull the decider wants: lever is 'serving' or 'train',
    direction 'grow' or 'shrink', payload the lever-specific argument
    (target world for train, nothing for serving — the controller picks
    the drain victim from observed state)."""

    __slots__ = ("lever", "direction", "payload")

    def __init__(self, lever: str, direction: str, payload: Optional[dict] = None):
        self.lever = lever
        self.direction = direction
        self.payload = payload or {}

    def __repr__(self):
        return f"Action({self.lever}:{self.direction} {self.payload})"


class Signals:
    """One tick's observed fleet state, assembled by the controller from
    CACHED snapshots (never fetched inside decide). Tests build these by
    hand — plain attributes, no clocks, no sockets."""

    __slots__ = (
        "queue_wait_s", "shed_delta", "miss_delta",
        "live_replicas", "draining_replicas",
        "train_world", "resize_busy",
    )

    def __init__(
        self,
        queue_wait_s: float = 0.0,
        shed_delta: int = 0,
        miss_delta: int = 0,
        live_replicas: int = 0,
        draining_replicas: int = 0,
        train_world: int = 0,
        resize_busy: bool = False,
    ):
        self.queue_wait_s = float(queue_wait_s)
        self.shed_delta = int(shed_delta)
        self.miss_delta = int(miss_delta)
        self.live_replicas = int(live_replicas)
        self.draining_replicas = int(draining_replicas)
        self.train_world = int(train_world)
        self.resize_busy = bool(resize_busy)


class ScaleDecider:
    """The pure decision engine: hysteresis + per-lever cooldowns + flap
    suppression + resize backoff. At most ONE action per tick — sequencing
    (shrink training, wait for the freed chip to show up in observed state,
    then spawn) emerges from reconciliation instead of a multi-step plan
    that a crash could orphan.

    All state here is advisory rate-limiting (streak counters, last-action
    stamps, backoff): losing it on a controller restart is SAFE — the fresh
    instance starts conservative (startup_quiet_s) and re-derives desired
    state from the signals alone."""

    def __init__(self, config: Optional[ScaleConfig] = None):
        self.cfg = config or ScaleConfig()
        self._high_streak = 0
        self._low_streak = 0
        self._started_at: Optional[float] = None
        # lever -> (direction, monotonic stamp) of the last ADMITTED action
        self._last_action: Dict[str, Tuple[str, float]] = {}
        self._resize_failures = 0
        self._backoff_until = 0.0
        self.suppressed: Dict[str, int] = {}
        self.decisions = 0

    # -- backoff feedback (controller calls these from actuation results) ---
    def note_resize_rejected(self, now: float) -> float:
        """A resize the master rejected (epoch in flight) or that timed
        out: back the train lever off exponentially instead of retrying
        hot. Returns the backoff horizon."""
        self._resize_failures += 1
        delay = min(
            self.cfg.backoff_base_s * (2.0 ** (self._resize_failures - 1)),
            self.cfg.backoff_max_s,
        )
        self._backoff_until = max(self._backoff_until, now + delay)
        return self._backoff_until

    def note_resize_ok(self) -> None:
        self._resize_failures = 0
        self._backoff_until = 0.0

    @property
    def resize_failures(self) -> int:
        return self._resize_failures

    # -- the decision -------------------------------------------------------
    def _suppress(self, reason: str) -> List[Action]:
        self.suppressed[reason] = self.suppressed.get(reason, 0) + 1
        obs_metrics.observe_scale_suppressed(reason)
        return []

    def _admit(self, action: Action, now: float) -> List[Action]:
        """Rate-limit gate: startup quiet period, per-lever cooldown, flap
        window, train-lever backoff. An admitted action resets BOTH streaks
        (one action per pressure episode; the next episode re-accumulates)."""
        if now - (self._started_at or now) < self.cfg.startup_quiet_s:
            return self._suppress("startup")
        if action.lever == "train" and now < self._backoff_until:
            return self._suppress("backoff")
        last = self._last_action.get(action.lever)
        if last is not None:
            last_dir, last_ts = last
            if now - last_ts < self.cfg.cooldown_s(action.lever):
                return self._suppress("cooldown")
            if (last_dir != action.direction
                    and now - last_ts < self.cfg.flap_window_s):
                return self._suppress("flap")
        self._last_action[action.lever] = (action.direction, now)
        self._high_streak = 0
        self._low_streak = 0
        self.decisions += 1
        obs_metrics.observe_scale_decision(action.lever, action.direction)
        return [action]

    def decide(self, sig: Signals, now: float) -> List[Action]:
        """One tick: classify pressure, accumulate hysteresis streaks, and
        emit at most one admitted action. Pure — no RPCs, no clock reads
        (`now` is the controller's once-per-tick stamp); the hot-loop lint
        pins this (tests/test_lint_hotloop.py)."""
        cfg = self.cfg
        if self._started_at is None:
            self._started_at = now
        high = (
            sig.queue_wait_s > cfg.high_wait_s
            or sig.shed_delta > 0
            or sig.miss_delta > 0
        )
        low = (
            sig.queue_wait_s < cfg.low_wait_s
            and sig.shed_delta == 0
            and sig.miss_delta == 0
        )
        self._high_streak = self._high_streak + 1 if high else 0
        self._low_streak = self._low_streak + 1 if low else 0

        # chip ledger from OBSERVED state only; a draining replica still
        # holds its chip until it leaves the fleet view
        serving_chips = (
            (sig.live_replicas + sig.draining_replicas)
            * cfg.chips_per_replica
        )
        free_chips = cfg.chips_total - serving_chips - sig.train_world

        if self._high_streak >= cfg.high_ticks:
            # serving under pressure: get a replica up. Spawn when a chip
            # is free; otherwise reclaim one from training first — the
            # spawn happens on a later tick once the shrunk world is
            # observed (reconciliation, not a journaled plan)
            if (sig.live_replicas + sig.draining_replicas < cfg.max_replicas
                    and free_chips >= cfg.chips_per_replica):
                return self._admit(Action("serving", "grow"), now)
            if (sig.train_world > cfg.train_min_world
                    and not sig.resize_busy):
                return self._admit(
                    Action("train", "shrink",
                           {"world": sig.train_world - 1}), now,
                )
            return []
        if self._low_streak >= cfg.low_ticks:
            # serving idle: hand a chip to training. Drain first; grow the
            # training world only out of chips already observed free
            if sig.live_replicas > cfg.min_replicas:
                if sig.draining_replicas == 0:
                    return self._admit(Action("serving", "shrink"), now)
                return []  # a drain is already in flight; let it land
            if (free_chips >= 1 and sig.train_world < cfg.train_max_world
                    and not sig.resize_busy):
                return self._admit(
                    Action("train", "grow",
                           {"world": sig.train_world + 1}), now,
                )
        return []


class ReplicaSpawner:
    """Default serving GROW lever: launch a `python -m paddle_tpu serve`
    subprocess pointed at the router. Fire-and-forget — the child warms up,
    registers itself with the router, and appears in the next observed
    snapshot; the controller never blocks on it. `extra_args` carries the
    model/engine flags of the deployment (the controller has no opinion on
    what a replica serves)."""

    def __init__(
        self,
        router_endpoints: EndpointsLike,
        extra_args: Sequence[str] = ("--demo",),
        env: Optional[Dict[str, str]] = None,
    ):
        eps = router_endpoints
        if isinstance(eps, (list, tuple)) and eps and not isinstance(
            eps[0], (list, tuple)
        ):
            eps = [eps]  # one (host, port) pair
        self.router_arg = ",".join(f"{h}:{p}" for h, p in eps)
        self.extra_args = list(extra_args)
        self.env = env
        self._procs: List[Any] = []
        self.spawned = 0

    def spawn(self):
        import os
        import subprocess
        import sys

        cmd = [
            sys.executable, "-m", "paddle_tpu", "serve",
            "--port", "0", "--router_endpoints", self.router_arg,
            "--exit_on_drain",
        ] + self.extra_args
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        self._procs.append(proc)
        self.spawned += 1
        log.warning("spawned serving replica (pid %d)", proc.pid)
        return proc

    def reap(self) -> int:
        """Drop exited children from the ledger; returns live child count."""
        self._procs = [p for p in self._procs if p.poll() is None]
        return len(self._procs)

    def stop_all(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=10.0)
            except Exception:
                p.kill()
        self._procs = []


class AutoscalerController:
    """The reconcile loop: observe (cached `stats` polls) → decide (pure)
    → actuate (per-decision lever RPCs), once per `tick_s`.

    Clients speak the shared line-JSON RPC protocol (MasterClient works
    against both the router and the master). Either endpoint may be absent:
    no router disables the serving lever, no master disables the train
    lever — the controller degrades, it never blocks. Drills inject
    in-process client stand-ins through `router_client`/`master_client`
    (anything with .call/.close)."""

    def __init__(
        self,
        router_endpoints: Optional[EndpointsLike] = None,
        master_endpoints: Optional[EndpointsLike] = None,
        *,
        config: Optional[ScaleConfig] = None,
        spawner: Optional[Any] = None,
        tick_s: float = 1.0,
        client_kw: Optional[dict] = None,
        router_client: Optional[Any] = None,
        master_client: Optional[Any] = None,
        liveness_port: Optional[int] = None,
        liveness_host: str = "127.0.0.1",
    ):
        kw = client_kw or {"timeout": 5.0, "retries": 2}
        self.cfg = config or ScaleConfig()
        self.decider = ScaleDecider(self.cfg)
        self.spawner = spawner
        self.tick_s = float(tick_s)
        self._router = router_client or (
            MasterClient(router_endpoints, **kw)
            if router_endpoints is not None else None
        )
        self._master = master_client or (
            MasterClient(master_endpoints, **kw)
            if master_endpoints is not None else None
        )
        # cached snapshots: observation failures reuse the last good view
        # (and suppress actuation) — the controller NEVER blocks a decision
        # on a live round trip beyond the tick's one cold-path stats poll
        self._router_snap: Optional[Dict[str, Any]] = None
        self._master_snap: Optional[Dict[str, Any]] = None
        self._prev_shed: Optional[int] = None
        self._prev_miss: Optional[int] = None
        # (instance, epoch, deadline) of the resize this controller
        # announced and is watching for completion/timeout
        self._resize_inflight: Optional[Tuple[str, int, float]] = None
        self.ticks = 0
        self.observe_failures = 0
        self.actions: List[str] = []
        self.dead = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # incarnation identity (ISSUE 18): a standby that takes this
        # controller's place overwrites it with its election token
        self.instance = mint_instance_token()
        # liveness port (ISSUE 18): the controller has no RPC surface of
        # its own, so an AutoscalerStandby needs SOMETHING to probe. This
        # bare accept-and-close listener is held open exactly as long as
        # the reconcile loop is healthy — closed when the loop exits for
        # ANY reason, including the controller_kill chaos site — so a TCP
        # probe against it answers "is the primary controller alive".
        self.liveness_address: Optional[Tuple[str, int]] = None
        self._liveness_sock = None
        if liveness_port is not None:
            import socket

            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((liveness_host, int(liveness_port)))
            s.listen(8)
            self._liveness_sock = s
            self.liveness_address = s.getsockname()
            threading.Thread(
                target=self._liveness_accept, name="autoscaler-liveness",
                daemon=True,
            ).start()

    def _liveness_accept(self) -> None:
        """Accept-and-close loop for the liveness port; exits when the
        socket is closed (loop death or stop())."""
        sock = self._liveness_sock  # _close_liveness nulls the attr
        while True:
            try:
                conn, _ = sock.accept()
                conn.close()
            except OSError:
                return

    def _close_liveness(self) -> None:
        import socket

        s, self._liveness_sock = self._liveness_sock, None
        if s is not None:
            try:
                # shutdown() first: close() alone does not wake a thread
                # blocked in accept() — the in-flight syscall pins the
                # socket open and the port would accept one more probe
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # -- observation (cold path: one stats poll per endpoint per tick) ------
    def _observe(self, now: float) -> Optional[Signals]:
        stale = False
        if self._router is not None:
            try:
                # rpc-ok: once-per-tick cold-path poll of the router's
                # piggyback-fed stats — never on a dispatch/decode path
                self._router_snap = self._router.call("stats")
            except ConnectionError:
                self.observe_failures += 1
                stale = True
        if self._master is not None:
            try:
                # rpc-ok: once-per-tick cold-path poll of the master's
                # TTL'd fleet aggregate + resize-epoch info
                self._master_snap = self._master.call("stats")
            except ConnectionError:
                self.observe_failures += 1
                stale = True
        if stale or (self._router_snap is None and self._master_snap is None):
            # degrade to static fleet: observed state is stale, so no
            # action this tick — serving/training liveness is unaffected
            return None

        rs = self._router_snap or {}
        reps = rs.get("replicas", [])
        live = [r for r in reps if r.get("state") == "live"]
        draining = [r for r in reps if r.get("state") == "draining"]
        # fleet-wide shed/deadline-miss: the router's own fleet-wide sheds
        # plus every live replica's piggybacked counters (fleet.LOAD_KEYS)
        shed = int(rs.get("shed", 0) or 0) + sum(
            int(r.get("load", {}).get("shed", 0) or 0) for r in live
        )
        miss = sum(
            int(r.get("load", {}).get("deadline_misses", 0) or 0)
            for r in live
        )
        # replica churn makes the fleet sums non-monotonic (a drained
        # replica's counters leave the view): clamp deltas at zero
        shed_delta = max(0, shed - (self._prev_shed
                                    if self._prev_shed is not None else shed))
        miss_delta = max(0, miss - (self._prev_miss
                                    if self._prev_miss is not None else miss))
        self._prev_shed, self._prev_miss = shed, miss

        ms = self._master_snap or {}
        rz = ms.get("resize", {}) or {}
        return Signals(
            queue_wait_s=float(rs.get("estimated_queue_wait_s", 0.0) or 0.0),
            shed_delta=shed_delta,
            miss_delta=miss_delta,
            live_replicas=len(live),
            draining_replicas=len(draining),
            # the resize plane's world IS the current training world
            # (seeded via MasterServer(initial_world=)) — the stateless
            # reconcile source a restarted controller adopts
            train_world=int(rz.get("world", 0) or 0),
            resize_busy=rz.get("state", "idle") != "idle",
        )

    # -- actuation (per-DECISION lever calls, cooldown-rate-limited) --------
    def _drain_victim(self) -> Optional[str]:
        """Least-loaded LIVE replica from the cached snapshot — the one
        whose in-flight work is cheapest to let finish."""
        reps = [
            r for r in (self._router_snap or {}).get("replicas", [])
            if r.get("state") == "live"
        ]
        if not reps:
            return None
        reps.sort(key=lambda r: (
            int(r.get("outstanding", 0) or 0)
            + int(r.get("load", {}).get("queue_depth", 0) or 0),
            r.get("replica_id", ""),
        ))
        return reps[0]["replica_id"]

    def _actuate(self, actions: List[Action], now: float) -> None:
        for act in actions:
            with trace.span("autoscaler.actuate", decisions=1):
                if act.lever == "serving" and act.direction == "grow":
                    if self.spawner is not None:
                        self.spawner.spawn()
                        self.actions.append("spawn")
                elif act.lever == "serving" and act.direction == "shrink":
                    victim = self._drain_victim()
                    if victim is not None and self._router is not None:
                        try:
                            # rpc-ok: one drain order per admitted decision
                            self._router.call(
                                "drain", replica_id=victim,
                                deadline_s=self.cfg.drain_deadline_s,
                            )
                            self.actions.append(f"drain:{victim}")
                        except ConnectionError:
                            self.observe_failures += 1
                elif act.lever == "train" and self._master is not None:
                    world = int(act.payload["world"])
                    try:
                        # rpc-ok: one resize announce per admitted decision
                        resp = self._master.call("resize", world=world)
                    except ConnectionError:
                        self.observe_failures += 1
                        continue
                    if "err" in resp:
                        # epoch already in flight (or malformed order):
                        # back off instead of retrying hot
                        self.decider.note_resize_rejected(now)
                        obs_metrics.observe_scale_rejected("train")
                        self.actions.append("resize_rejected")
                    else:
                        self._resize_inflight = (
                            resp.get("instance", ""),
                            int(resp.get("epoch", 0)),
                            now + self.cfg.resize_timeout_s,
                        )
                        self.actions.append(f"resize:{world}")

    def _watch_resize(self, now: float) -> None:
        """Settle the resize this controller announced: a completed epoch
        resets the backoff; one stuck past resize_timeout_s counts as a
        rejection (backoff) and is abandoned to the master's own drain
        timeout — the controller never force-completes an epoch."""
        if self._resize_inflight is None:
            return
        instance, epoch, deadline = self._resize_inflight
        rz = (self._master_snap or {}).get("resize", {}) or {}
        same = (rz.get("instance") == instance
                and int(rz.get("epoch", -1) or -1) == epoch)
        if same and rz.get("state") == "idle":
            self.decider.note_resize_ok()
            self._resize_inflight = None
        elif rz.get("state") == "idle" and not same:
            # a failed-over master restarted the epoch counter: the epoch
            # we watched no longer exists — reconcile from scratch
            self._resize_inflight = None
        elif now > deadline:
            self.decider.note_resize_rejected(now)
            obs_metrics.observe_scale_rejected("train_timeout")
            self._resize_inflight = None

    # -- the tick -----------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[Action]:
        """One observe→decide→actuate pass. Public so drills and tests can
        drive the controller without its thread."""
        # seeded chaos sites: controller death (the loop thread exits and
        # the fleet degrades to static) and a wedged decision pass (which
        # must stall only THIS controller, never serving/training)
        faults.get().maybe_raise("controller_kill")
        faults.maybe_stall(
            "scale_decision_stall", env="PADDLE_TPU_SCALE_STALL_S",
            default_s=300.0,
        )
        if now is None:
            # clock-ok: the ONE wall-clock read per controller tick — every
            # cooldown/flap/backoff comparison inside decide() uses this
            # stamp (tests/test_lint_hotloop.py pins this site)
            now = time.monotonic()
        self.ticks += 1
        sig = self._observe(now)
        if sig is None:
            return []
        self._watch_resize(now)
        actions = self.decider.decide(sig, now)
        self._actuate(actions, now)
        if self.spawner is not None and hasattr(self.spawner, "reap"):
            self.spawner.reap()
        return actions

    def _loop(self) -> None:
        try:
            while not self._stop_evt.wait(self.tick_s):
                try:
                    self.tick()
                except faults.InjectedFault:
                    # the controller_kill drill: this controller is dead;
                    # the fleet it was steering keeps running statically
                    self.dead = True
                    core_stats.FT_EVENTS.incr("autoscaler_controller_killed")
                    log.warning("autoscaler controller killed (chaos "
                                "site); fleet degrades to static")
                    return
                except Exception:
                    # an unexpected tick failure must not take the loop
                    # down — the next tick re-observes from scratch
                    self.observe_failures += 1
                    log.exception("autoscaler tick failed; continuing")
        finally:
            # liveness port tracks the LOOP, not the process: any exit —
            # stop(), controller_kill, an escape we didn't foresee — drops
            # it so a watching standby (ISSUE 18) sees the death
            self._close_liveness()

    def start(self) -> "AutoscalerController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="autoscaler", daemon=True
            )
            self._thread.start()
        return self

    @property
    def alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self.dead)

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._close_liveness()
        for c in (self._router, self._master):
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass

    def stats(self) -> Dict[str, Any]:
        return {
            "ticks": self.ticks,
            "decisions": self.decider.decisions,
            "suppressed": dict(self.decider.suppressed),
            "resize_failures": self.decider.resize_failures,
            "observe_failures": self.observe_failures,
            "actions": list(self.actions),
            "alive": self.alive,
            "dead": self.dead,
            "instance": self.instance,
        }


class AutoscalerStandby:
    """Warm standby for the autoscaler (ISSUE 18), on the shared election
    primitive — and the degenerate, zero-extra-state consumer of it: the
    controller is ALREADY stateless-reconciling (desired state re-derived
    every tick from observed router/master stats; an in-flight resize epoch
    adopted from `stats()["resize"]`), so takeover is just "watch the
    primary's liveness port, then build a fresh controller". No sweep, no
    books, nothing to rebuild.

    `factory` is a zero-arg callable returning an UNSTARTED
    AutoscalerController — the standby cannot hold live clients/spawners
    for a controller that may never exist."""

    def __init__(self, primary: EndpointsLike,
                 factory: Callable[[], "AutoscalerController"],
                 poll_s: float = 0.2, confirm_failures: int = 2,
                 max_wait_s: Optional[float] = None,
                 stop_evt: Optional[threading.Event] = None):
        self.primary = primary
        self.factory = factory
        self.poll_s = float(poll_s)
        self.confirm_failures = int(confirm_failures)
        self.max_wait_s = max_wait_s
        self.stop_evt = stop_evt

    def run(self) -> Optional["AutoscalerController"]:
        """Block watching the primary's liveness port; on confirmed death
        return a STARTED controller whose `instance` is the election token.
        None when stopped or timed out with the primary still alive."""
        token = watch_primary(
            self.primary, plane="autoscaler", poll_s=self.poll_s,
            confirm_failures=self.confirm_failures,
            max_wait_s=self.max_wait_s, stop_evt=self.stop_evt,
        )
        if token is None:
            return None
        ctl = self.factory()
        ctl.instance = token
        log.warning("autoscaler standby (incarnation %s) taking over",
                    token)
        return ctl.start()


def _parse_endpoint(s: str) -> Tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _main(argv: Optional[List[str]] = None) -> int:
    """`python -m paddle_tpu.runtime.autoscaler serve` — the controller as
    its own (expendable) process. Killing it at any moment leaves the fleet
    static; restarting it reconciles from observed state."""
    import argparse
    import json
    import signal as _signal

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.runtime.autoscaler",
        description="goodput-driven autoscaler controller",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    # the controller flags, shared by `serve` (the primary) and `standby`
    # (which builds an IDENTICAL controller if and when it takes over)
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--router", default=None,
                        help="router host:port (serving spawn/drain lever)")
    common.add_argument("--master", default=None,
                        help="master host:port (training resize lever)")
    common.add_argument("--tick_s", type=float, default=1.0)
    common.add_argument("--chips", type=int, default=8,
                        help="total chip budget arbitrated across both "
                             "fleets")
    common.add_argument("--chips_per_replica", type=int, default=1)
    common.add_argument("--min_replicas", type=int, default=1)
    common.add_argument("--max_replicas", type=int, default=8)
    common.add_argument("--train_min_world", type=int, default=0)
    common.add_argument("--train_max_world", type=int, default=8)
    common.add_argument("--high_wait_s", type=float, default=0.5)
    common.add_argument("--low_wait_s", type=float, default=0.05)
    common.add_argument("--serving_cooldown_s", type=float, default=8.0)
    common.add_argument("--train_cooldown_s", type=float, default=10.0)
    common.add_argument("--flap_window_s", type=float, default=20.0)
    common.add_argument("--drain_deadline_s", type=float, default=30.0)
    common.add_argument("--spawn_arg", action="append", default=None,
                        help="repeatable: extra argv for spawned replicas "
                             "(default: --demo)")
    common.add_argument("--liveness_port", type=int, default=None,
                        help="bind a liveness port a standby can watch "
                             "(closed when the reconcile loop dies)")
    sv = sub.add_parser("serve", parents=[common],
                        help="run the reconcile loop")
    sb = sub.add_parser(
        "standby", parents=[common],
        help="watch a primary controller's liveness port; run an identical "
             "controller when it dies (ISSUE 18)",
    )
    sb.add_argument("--primary", required=True,
                    help="primary controller's liveness host:port")
    sb.add_argument("--poll_s", type=float, default=0.2)
    sb.add_argument("--max_wait_s", type=float, default=None,
                    help="give up after this long with the primary healthy")
    args = ap.parse_args(argv)

    if args.router is None and args.master is None:
        ap.error("need --router and/or --master")
    router_ep = _parse_endpoint(args.router) if args.router else None
    cfg = ScaleConfig(
        chips_total=args.chips, chips_per_replica=args.chips_per_replica,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        train_min_world=args.train_min_world,
        train_max_world=args.train_max_world,
        high_wait_s=args.high_wait_s, low_wait_s=args.low_wait_s,
        serving_cooldown_s=args.serving_cooldown_s,
        train_cooldown_s=args.train_cooldown_s,
        flap_window_s=args.flap_window_s,
        drain_deadline_s=args.drain_deadline_s,
    )

    def _build() -> AutoscalerController:
        spawner = (
            ReplicaSpawner(
                router_ep,
                extra_args=(args.spawn_arg
                            if args.spawn_arg is not None else ["--demo"]),
            )
            if router_ep is not None else None
        )
        return AutoscalerController(
            router_endpoints=router_ep,
            master_endpoints=(
                _parse_endpoint(args.master) if args.master else None
            ),
            config=cfg, spawner=spawner, tick_s=args.tick_s,
            liveness_port=args.liveness_port,
        )

    if args.cmd == "standby":
        stop_evt = threading.Event()
        _signal.signal(_signal.SIGTERM, lambda *_: stop_evt.set())
        _signal.signal(_signal.SIGINT, lambda *_: stop_evt.set())
        ctl = AutoscalerStandby(
            args.primary, _build, poll_s=args.poll_s,
            max_wait_s=args.max_wait_s, stop_evt=stop_evt,
        ).run()
        if ctl is None:
            print(json.dumps({"role": "autoscaler_standby",
                              "takeover": False}), flush=True)
            return 3
        print(json.dumps({"role": "autoscaler_standby", "takeover": True,
                          "instance": ctl.instance}), flush=True)
    else:
        ctl = _build().start()
    _signal.signal(_signal.SIGTERM, lambda *_: ctl.stop())
    _signal.signal(_signal.SIGINT, lambda *_: ctl.stop())
    if args.cmd == "serve":
        print(json.dumps({
            "role": "autoscaler", "tick_s": args.tick_s,
            "liveness": (list(ctl.liveness_address)
                         if ctl.liveness_address else None),
        }), flush=True)
    while ctl._thread is not None and ctl._thread.is_alive():
        time.sleep(0.05)
    if ctl.spawner is not None:
        ctl.spawner.stop_all()
    print(json.dumps({"role": "autoscaler", "final": ctl.stats()}),
          flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
