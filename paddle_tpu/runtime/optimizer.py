"""Native optimizer library binding (csrc/optimizer.cc; paddle/optimizer
parity — the C ABI the reference's Go pserver consumes via cgo). Host-side
parameter updates with checkpointable slot state; the jax optim package is
the numerical oracle in tests."""

from __future__ import annotations

import ctypes as C
from typing import Optional

import numpy as np

from paddle_tpu.runtime import native

_TYPES = {"sgd": 0, "adagrad": 1, "adadelta": 2, "adam": 3}
_LR_POLICIES = {"const": 0, "linear": 1}


def _lib():
    L = native.lib()
    if L is None:
        raise RuntimeError("native runtime unavailable (g++ build failed?)")
    if not hasattr(L, "_opt_bound"):
        L.pt_opt_create.restype = C.c_void_p
        L.pt_opt_create.argtypes = [C.c_int] + [C.c_double] * 7 + [C.c_int]
        L.pt_opt_set_lr_policy.restype = None
        L.pt_opt_set_lr_policy.argtypes = [C.c_void_p, C.c_int, C.c_double, C.c_double]
        L.pt_opt_update.restype = C.c_int
        L.pt_opt_update.argtypes = [
            C.c_void_p,
            C.POINTER(C.c_float),
            C.POINTER(C.c_float),
            C.c_uint64,
        ]
        L.pt_opt_current_lr.restype = C.c_double
        L.pt_opt_current_lr.argtypes = [C.c_void_p]
        L.pt_opt_serialize.restype = C.c_int64
        L.pt_opt_serialize.argtypes = [C.c_void_p, C.c_char_p, C.c_int64]
        L.pt_opt_deserialize.restype = C.c_int
        L.pt_opt_deserialize.argtypes = [C.c_void_p, C.c_char_p, C.c_int64]
        L.pt_opt_destroy.restype = None
        L.pt_opt_destroy.argtypes = [C.c_void_p]
        L._opt_bound = True
    return L


class NativeOptimizer:
    def __init__(
        self,
        kind: str = "sgd",
        learning_rate: float = 0.01,
        momentum: float = 0.0,
        beta1: float = 0.9,
        beta2: float = 0.999,
        epsilon: float = 1e-8,
        rho: float = 0.95,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        lr_policy: str = "const",
        lr_decay_a: float = 0.0,
        lr_decay_b: float = 0.0,
    ):
        if kind not in _TYPES:
            raise ValueError(f"unknown optimizer kind {kind!r}; got {sorted(_TYPES)}")
        self._lib = _lib()
        self.kind = kind
        self._h = self._lib.pt_opt_create(
            _TYPES[kind], learning_rate, momentum, beta1, beta2, epsilon,
            rho, weight_decay, int(nesterov),
        )
        if lr_policy != "const":
            self._lib.pt_opt_set_lr_policy(
                self._h, _LR_POLICIES[lr_policy], lr_decay_a, lr_decay_b
            )

    def update(self, param: np.ndarray, grad: np.ndarray) -> np.ndarray:
        """In-place update of a contiguous float32 parameter array; returns
        it. Raises TypeError rather than silently updating a copy."""
        if not (
            isinstance(param, np.ndarray)
            and param.dtype == np.float32
            and param.flags["C_CONTIGUOUS"]
            and param.flags["WRITEABLE"]
        ):
            raise TypeError(
                "param must be a writeable contiguous float32 ndarray "
                "(in-place update); convert with np.ascontiguousarray(p, np.float32)"
            )
        g = np.ascontiguousarray(grad, np.float32)
        if param.shape != g.shape:
            raise ValueError(f"param {param.shape} vs grad {g.shape}")
        rc = self._lib.pt_opt_update(
            self._h,
            param.ctypes.data_as(C.POINTER(C.c_float)),
            g.ctypes.data_as(C.POINTER(C.c_float)),
            param.size,
        )
        if rc != 0:
            raise ValueError(
                f"optimizer slot state sized for a different parameter "
                f"(got {param.size} elements)"
            )
        return param

    @property
    def current_lr(self) -> float:
        return float(self._lib.pt_opt_current_lr(self._h))

    # -- checkpointable state (OptimizerConfig.proto state parity) ----------
    def serialize(self) -> bytes:
        need = self._lib.pt_opt_serialize(self._h, None, 0)
        buf = C.create_string_buffer(need)
        wrote = self._lib.pt_opt_serialize(self._h, buf, need)
        if wrote != need:
            raise RuntimeError("optimizer serialization failed")
        return buf.raw

    def deserialize(self, blob: bytes) -> None:
        if self._lib.pt_opt_deserialize(self._h, blob, len(blob)) != 0:
            raise ValueError("bad optimizer state blob (magic/type mismatch)")

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.pt_opt_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
