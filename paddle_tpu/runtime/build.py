"""Builds csrc/ into libpaddle_tpu_rt.so on first use (cached by mtime).

The reference ships its native runtime as CMake targets; here the library is
small enough that a single g++ invocation at import keeps the source tree the
only build input. Set PADDLE_TPU_NO_NATIVE=1 to skip (pure-Python fallbacks
are used where they exist)."""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CSRC = os.path.join(_REPO, "csrc")
OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")
SO_PATH = os.path.join(OUT_DIR, "libpaddle_tpu_rt.so")


def _needs_build() -> bool:
    if not os.path.exists(SO_PATH):
        return True
    so_mtime = os.path.getmtime(SO_PATH)
    for fn in os.listdir(CSRC):
        if fn.endswith((".cc", ".h")):
            if os.path.getmtime(os.path.join(CSRC, fn)) > so_mtime:
                return True
    return False


def ensure_built(verbose: bool = False) -> Optional[str]:
    """Compile if needed; returns the .so path or None when unavailable."""
    if os.environ.get("PADDLE_TPU_NO_NATIVE"):
        return None
    if not os.path.isdir(CSRC):
        return None
    if not _needs_build():
        return SO_PATH
    os.makedirs(OUT_DIR, exist_ok=True)
    sources = sorted(
        os.path.join(CSRC, f) for f in os.listdir(CSRC) if f.endswith(".cc")
    )
    tmp = SO_PATH + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
        "-o", tmp, *sources,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        if verbose:
            print(f"native build unavailable: {e}", file=sys.stderr)
        return None
    if proc.returncode != 0:
        if verbose:
            print(f"native build failed:\n{proc.stderr}", file=sys.stderr)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    os.replace(tmp, SO_PATH)
    return SO_PATH
