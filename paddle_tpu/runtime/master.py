"""Elastic task master — go/master parity (SURVEY §2.2, §5 failure recovery).

TaskMaster wraps the native dispatcher (csrc/master.cc): todo/pending/done
queues, lease timeouts with re-queue, failureMax discard, snapshot/restore.
MasterServer exposes it over TCP (newline-delimited JSON — the Go master's
net/rpc role) so multi-host trainers share one queue; MasterClient +
`cluster_reader` replace python/paddle/v2/master/client.py:15 (the ctypes→Go
reader shim): trainers are stateless task consumers pulling recordio shard
lists."""

from __future__ import annotations

import ctypes as C
import json
import logging
import os
import random
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Iterator, List, Optional, Sequence

from paddle_tpu.core import faults, stats
from paddle_tpu.runtime import native
from paddle_tpu.runtime import recordio

log = logging.getLogger("paddle_tpu.master")


class TaskMaster:
    """In-process dispatcher. Payload per task = newline-joined shard paths."""

    PASS_FINISHED = -2

    def __init__(self, timeout_s: float = 60.0, failure_max: int = 3):
        L = native.lib()
        if L is None:
            raise RuntimeError("native runtime unavailable (g++ build failed?)")
        self._lib = L
        self._m = L.pt_master_create(timeout_s, failure_max)
        self._buf = C.create_string_buffer(1 << 20)

    def set_dataset(
        self, shard_paths: Sequence[str], chunks_per_task: int = 1
    ) -> None:
        """Group shards into tasks of `chunks_per_task` (go master
        NewService(chunksPerTask), service.go:140)."""
        payloads: List[str] = []
        group: List[str] = []
        for p in shard_paths:
            group.append(p)
            if len(group) >= chunks_per_task:
                payloads.append("\n".join(group))
                group = []
        if group:
            payloads.append("\n".join(group))
        blob = b"".join(p.encode() + b"\0" for p in payloads)
        self._lib.pt_master_set_dataset(self._m, blob, len(payloads))

    def get_task(self) -> Optional[tuple]:
        """→ (task_id, [shard paths]) | None (retry later) | raises StopIteration
        on pass end? No — returns ('pass_finished') sentinel via id==-2."""
        tid = self._lib.pt_master_get_task(self._m, self._buf, len(self._buf))
        while tid == -3:  # buffer too small: grow until the payload fits
            self._buf = C.create_string_buffer(len(self._buf) * 4)
            tid = self._lib.pt_master_get_task(self._m, self._buf, len(self._buf))
        if tid < 0:
            return None if tid == -1 else (self.PASS_FINISHED, [])
        return int(tid), self._buf.value.decode().split("\n")

    def task_finished(self, task_id: int) -> bool:
        return self._lib.pt_master_task_finished(self._m, task_id) == 0

    def task_failed(self, task_id: int) -> bool:
        return self._lib.pt_master_task_failed(self._m, task_id) == 0

    def pass_finished(self, start_next: bool = False) -> bool:
        return self._lib.pt_master_pass_finished(self._m, int(start_next)) == 1

    def stats(self) -> dict:
        out = (C.c_int64 * 5)()
        self._lib.pt_master_stats(self._m, out)
        return {
            "todo": out[0], "pending": out[1], "done": out[2],
            "discarded": out[3], "pass": out[4],
        }

    def snapshot(self, path: str) -> None:
        if self._lib.pt_master_snapshot(self._m, path.encode()) != 0:
            raise OSError(f"snapshot to {path} failed")

    def restore(self, path: str) -> None:
        if self._lib.pt_master_restore(self._m, path.encode()) != 0:
            raise OSError(f"restore from {path} failed")

    def close(self) -> None:
        if self._m:
            self._lib.pt_master_destroy(self._m)
            self._m = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# TCP service (the Go master's RPC role), newline-delimited JSON
# ---------------------------------------------------------------------------


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        master: TaskMaster = self.server.master  # type: ignore[attr-defined]
        lock: threading.Lock = self.server.master_lock  # type: ignore[attr-defined]
        snapshot_path = self.server.snapshot_path  # type: ignore[attr-defined]
        for line in self.rfile:
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                self._reply({"err": "bad json"})
                continue
            method = req.get("method")
            if faults.get().fire("master_drop"):
                # chaos hook: the RPC vanishes in transit — drop the
                # connection without processing or replying; the client's
                # reconnect/backoff path has to absorb it
                return
            with lock:
                if method == "get_task":
                    got = master.get_task()
                    if got is None:
                        resp = {"retry": True}
                    elif got[0] == TaskMaster.PASS_FINISHED:
                        resp = {"pass_finished": True}
                    else:
                        resp = {"task_id": got[0], "shards": got[1]}
                elif method == "task_finished":
                    ok = master.task_finished(int(req["task_id"]))
                    resp = {"ok": ok}
                    if snapshot_path:
                        try:
                            master.snapshot(snapshot_path)
                        except OSError as e:
                            # progress was acked to the trainer but NOT made
                            # durable — a crash now replays this task; say so
                            # instead of silently losing recovery fidelity
                            self.server.snapshot_failures += 1  # type: ignore[attr-defined]
                            log.warning(
                                "master snapshot to %s failed (%s); a crash "
                                "before the next successful snapshot will "
                                "re-dispatch acked tasks", snapshot_path, e,
                            )
                elif method == "task_failed":
                    resp = {"ok": master.task_failed(int(req["task_id"]))}
                elif method == "set_dataset":
                    master.set_dataset(
                        req["shards"], int(req.get("chunks_per_task", 1))
                    )
                    resp = {"ok": True}
                elif method == "pass_finished":
                    resp = {
                        "finished": master.pass_finished(
                            bool(req.get("start_next", False))
                        )
                    }
                elif method == "stats":
                    resp = master.stats()
                    resp["snapshot_failures"] = (
                        self.server.snapshot_failures  # type: ignore[attr-defined]
                    )
                else:
                    resp = {"err": f"unknown method {method!r}"}
            self._reply(resp)

    def _reply(self, obj: Any) -> None:
        self.wfile.write(json.dumps(obj).encode() + b"\n")
        self.wfile.flush()


class MasterServer:
    """Threaded TCP wrapper; start()/stop(); port 0 picks a free port (the
    reference's in-process-localhost test idiom, test_CompareSparse.cpp:65)."""

    def __init__(
        self,
        master: Optional[TaskMaster] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        snapshot_path: Optional[str] = None,
    ):
        self.master = master or TaskMaster()
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.master = self.master  # type: ignore[attr-defined]
        self._srv.master_lock = threading.Lock()  # type: ignore[attr-defined]
        self._srv.snapshot_path = snapshot_path  # type: ignore[attr-defined]
        self._srv.snapshot_failures = 0  # type: ignore[attr-defined]
        if snapshot_path and os.path.exists(snapshot_path):
            self.master.restore(snapshot_path)  # crash recovery (service.go:166)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> tuple:
        return self._srv.server_address

    @property
    def snapshot_failures(self) -> int:
        return self._srv.snapshot_failures  # type: ignore[attr-defined]

    def start(self) -> "MasterServer":
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class MasterClient:
    """Blocking line-JSON client with reconnect (go/master/client.go parity).

    Failed calls reconnect and retry with bounded exponential backoff plus
    jitter (the Go client's backoff discipline; jitter keeps a restarted
    master from being stampeded by every trainer retrying in lockstep).
    After `retries` attempts the terminal ConnectionError names the method,
    the address, the attempt count and the last underlying error."""

    def __init__(
        self,
        address: tuple,
        timeout: float = 30.0,
        retries: int = 5,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
    ):
        self.address = tuple(address)
        self.timeout = timeout
        self.retries = max(1, int(retries))
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._sock: Optional[socket.socket] = None
        self._rfile = None

    def _connect(self):
        if self._sock is None:
            self._sock = socket.create_connection(self.address, timeout=self.timeout)
            self._rfile = self._sock.makefile("rb")

    def call(self, method: str, **kw) -> dict:
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            try:
                self._connect()
                msg = json.dumps({"method": method, **kw}).encode() + b"\n"
                self._sock.sendall(msg)
                line = self._rfile.readline()
                if not line:
                    raise ConnectionError("master closed connection")
                return json.loads(line)
            except (OSError, ConnectionError, json.JSONDecodeError) as e:
                last_err = e
                self.close()
                stats.FT_EVENTS.incr("master_reconnect")
                if attempt + 1 < self.retries:
                    delay = min(self.backoff_max, self.backoff_base * 2 ** attempt)
                    delay *= 0.5 + random.random() / 2  # full-jitter in [.5d, d)
                    log.warning(
                        "master RPC %r failed (%s: %s); reconnecting in %.0fms "
                        "(attempt %d/%d)", method, type(e).__name__, e,
                        delay * 1e3, attempt + 1, self.retries,
                    )
                    time.sleep(delay)
        raise ConnectionError(
            f"master RPC {method!r} to {self.address} failed after "
            f"{self.retries} attempts; giving up (last error: "
            f"{type(last_err).__name__}: {last_err})"
        ) from last_err

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._rfile = None


def cluster_reader(
    master_address: tuple,
    deserialize: Callable[[bytes], Any] = None,
    poll_interval: float = 0.5,
) -> Callable[[], Iterator[Any]]:
    """v2 cluster reader (master/client.py:15): pull tasks from the master,
    stream their recordio shards, ack on completion, report failures. One
    call of the returned reader = one pass."""
    import pickle

    deserialize = deserialize or pickle.loads

    def reader() -> Iterator[Any]:
        client = MasterClient(master_address)
        try:
            while True:
                resp = client.call("get_task")
                if resp.get("pass_finished"):
                    return
                if resp.get("retry"):
                    time.sleep(poll_interval)
                    continue
                task_id, shards = resp["task_id"], resp["shards"]
                try:
                    yield from recordio.read_shards(shards, deserialize)
                except Exception:
                    client.call("task_failed", task_id=task_id)
                    raise
                client.call("task_finished", task_id=task_id)
        finally:
            client.close()

    return reader
