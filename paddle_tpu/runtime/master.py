"""Elastic task master — go/master parity (SURVEY §2.2, §5 failure recovery).

TaskMaster wraps the native dispatcher (csrc/master.cc): todo/pending/done
queues, lease timeouts with re-queue, failureMax discard, snapshot/restore.
MasterServer exposes it over TCP (newline-delimited JSON — the Go master's
net/rpc role) so multi-host trainers share one queue; MasterClient +
`cluster_reader` replace python/paddle/v2/master/client.py:15 (the ctypes→Go
reader shim): trainers are stateless task consumers pulling recordio shard
lists.

Cluster-level failure is a first-class code path here:

- **Failover**: MasterClient takes an endpoint *list* ("a:p,b:p") and rotates
  through it inside its existing reconnect/backoff loop; `standby_master`
  watches a primary and takes over from the shared snapshot the moment it
  dies (pending tasks snapshot as todo, so lost leases re-dispatch — the Go
  master's etcd-recovery discipline, service.go:166).
- **Membership**: trainers `register` for a lease and renew it via
  `heartbeat` (every RPC bearing a trainer_id renews implicitly — RPCs stay
  retry-exact, per "RPC Considered Harmful"). An expired trainer's pending
  tasks are re-queued *eagerly*, not left to the per-task timeout; live and
  evicted counts ride in `stats()`.
- **Chaos**: the seeded sites `master_drop` (RPC vanishes), `master_kill`
  (server dies mid-RPC, no final snapshot) and `conn_reset` (client socket
  resets) make every failover path deterministic and testable.
- **Elastic resize** (ISSUE 8): a `resize` RPC (or join/evict with
  `resize_on_membership=True`) announces a resize EPOCH; the drain signal
  piggybacks on heartbeat replies (no control-plane RPC storm), every live
  member acks `resize_drained` at its own boundary, eviction recomputes the
  barrier so a trainer killed mid-drain cannot wedge the epoch, and
  `resize_status` polls double as resumed acks. `_ResizeEpoch` is the state
  machine; `ResizeClient` is the trainer-side hook
  (`train(resize_barrier=rc.barrier)`); a registered `cluster_reader`
  participates between task acks. The seeded sites `resize_drain_stall`
  (member wedges inside the barrier) and `reshard_kill` (death mid-re-shard,
  trainer side) make the epoch's failure transitions deterministic.
"""

from __future__ import annotations

import base64
import ctypes as C
import json
import logging
import os
import random
import socket
import socketserver
import threading
import time
import uuid
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple, Union

from paddle_tpu.core import faults, stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.runtime import frames
from paddle_tpu.runtime import native
from paddle_tpu.runtime import recordio

log = logging.getLogger("paddle_tpu.master")

Endpoint = Tuple[str, int]
EndpointsLike = Union[str, Endpoint, Sequence[Union[str, Endpoint]]]


def parse_endpoints(address: EndpointsLike) -> List[Endpoint]:
    """Normalize one endpoint or a failover list into [(host, port), ...].

    Accepts a (host, port) tuple, "host:port", the CLI's comma form
    "a:p1,b:p2", or any sequence mixing those."""
    if isinstance(address, str):
        parts = [p.strip() for p in address.split(",") if p.strip()]
    elif (
        isinstance(address, (tuple, list))
        and len(address) == 2
        and isinstance(address[0], str)
        and isinstance(address[1], int)
    ):
        parts = [address]
    else:
        parts = list(address)
    out: List[Endpoint] = []
    for p in parts:
        if isinstance(p, str):
            host, sep, port = p.rpartition(":")
            if not sep:
                raise ValueError(f"bad master endpoint {p!r}: want host:port")
            out.append((host, int(port)))
        else:
            host, port = p
            out.append((str(host), int(port)))
    if not out:
        raise ValueError(f"no master endpoints in {address!r}")
    return out


class TaskMaster:
    """In-process dispatcher. Payload per task = newline-joined shard paths."""

    PASS_FINISHED = -2

    def __init__(self, timeout_s: float = 60.0, failure_max: int = 3):
        L = native.lib()
        if L is None:
            raise RuntimeError("native runtime unavailable (g++ build failed?)")
        self._lib = L
        self._m = L.pt_master_create(timeout_s, failure_max)
        self._buf = C.create_string_buffer(1 << 20)

    def set_dataset(
        self, shard_paths: Sequence[str], chunks_per_task: int = 1
    ) -> None:
        """Group shards into tasks of `chunks_per_task` (go master
        NewService(chunksPerTask), service.go:140)."""
        payloads: List[str] = []
        group: List[str] = []
        for p in shard_paths:
            group.append(p)
            if len(group) >= chunks_per_task:
                payloads.append("\n".join(group))
                group = []
        if group:
            payloads.append("\n".join(group))
        blob = b"".join(p.encode() + b"\0" for p in payloads)
        self._lib.pt_master_set_dataset(self._m, blob, len(payloads))

    def get_task(self) -> Optional[tuple]:
        """→ (task_id, [shard paths]) | None (retry later) | raises StopIteration
        on pass end? No — returns ('pass_finished') sentinel via id==-2."""
        tid = self._lib.pt_master_get_task(self._m, self._buf, len(self._buf))
        while tid == -3:  # buffer too small: grow until the payload fits
            self._buf = C.create_string_buffer(len(self._buf) * 4)
            tid = self._lib.pt_master_get_task(self._m, self._buf, len(self._buf))
        if tid < 0:
            return None if tid == -1 else (self.PASS_FINISHED, [])
        return int(tid), self._buf.value.decode().split("\n")

    def task_finished(self, task_id: int) -> bool:
        return self._lib.pt_master_task_finished(self._m, task_id) == 0

    def task_failed(self, task_id: int) -> bool:
        return self._lib.pt_master_task_failed(self._m, task_id) == 0

    def pass_finished(self, start_next: bool = False) -> bool:
        return self._lib.pt_master_pass_finished(self._m, int(start_next)) == 1

    def stats(self) -> dict:
        out = (C.c_int64 * 5)()
        self._lib.pt_master_stats(self._m, out)
        return {
            "todo": out[0], "pending": out[1], "done": out[2],
            "discarded": out[3], "pass": out[4],
        }

    def snapshot(self, path: str) -> None:
        if self._m is None:  # killed under a debounced writer — not a segfault
            raise OSError("snapshot on a closed TaskMaster")
        if self._lib.pt_master_snapshot(self._m, path.encode()) != 0:
            raise OSError(f"snapshot to {path} failed")

    def restore(self, path: str) -> None:
        if self._lib.pt_master_restore(self._m, path.encode()) != 0:
            raise OSError(f"restore from {path} failed")

    @property
    def closed(self) -> bool:
        return self._m is None

    def close(self) -> None:
        if self._m:
            self._lib.pt_master_destroy(self._m)
            self._m = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Trainer membership: register/heartbeat leases + eager re-queue on eviction
# ---------------------------------------------------------------------------


class _Membership:
    """Soft-state trainer leases (go/master's etcd TTL keys, in-process).

    Any RPC bearing a trainer_id renews — or adopts — the lease, so a
    failover to a standby that never saw `register` heals itself on the next
    request instead of erroring (retry-exact RPCs). Pending-task ownership is
    tracked so an expired trainer's tasks can be re-queued eagerly."""

    def __init__(self, lease_s: float):
        self.lease_s = float(lease_s)
        self._lock = threading.Lock()
        self._last_seen: Dict[str, float] = {}
        # lease role: "trainer" (default) or "reader" — one PROCESS may hold
        # both (ResizeClient + registered cluster_reader), so membership-
        # triggered resize worlds must count trainer leases, not all leases
        self._roles: Dict[str, str] = {}
        self._owned: Dict[str, Set[int]] = {}
        self._owner: Dict[int, str] = {}
        self._next = 0
        # server-unique prefix: ids minted by a primary and its standby never
        # collide, so an adopted lease is unambiguous
        self._prefix = uuid.uuid4().hex[:6]
        self.evicted = 0

    def register(self, role: str = "trainer") -> str:
        with self._lock:
            tid = f"tr-{self._prefix}-{self._next}"
            self._next += 1
            self._last_seen[tid] = time.monotonic()
            self._roles[tid] = role or "trainer"
            return tid

    def note_seen(self, tid: Optional[str], role: Optional[str] = None) -> None:
        if not tid:
            return
        with self._lock:
            self._last_seen[tid] = time.monotonic()
            if role:
                # heartbeats re-assert the role so a lease ADOPTED by a
                # standby (which never saw `register`) heals its type too
                self._roles[tid] = role

    def own(self, tid: Optional[str], task_id: int) -> None:
        if not tid:
            return
        with self._lock:
            self._owned.setdefault(tid, set()).add(task_id)
            self._owner[task_id] = tid

    def release(self, task_id: int) -> None:
        with self._lock:
            tid = self._owner.pop(task_id, None)
            if tid is not None:
                self._owned.get(tid, set()).discard(task_id)

    def drop(self, tid: str) -> Set[int]:
        """Forget a trainer (graceful deregister or eviction); returns the
        task ids it still held, for the caller to re-queue. Reader-role
        entries survive as tombstones: an evicted-but-alive reader whose
        next get_task/task_done resurrects the lease (note_seen carries no
        role) must not default back to "trainer" and inflate the next
        membership-triggered world size. Ids are never reused, so the
        tombstones are one short string per ever-registered reader."""
        with self._lock:
            self._last_seen.pop(tid, None)
            if self._roles.get(tid, "trainer") != "reader":
                self._roles.pop(tid, None)
            tasks = self._owned.pop(tid, set())
            for t in tasks:
                self._owner.pop(t, None)
            return tasks

    def expired(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return [
                tid for tid, seen in self._last_seen.items()
                if now - seen > self.lease_s
            ]

    @property
    def live(self) -> int:
        with self._lock:
            return len(self._last_seen)

    @property
    def live_trainers(self) -> int:
        """Trainer-role leases only — the world size a membership-triggered
        resize should announce (reader leases join the drain barrier but do
        not shard the data axis)."""
        with self._lock:
            return sum(
                1 for t in self._last_seen
                if self._roles.get(t, "trainer") != "reader"
            )

    def role(self, tid: Optional[str]) -> str:
        with self._lock:
            return self._roles.get(tid, "trainer") if tid else "trainer"

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._last_seen)


class _ResizeEpoch:
    """Master-side elastic-resize state machine (ISSUE 8 tentpole).

    One epoch at a time:

        idle --announce--> draining --all live members acked--> go
          ^                                                      |
          +------------- every acked member saw go --------------+

    `announce(world, live)` snapshots the live trainer set as the drain
    BARRIER membership; each member acks `resize_drained` at its own batch
    boundary. The barrier is recomputed on eviction (`note_dropped`) so a
    trainer KILLED during the drain cannot deadlock the epoch: lease expiry
    shrinks the membership and the survivors proceed. A trainer that is
    wedged but still heart-beating (`resize_drain_stall` — its daemon
    heartbeat thread keeps the lease alive) is caught by the second guard:
    `tick()` (called from the master's reaper loop) times the DRAIN phase
    out after `drain_timeout_s` and drops non-acked members from the
    barrier, so liveness never depends on every member being prompt. In
    `go`, members poll `resize_status` (their poll marks them resumed); once
    every surviving member resumed, the epoch closes and the drain/total
    latency lands in `last`. A timed-out straggler that eventually wakes
    sees the epoch in `go`/`idle`, adopts the decided world, and rejoins.

    Task accounting stays exactly-once across the epoch by construction:
    drained trainers hold no in-flight task (the reader drains between task
    acks), and a killed trainer's pending tasks ride the existing eager
    re-queue on eviction — nothing is double-acked and nothing is lost, so
    `done == ntasks` holds at pass end regardless of how many epochs (or
    mid-epoch deaths) the pass saw."""

    def __init__(self, drain_timeout_s: float = 60.0):
        self._lock = threading.Lock()
        self.drain_timeout_s = float(drain_timeout_s)
        # epoch numbers are a per-master-INSTANCE counter: a promoted
        # standby (or restarted master) counts from 1 again, so clients
        # must treat (instance, epoch) — not the bare number — as the
        # epoch's identity or a post-failover collision with an already-
        # handled number silently exempts them from the new master's epochs
        self.instance = uuid.uuid4().hex[:8]
        self.epoch = 0
        self.state = "idle"  # idle | draining | go
        self.world = 0
        self.barrier: Set[str] = set()
        self.acked: Set[str] = set()
        self.resumed: Set[str] = set()
        self.evicted_during = 0
        self.timed_out = 0
        self.announced_at = 0.0
        self.drained_at = 0.0
        self.completed = 0
        self.last: Dict[str, Any] = {}

    def announce(self, world: int, live: Sequence[str]) -> Dict[str, Any]:
        with self._lock:
            if self.state != "idle":
                return {
                    "err": (
                        f"resize epoch {self.epoch} still {self.state} "
                        f"(world {self.world}); retry after it completes"
                    )
                }
            self.epoch += 1
            self.state = "draining"
            self.world = int(world)
            self.barrier = set(live)
            self.acked = set()
            self.resumed = set()
            self.evicted_during = 0
            self.timed_out = 0
            self.announced_at = time.monotonic()
            self.drained_at = 0.0
            if not self.barrier:
                # nobody to drain (resize before any trainer registered):
                # complete immediately instead of wedging `draining` — and
                # rejecting every later announce — until the drain timeout
                self._maybe_go_locked()
            info = self._info_locked()
        stats.FT_EVENTS.incr("resize_announce")
        log.warning(
            "resize epoch %d announced: world -> %d, drain barrier of %d "
            "trainer(s)", info["epoch"], info["world"], info["barrier"],
        )
        return info

    def ack_drained(self, tid: Optional[str], epoch: int) -> Dict[str, Any]:
        with self._lock:
            if self.state == "draining" and epoch == self.epoch and tid:
                self.acked.add(tid)
                # a late joiner acking the barrier counts as a member (it
                # registered after the announce but still drains with us)
                self.barrier.add(tid)
                self._maybe_go_locked()
            return self._info_locked()

    def mark_resumed(self, tid: Optional[str], epoch: int) -> Dict[str, Any]:
        with self._lock:
            if self.state == "go" and epoch == self.epoch and tid:
                self.resumed.add(tid)
                self._maybe_finish_locked()
            return self._info_locked()

    def note_dropped(self, tid: str) -> None:
        """Membership eviction/deregister during an epoch: the barrier must
        not wait for the dead."""
        with self._lock:
            if self.state == "idle":
                return
            dropped = False
            for s in (self.barrier, self.acked, self.resumed):
                if tid in s:
                    s.discard(tid)
                    dropped = True
            if not dropped:
                return
            self.evicted_during += 1
            if self.state == "draining":
                self._maybe_go_locked()
            elif self.state == "go":
                self._maybe_finish_locked()
        stats.FT_EVENTS.incr("resize_barrier_evicted")

    def tick(self) -> None:
        """Reaper-loop guard: a drain phase older than `drain_timeout_s`
        drops every non-acked member from the barrier (a member can be
        wedged yet still heart-beating, so lease eviction alone is not a
        liveness guarantee) and lets the survivors go. The `go` phase gets
        the same guard against its own wedge mode: a member that acked the
        drain and then hung inside its re-shard (heartbeat thread still
        renewing the lease) must not pin the epoch in `go` — and reject
        every future announce — forever."""
        stragglers: Set[str] = set()
        with self._lock:
            if self.state == "draining":
                if time.monotonic() - self.announced_at < self.drain_timeout_s:
                    return
                stragglers = self.barrier - self.acked
                if stragglers:
                    log.warning(
                        "resize epoch %d: drain barrier timed out after "
                        "%.0fs — dropping %d non-acked member(s) and "
                        "proceeding",
                        self.epoch, self.drain_timeout_s, len(stragglers),
                    )
                    self.barrier -= stragglers
                    self.timed_out += len(stragglers)
                    self.evicted_during += len(stragglers)
                self._maybe_go_locked()
            elif self.state == "go":
                if time.monotonic() - self.drained_at < self.drain_timeout_s:
                    return
                stragglers = self.barrier - self.resumed
                if stragglers:
                    log.warning(
                        "resize epoch %d: %d drained member(s) never resumed "
                        "after %.0fs — dropping them and completing the "
                        "epoch",
                        self.epoch, len(stragglers), self.drain_timeout_s,
                    )
                    self.barrier -= stragglers
                    self.acked -= stragglers
                    self.timed_out += len(stragglers)
                    self.evicted_during += len(stragglers)
                self._maybe_finish_locked()
            else:
                return
        for _ in stragglers:
            stats.FT_EVENTS.incr("resize_barrier_timeout")

    def _maybe_go_locked(self) -> None:
        if self.barrier and not (self.barrier - self.acked):
            self.state = "go"
            self.drained_at = time.monotonic()
            log.warning(
                "resize epoch %d: all %d live trainer(s) drained (%.3fs) — go",
                self.epoch, len(self.barrier),
                self.drained_at - self.announced_at,
            )
        elif not self.barrier:
            # everyone died mid-drain: nothing left to coordinate
            self.state = "go"
            self.drained_at = time.monotonic()
            self._maybe_finish_locked()

    def _maybe_finish_locked(self) -> None:
        if self.barrier - self.resumed:
            return
        self.state = "idle"
        self.completed += 1
        now = time.monotonic()
        self.last = {
            "epoch": self.epoch,
            "world": self.world,
            "trainers": len(self.barrier),
            "evicted_during": self.evicted_during,
            "timed_out": self.timed_out,
            "drain_s": round(
                (self.drained_at or now) - self.announced_at, 6
            ),
            "total_s": round(now - self.announced_at, 6),
        }
        stats.FT_EVENTS.incr("resize_complete")
        log.warning(
            "resize epoch %d complete: world=%d %d trainer(s), %d evicted "
            "mid-epoch, drain %.3fs total %.3fs", self.epoch, self.world,
            len(self.barrier), self.evicted_during, self.last["drain_s"],
            self.last["total_s"],
        )

    def _info_locked(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "instance": self.instance,
            "epoch": self.epoch,
            "world": self.world,
            "barrier": len(self.barrier),
            "drained": len(self.acked),
            "resumed": len(self.resumed),
            "timed_out": self.timed_out,
            "completed": self.completed,
            "last": dict(self.last),
        }

    def info(self) -> Dict[str, Any]:
        with self._lock:
            return self._info_locked()

    def heartbeat_payload(self) -> Optional[Dict[str, Any]]:
        """The drain signal that piggybacks on heartbeat replies while an
        epoch is active — no extra RPC round-trips on the control plane
        ("RPC Considered Harmful"); None (omitted) when idle."""
        with self._lock:
            if self.state == "idle":
                return None
            return {
                "state": self.state, "instance": self.instance,
                "epoch": self.epoch, "world": self.world,
            }


class _SnapshotPolicy:
    """Debounced, atomic snapshot writes OUTSIDE the RPC lock.

    The native snapshot takes the master's own mutex, so the only thing the
    RPC lock was buying during the write was a full stall of every other
    trainer behind one fsync. Writes go to a temp file + rename (never a torn
    snapshot for a standby to restore), rate-limited to at most once per
    `every` acks and once per `interval_s` seconds."""

    def __init__(self, path: str, every: int = 1, interval_s: float = 0.0):
        self.path = path
        self.every = max(1, int(every))
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._acks = 0
        self._last = 0.0  # monotonic; 0 = never written
        self.failures = 0

    def note_ack(self) -> bool:
        """Record one durable-progress event; True when a snapshot is due."""
        with self._lock:
            self._acks += 1
            return self._due_locked()

    def _due_locked(self) -> bool:
        if self._acks < self.every:
            return False
        if self.interval_s and time.monotonic() - self._last < self.interval_s:
            return False
        return True

    def pending(self) -> bool:
        """Acks recorded but not yet made durable (reaper/stop flush them).
        Before the FIRST write, sub-threshold acks stay debounced (stop()
        still flushes them) — `_last == 0` must not read as 'interval long
        since elapsed'."""
        with self._lock:
            if self._acks == 0:
                return False
            if not self.interval_s:
                return True
            if self._last == 0.0:
                return False
            return time.monotonic() - self._last >= self.interval_s

    def write(self, master: TaskMaster) -> None:
        with self._lock:
            self._acks = 0
            self._last = time.monotonic()
        with self._write_lock:
            tmp = f"{self.path}.{os.getpid()}.tmp"
            try:
                master.snapshot(tmp)
                os.replace(tmp, self.path)
            except OSError as e:
                # progress was acked to the trainer but NOT made durable — a
                # crash now replays those tasks; say so instead of silently
                # losing recovery fidelity
                self.failures += 1
                log.warning(
                    "master snapshot to %s failed (%s); a crash before the "
                    "next successful snapshot will re-dispatch acked tasks",
                    self.path, e,
                )
            finally:
                if os.path.exists(tmp):
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass


# ---------------------------------------------------------------------------
# TCP service (the Go master's RPC role), newline-delimited JSON
# ---------------------------------------------------------------------------


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        ms: MasterServer = self.server.ctx  # type: ignore[attr-defined]
        for line in self.rfile:
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                self._reply({"err": "bad json"})
                continue
            if req.get("method") == "_hello":
                # wire negotiation (ISSUE 20): the probe and its answer ride
                # line JSON, so a legacy peer — which never probes — is
                # served bit-for-bit by this unchanged loop, while a
                # frames-capable client switches THIS connection to the
                # binary frame layer for the rest of its life
                if req.get("frames") == 1:
                    self._reply({"frames": 1})
                    self._serve_frames(ms)
                    return
                self._reply({"frames": 0})
                continue
            # span per RPC, adopting the caller's piggybacked trace context
            # (`_trace` on the line-JSON frame) so a task's or request's
            # spans stitch client → master under one trace id
            with obs_trace.server_span(
                "rpc." + str(req.get("method")), req.get("_trace"),
                side="server",
            ):
                keep, resp = self._handle_one(ms, req)
            if resp is not None:
                if "_bin" in resp:
                    # line JSON cannot carry raw bytes: base64 downgrade
                    resp = dict(resp)
                    resp["bin_b64"] = base64.b64encode(
                        resp.pop("_bin")
                    ).decode("ascii")
                self._reply(resp)
            if not keep:
                return

    def _serve_frames(self, ms: "MasterServer") -> None:
        """The framed connection loop: request frames are processed in
        arrival order and answered on the same socket, so a pipelining
        client (`MasterClient.call_many`) gets its replies back in request
        order, matched by req_id. A malformed frame severs with a NAMED
        error reply (frames.FrameError subclasses) instead of wedging this
        handler thread on a blocking read."""
        while True:
            try:
                got = frames.read_frame(self.rfile)
            except frames.FrameError as e:
                self._reply_frame({"err": f"{type(e).__name__}: {e}"}, 0, 0, b"")
                return
            if got is None:
                return
            req, req_id, _, _ = got
            with obs_trace.server_span(
                "rpc." + str(req.get("method")), req.get("_trace"),
                side="server",
            ):
                keep, resp = self._handle_one(ms, req)
            if resp is not None:
                flags = 0
                blob = b""
                if "_bin" in resp:
                    resp = dict(resp)
                    blob = resp.pop("_bin")
                    flags |= frames.FLAG_BIN_BLOB
                # piggyback discipline (ISSUE 20): while a resize epoch is
                # active the drain signal rides EVERY framed data reply to
                # a lease holder, not just heartbeat replies — a busy
                # reader hears it one data round trip sooner and its
                # heartbeat thread stands down (_Heartbeater's
                # data-fresh skip)
                if req.get("trainer_id") and "resize" not in resp:
                    rz = ms.resize.heartbeat_payload()
                    if rz is not None:
                        resp["_rz"] = rz
                        flags |= frames.FLAG_PIGGY
                self._reply_frame(resp, req_id, flags, blob)
            if not keep:
                return

    def _reply_frame(self, obj: dict, req_id: int, flags: int,
                     blob: bytes) -> None:
        try:
            frames.write_frame(
                self.wfile, obj, req_id=req_id, flags=flags, bin_payload=blob
            )
        except (OSError, ValueError):
            pass  # peer vanished mid-reply; its retry path handles it

    def _handle_one(
        self, ms: "MasterServer", req: dict
    ) -> Tuple[bool, Optional[dict]]:
        """Process one request -> (keep_connection, reply | None). The
        caller owns the wire (line vs frame encode); keep=False severs the
        connection (chaos sites, master killed under us)."""
        master = ms.master
        lock = ms.master_lock
        method = req.get("method")
        if faults.get().fire("master_drop"):
            # chaos hook: the RPC vanishes in transit — drop the
            # connection without processing or replying; the client's
            # reconnect/backoff path has to absorb it
            return False, None
        if faults.get().fire("master_kill"):
            # chaos hook: the master process dies mid-RPC — no reply, no
            # final snapshot, every open connection severed; only a
            # standby restoring the last on-disk snapshot saves the pass
            log.warning("chaos: master_kill fired — dying without reply")
            ms.kill()
            return False, None
        trainer_id = req.get("trainer_id")
        ms.membership.note_seen(trainer_id, req.get("role"))
        # (expired leases are swept by the reaper thread every lease_s/4 —
        # that bound IS the eager-requeue guarantee; scanning again per
        # RPC would only add membership-lock traffic to the hot path)
        # membership + observability RPCs never touch the native queue —
        # answered outside master_lock (drop_trainer takes it itself)
        if method == "register":
            role = req.get("role") or "trainer"
            tid = ms.membership.register(role)
            if (
                ms.resize_on_membership
                and role != "reader"
                and ms.membership.live_trainers > 1
            ):
                # join-triggered epoch: re-shape the fleet to the new live
                # TRAINER count (while another epoch is still in flight the
                # announce parks and the reaper re-fires it on completion);
                # a reader lease joining changes no world size
                ms.announce_membership_resize()
            return True, {
                "trainer_id": tid,
                "lease_s": ms.membership.lease_s,
            }
        if method == "heartbeat":
            # note_seen above already renewed (or adopted) the lease; a
            # piggybacked metrics snapshot joins the fleet aggregate
            if trainer_id and "metrics" in req:
                ms.fleet.update(trainer_id, req["metrics"])
            resp = {"ok": bool(trainer_id)}
            rz = ms.resize.heartbeat_payload()
            if rz is not None:
                # the resize drain signal rides the lease renewal — an
                # active epoch reaches every live trainer within one
                # heartbeat period, with zero extra control-plane RPCs
                resp["resize"] = rz
            return True, resp
        if method == "deregister":
            return True, {"ok": ms.drop_trainer(trainer_id, evict=False)}
        if method == "resize":
            # explicit fleet re-shape order (ops tooling / chaos bench);
            # join/evict-triggered epochs go through the same announce. A
            # malformed order gets an err REPLY — crashing the handler here
            # would sever the connection instead
            try:
                world = req["world"]
                # strict: a JSON bool/float would coerce under int() (True
                # -> 1 would re-shard the fleet to one chip; 4.7 -> 4) —
                # reply err instead of guessing what the operator meant
                if (
                    isinstance(world, bool)
                    or not isinstance(world, int)
                    or world < 1
                ):
                    raise ValueError(world)
            except (KeyError, TypeError, ValueError):
                return True, {
                    "err": f"resize needs a positive integer world, got "
                           f"{req.get('world')!r}"
                }
            return True, ms.resize.announce(world, ms.membership.ids())
        if method in ("resize_drained", "resize_status"):
            try:
                epoch = int(req.get("epoch", 0))
            except (TypeError, ValueError):
                epoch = -1  # malformed: matches no epoch, replies status-only
            # in `go`, a member's status poll doubles as its resumed ack
            return True, (
                ms.resize.ack_drained(trainer_id, epoch)
                if method == "resize_drained"
                else ms.resize.mark_resumed(trainer_id, epoch)
            )
        if method == "metrics":
            fleet = ms.fleet.aggregate()
            return True, {
                "text": obs_metrics.to_prometheus_text(fleet=fleet),
                "fleet": fleet,
            }
        if method == "trace_export":
            return True, {"chrome_trace": obs_trace.export_chrome()}
        if method == "snapshot_fetch":
            # bulk body (ISSUE 20): the snapshot blob rides the frame's RAW
            # binary payload (base64 over a line-JSON connection) — a
            # standby can warm itself over the wire instead of requiring
            # shared snapshot storage. The on-disk file is always a
            # complete snapshot (temp + rename writes), so a plain read
            # outside master_lock is consistent.
            path = ms.snapshot_path
            if not path or not os.path.exists(path):
                return True, {"err": "no snapshot available"}
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError as e:
                return True, {"err": f"snapshot read failed: {e}"}
            return True, {"_bin": blob, "bytes": len(blob)}
        snapshot_due = False
        with lock:
            if master.closed:  # killed under us — sever like a crash
                return False, None
            if method == "get_task":
                got = master.get_task()
                if got is None:
                    resp = {"retry": True}
                elif got[0] == TaskMaster.PASS_FINISHED:
                    resp = {"pass_finished": True}
                else:
                    resp = {"task_id": got[0], "shards": got[1]}
                    ms.membership.own(trainer_id, got[0])
            elif method == "get_tasks":
                # bulk range lease + piggybacked acks (ISSUE 20): done /
                # failed acks from the PREVIOUS batch land first — so the
                # final ack of a pass rides the very request that discovers
                # pass_finished — then up to n tasks are leased. One round
                # trip does what the single-task surface took 2n for.
                acked = 0
                for t in req.get("done_ids") or []:
                    if master.task_finished(int(t)):
                        acked += 1
                        if ms.snap is not None and ms.snap.note_ack():
                            snapshot_due = True
                    ms.membership.release(int(t))
                for t in req.get("failed_ids") or []:
                    master.task_failed(int(t))
                    ms.membership.release(int(t))
                tasks: List[dict] = []
                resp = {"tasks": tasks, "acked": acked}
                for _ in range(max(0, int(req.get("n", 1) or 0))):
                    got = master.get_task()
                    if got is None:
                        if not tasks:
                            resp["retry"] = True
                        break
                    if got[0] == TaskMaster.PASS_FINISHED:
                        if not tasks:
                            resp["pass_finished"] = True
                        break
                    tasks.append({"task_id": got[0], "shards": got[1]})
                    ms.membership.own(trainer_id, got[0])
            elif method == "task_finished":
                tid = int(req["task_id"])
                ok = master.task_finished(tid)
                ms.membership.release(tid)
                resp = {"ok": ok}
                if ok and ms.snap is not None:
                    snapshot_due = ms.snap.note_ack()
            elif method == "task_failed":
                tid = int(req["task_id"])
                ok = master.task_failed(tid)
                ms.membership.release(tid)
                resp = {"ok": ok}
            elif method == "set_dataset":
                master.set_dataset(
                    req["shards"], int(req.get("chunks_per_task", 1))
                )
                resp = {"ok": True}
            elif method == "pass_finished":
                resp = {
                    "finished": master.pass_finished(
                        bool(req.get("start_next", False))
                    )
                }
            elif method == "stats":
                resp = master.stats()
                resp["snapshot_failures"] = ms.snapshot_failures
                # role-aware: live_trainers is the world size a resize
                # would announce; reader leases show up in live_leases
                resp["live_trainers"] = ms.membership.live_trainers
                resp["live_leases"] = ms.membership.live
                resp["evicted_trainers"] = ms.membership.evicted
                # resize-epoch observability: state machine position,
                # completed-epoch count and the last epoch's latency split
                resp["resize"] = ms.resize.info()
                # fleet-wide aggregate of the heartbeat metric snapshots:
                # one stats() answers for every reporting trainer
                resp["fleet"] = ms.fleet.aggregate()
            else:
                resp = {"err": f"unknown method {method!r}"}
        if snapshot_due:
            # the write happens OUTSIDE master_lock: other trainers keep
            # getting tasks while this thread does file I/O (the native
            # snapshot takes its own internal mutex for a consistent view)
            ms.snap.write(master)
        return True, resp

    def _reply(self, obj: Any) -> None:
        try:
            self.wfile.write(json.dumps(obj).encode() + b"\n")
            self.wfile.flush()
        except (OSError, ValueError):
            pass  # peer vanished mid-reply; its retry path handles it


class MasterServer:
    """Threaded TCP wrapper; start()/stop(); port 0 picks a free port (the
    reference's in-process-localhost test idiom, test_CompareSparse.cpp:65).

    lease_s: trainer membership lease — a trainer silent for longer is
    evicted and its pending tasks are re-queued immediately.
    snapshot_every / snapshot_interval_s: debounce for the per-ack snapshot
    (at most once per N acks and once per T seconds; the reaper thread and
    stop() flush anything still pending)."""

    def __init__(
        self,
        master: Optional[TaskMaster] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        snapshot_path: Optional[str] = None,
        lease_s: float = 10.0,
        snapshot_every: int = 1,
        snapshot_interval_s: float = 0.0,
        resize_on_membership: bool = False,
        resize_drain_timeout_s: Optional[float] = None,
        initial_world: int = 0,
    ):
        self.master = master or TaskMaster()
        self.master_lock = threading.Lock()
        self.membership = _Membership(lease_s)
        # elastic resize epoch state machine; resize_on_membership=True also
        # announces an epoch (world = live trainer count) whenever a trainer
        # joins or is evicted — the join/evict-triggered policy; explicit
        # `resize` RPCs work either way. The drain timeout defaults to a few
        # leases: enough for every prompt member's next batch boundary,
        # short enough that one wedged-but-heartbeating member cannot hold
        # the fleet hostage.
        self.resize = _ResizeEpoch(
            drain_timeout_s=(
                resize_drain_timeout_s
                if resize_drain_timeout_s is not None
                else max(4.0 * lease_s, 10.0)
            )
        )
        # autoscaler hook (ISSUE 17): seed the resize plane's world so
        # `stats()["resize"]["world"]` answers "what IS the training world"
        # even before the first epoch — the stateless-reconciling
        # controller re-derives desired state from this observed value
        # instead of journaling its own actions
        self.resize.world = int(initial_world)
        self.resize_on_membership = resize_on_membership
        # membership churn that lands while an epoch is in flight parks here
        # (announce() rejects overlapping epochs); the reaper re-announces
        # against the CURRENT membership once the epoch completes, so the
        # fleet never settles at a stale world size
        self._resize_pending = False
        # serializes announce()+park so a successful announce on one handler
        # thread cannot clobber a concurrent rejected announce's park (the
        # lost-update hazard maybe_reannounce_resize's docstring describes)
        self._resize_announce_lock = threading.Lock()
        # per-trainer heartbeat metric snapshots → fleet aggregate in stats();
        # entries expire a few leases after the last heartbeat
        self.fleet = obs_metrics.FleetMetrics(ttl_s=max(3.0 * lease_s, 30.0))
        self.snap = (
            _SnapshotPolicy(snapshot_path, snapshot_every, snapshot_interval_s)
            if snapshot_path
            else None
        )
        self.snapshot_path = snapshot_path
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.ctx = self  # type: ignore[attr-defined]
        if snapshot_path and os.path.exists(snapshot_path):
            self.master.restore(snapshot_path)  # crash recovery (service.go:166)
        self._thread: Optional[threading.Thread] = None
        self._reaper: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._stopped = False
        self._killed = False

    @property
    def address(self) -> tuple:
        return self._srv.server_address

    @property
    def snapshot_failures(self) -> int:
        return self.snap.failures if self.snap is not None else 0

    @property
    def alive(self) -> bool:
        return (
            self._thread is not None
            and self._thread.is_alive()
            and not self._stopped
            and not self._killed
        )

    def evict_expired(self) -> int:
        """Drop trainers whose lease lapsed; re-queue their pending tasks NOW
        (the per-task timeout would get there eventually — minutes later)."""
        n = 0
        for tid in self.membership.expired():
            if self.drop_trainer(tid, evict=True):
                n += 1
        return n

    def announce_membership_resize(self) -> None:
        """Join/evict-triggered resize epoch for the CURRENT membership.
        The announced WORLD counts trainer-role leases only (a process may
        hold a reader lease too — double-counting would shard the data axis
        to a size no real trainer count backs), while the drain BARRIER
        spans every lease (readers drain between tasks). While another
        epoch is still in flight the announce is rejected; park it so the
        reaper fires it once the epoch completes instead of silently
        dropping the churn. The announce and the park write are one
        critical section: handler threads race here, and a successful
        announce's pending=False must not overwrite a concurrent rejected
        announce's park (that churn would be silently dropped). A success
        CLEARS the park because the membership it announced was read inside
        the same lock — any parked churn is subsumed by that epoch."""
        with self._resize_announce_lock:
            world = self.membership.live_trainers
            if not world:
                return
            r = self.resize.announce(world, self.membership.ids())
            self._resize_pending = "err" in r

    def maybe_reannounce_resize(self) -> None:
        """Reaper hook: fire a parked membership-churn announce once the
        in-flight epoch has completed. Ordering matters: while
        resize_on_membership is on, this thread must never WRITE
        _resize_pending=False on the not-pending path — an RPC handler's
        rejected announce can park (set True) between this thread's read
        and such a write, and the clobbered park would silently drop the
        churn (the fleet settles at a stale world size)."""
        if not self.resize_on_membership:
            self._resize_pending = False
            return
        if not self._resize_pending:
            return
        if self.resize.info()["state"] != "idle":
            return
        if not self.membership.live_trainers:
            # keep the park (same lost-update hazard as above): once a
            # trainer appears the next tick announces at the live count
            return
        self.announce_membership_resize()

    def drop_trainer(self, tid: Optional[str], evict: bool) -> bool:
        if not tid:
            return False
        was_trainer = self.membership.role(tid) != "reader"
        tasks = self.membership.drop(tid)
        self.fleet.drop(tid)
        # a dead/deregistered trainer must not hold up an in-flight resize
        # drain barrier — recompute it against the survivors
        self.resize.note_dropped(tid)
        if (
            evict
            and self.resize_on_membership
            and was_trainer
            and self.membership.live_trainers
        ):
            # evict-triggered epoch: shrink the fleet to the surviving
            # trainers (an evicted reader lease changes no world size)
            self.announce_membership_resize()
        requeued = 0
        with self.master_lock:
            if not self.master.closed:
                for t in tasks:
                    if self.master.task_failed(t):
                        requeued += 1
        if evict:
            self.membership.evicted += 1
            stats.FT_EVENTS.incr("trainer_evicted")
            log.warning(
                "trainer %s lease expired (%gs); evicted, %d pending task(s) "
                "re-queued eagerly", tid, self.membership.lease_s, requeued,
            )
        elif requeued:
            log.info(
                "trainer %s deregistered with %d task(s) in flight; re-queued",
                tid, requeued,
            )
        return True

    def _reap_loop(self) -> None:
        period = max(0.05, min(1.0, self.membership.lease_s / 4.0))
        while not self._stop_evt.wait(period):
            self.evict_expired()
            self.resize.tick()  # drain/go-phase timeout guard
            self.maybe_reannounce_resize()  # parked membership churn
            if self.snap is not None and self.snap.pending():
                # quiet-period flush: acks below the debounce threshold still
                # become durable without waiting for the next burst
                with self.master_lock:
                    closed = self.master.closed
                if not closed:
                    self.snap.write(self.master)

    def start(self) -> "MasterServer":
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()
        return self

    def stop(self) -> None:
        """Graceful shutdown: stop serving, flush a final snapshot, close the
        native handle. Idempotent (and safe after kill())."""
        if self._stopped:
            return
        self._stopped = True
        self._stop_evt.set()
        if self._thread is not None:  # shutdown() hangs if serve never ran
            self._srv.shutdown()
        self._srv.server_close()
        if self._reaper is not None:
            self._reaper.join(timeout=5.0)
        if not self._killed and self.snap is not None and not self.master.closed:
            self.snap.write(self.master)
        # the native TaskMaster handle used to leak here — close it (close()
        # is a no-op on an already-closed handle)
        self._close_master()

    def _close_master(self) -> None:
        """Destroy the native handle serialized against BOTH in-flight RPC
        dispatch (master_lock) and any debounced snapshot writer that runs
        outside it (_write_lock) — never a use-after-free under the lib."""
        if self.snap is not None:
            with self.snap._write_lock, self.master_lock:
                self.master.close()
        else:
            with self.master_lock:
                self.master.close()

    def kill(self) -> None:
        """Crash semantics (chaos master_kill): stop serving abruptly — NO
        final snapshot, so recovery exercises the last debounced on-disk
        state, exactly like a real master death."""
        if self._killed or self._stopped:
            return
        self._killed = True
        self._stop_evt.set()

        def _die():
            try:
                if self._thread is not None:
                    self._srv.shutdown()
                self._srv.server_close()
            except OSError:
                pass
            self._close_master()

        # shutdown() must not run on a handler thread holding the serve loop's
        # attention — a dedicated thread severs everything without deadlock
        threading.Thread(target=_die, daemon=True).start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


def standby_master(
    primary: EndpointsLike,
    host: str = "127.0.0.1",
    port: int = 0,
    snapshot_path: Optional[str] = None,
    poll_s: float = 0.2,
    confirm_failures: int = 2,
    max_wait_s: Optional[float] = None,
    stop_evt: Optional[threading.Event] = None,
    **server_kw,
) -> Optional[MasterServer]:
    """Warm-standby loop: watch `primary`; when it stays unreachable for
    `confirm_failures` consecutive probes, restore the shared snapshot and
    start serving on (host, port). Blocks until takeover (returns the started
    server), `max_wait_s` elapses, or `stop_evt` is set (returns None).

    The standby does NOT bind its port before takeover — a client failing
    over early gets connection-refused and keeps rotating. Death evidence is
    weighed: a refused/unreachable probe counts fully, a TIMED-OUT probe
    (slow ≠ dead) only half, and a final patient probe must still fail
    before binding — a briefly-overloaded primary is not usurped. Without a
    consensus backend this is still a heuristic: a primary alive on the far
    side of a real network partition can double-serve; production
    deployments should fence via the shared snapshot storage.

    The watch/confirm loop itself is `runtime/election.py` (ISSUE 18) —
    this is the master-plane consumer of the same primitive `RouterStandby`
    and `AutoscalerStandby` stand on."""
    from paddle_tpu.runtime.election import watch_primary

    token = watch_primary(
        primary, plane="master", poll_s=poll_s,
        confirm_failures=confirm_failures, max_wait_s=max_wait_s,
        stop_evt=stop_evt,
    )
    if token is None:
        return None
    log.warning(
        "standby master (incarnation %s) taking over on %s:%d from "
        "snapshot %s", token, host, port, snapshot_path,
    )
    return MasterServer(
        host=host, port=port, snapshot_path=snapshot_path, **server_kw
    ).start()


# exit code of a served master that died to the master_kill chaos site —
# distinct from 0 (clean stop) so a supervisor/test can tell crash from stop
KILLED_EXIT = 17


def _main(argv: Optional[List[str]] = None) -> int:
    """`python -m paddle_tpu.runtime.master serve|standby ...` — a master (or
    warm standby) as its own OS process, for the multi-process chaos
    scenarios in benchmarks/chaos_bench.py and tests/test_cluster.py."""
    import argparse
    import signal as _signal

    ap = argparse.ArgumentParser(prog="paddle_tpu.runtime.master")
    sub = ap.add_subparsers(dest="role", required=True)
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--host", default="127.0.0.1")
    common.add_argument("--port", type=int, required=True)
    common.add_argument("--snapshot", default=None)
    common.add_argument("--lease_s", type=float, default=10.0)
    common.add_argument("--snapshot_every", type=int, default=1)
    common.add_argument("--snapshot_interval_s", type=float, default=0.0)
    common.add_argument("--timeout_s", type=float, default=60.0)
    common.add_argument("--failure_max", type=int, default=3)
    common.add_argument("--faults", default=None)
    common.add_argument("--faults_seed", type=int, default=0)
    common.add_argument(
        "--trace", type=int, default=0,
        help="1 = record RPC spans into the ring buffer (also settable via "
             "PADDLE_TPU_TRACE); fetch them with the trace_export RPC or "
             "`python -m paddle_tpu.obs trace --endpoint host:port`",
    )
    sub.add_parser("serve", parents=[common])
    st = sub.add_parser("standby", parents=[common])
    st.add_argument("--primary", required=True, help="host:port to watch")
    st.add_argument("--poll_s", type=float, default=0.2)
    st.add_argument("--max_wait_s", type=float, default=None)
    args = ap.parse_args(argv)

    if args.faults:
        faults.get().configure(args.faults, args.faults_seed)
    if args.trace:
        obs_trace.enable_tracing(True)

    def build() -> MasterServer:
        return MasterServer(
            TaskMaster(timeout_s=args.timeout_s, failure_max=args.failure_max),
            host=args.host,
            port=args.port,
            snapshot_path=args.snapshot,
            lease_s=args.lease_s,
            snapshot_every=args.snapshot_every,
            snapshot_interval_s=args.snapshot_interval_s,
        ).start()

    if args.role == "serve":
        server = build()
    else:
        got = standby_master(
            args.primary,
            host=args.host,
            port=args.port,
            snapshot_path=args.snapshot,
            poll_s=args.poll_s,
            max_wait_s=args.max_wait_s,
            master=TaskMaster(
                timeout_s=args.timeout_s, failure_max=args.failure_max
            ),
            lease_s=args.lease_s,
            snapshot_every=args.snapshot_every,
            snapshot_interval_s=args.snapshot_interval_s,
        )
        if got is None:
            print(json.dumps({"role": args.role, "takeover": False}), flush=True)
            return 3
        server = got

    _signal.signal(_signal.SIGTERM, lambda *_: server.stop())
    _signal.signal(_signal.SIGINT, lambda *_: server.stop())
    print(
        json.dumps({"role": args.role, "address": list(server.address)}),
        flush=True,
    )
    while server.alive:
        time.sleep(0.05)
    # distinguish the chaos master_kill crash from a clean SIGTERM stop
    return KILLED_EXIT if server._killed else 0


class _CountingReader:
    """Buffered-reader wrapper that counts received bytes into its owning
    MasterClient — the wire-economics observability (bytes per delivered
    token, bytes per task) the benches report rides on these counters."""

    __slots__ = ("_f", "_owner")

    def __init__(self, f, owner: "MasterClient"):
        self._f = f
        self._owner = owner

    def read(self, n: int = -1) -> bytes:
        b = self._f.read(n)
        self._owner.bytes_received += len(b)
        return b

    def readline(self) -> bytes:
        b = self._f.readline()
        self._owner.bytes_received += len(b)
        return b

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        b = self.readline()
        if not b:
            raise StopIteration
        return b

    def close(self) -> None:
        self._f.close()


class MasterClient:
    """Blocking RPC client with reconnect + endpoint failover
    (go/master/client.go parity), speaking either wire.

    `address` may be one endpoint or a failover list ((h, p), "h:p",
    "a:p1,b:p2", or a sequence of those — the CLI's --master_endpoints form).
    Failed calls reconnect and retry with bounded exponential backoff plus
    jitter (the Go client's backoff discipline; jitter keeps a restarted
    master from being stampeded by every trainer retrying in lockstep),
    rotating to the next endpoint on every reconnect so a dead primary's
    standby is found inside the same loop. After `retries` attempts
    (default: enough for several full rotations) the terminal ConnectionError
    names the method, the endpoints, the attempt count and the last
    underlying error.

    Wire (ISSUE 20): each connection opens with the line-JSON `_hello`
    probe; a frames-capable server upgrades the connection to the binary
    frame layer (runtime/frames.py — pipelining via `call_many`, binary
    token payloads, header trace context, piggybacked control signals), a
    legacy server refuses and the client stays line-JSON (memoized per
    endpoint so later reconnects skip the probe). `wire` /
    `PADDLE_TPU_WIRE` selects: "auto" (default), "json" (never probe),
    "frames" (downgrade is an error). All traffic rides ONE socket per
    endpoint; `close()` releases the buffered reader and writer with it."""

    def __init__(
        self,
        address: EndpointsLike,
        timeout: float = 30.0,
        retries: Optional[int] = None,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        wire: Optional[str] = None,
        on_piggyback: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self.endpoints = parse_endpoints(address)
        self.timeout = timeout
        self.retries = (
            max(1, int(retries))
            if retries is not None
            else max(5, 4 * len(self.endpoints))
        )
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.wire = (wire or os.environ.get("PADDLE_TPU_WIRE", "auto")).lower()
        # piggybacked control signals stripped off data replies (`_rz`, the
        # resize drain signal) land here instead of surprising callers
        self.on_piggyback = on_piggyback
        self._i = 0
        self._sock: Optional[socket.socket] = None
        self._rfile: Optional[_CountingReader] = None
        self._wfile = None
        self._framed = False
        self._req_seq = 0
        # endpoints that refused the hello probe: line-JSON forever (well,
        # until this client object dies) — no re-probe per reconnect
        self._legacy: Set[Endpoint] = set()
        self.bytes_sent = 0
        self.bytes_received = 0
        self.round_trips = 0
        # monotonic stamp of the last successful RPC: the heartbeat
        # suppression signal (_Heartbeater skips while data-plane traffic
        # bearing the trainer_id is fresher than a heartbeat would be)
        self.last_rpc = 0.0

    @property
    def address(self) -> Endpoint:
        """The endpoint currently in use (compat with the single-address API)."""
        return self.endpoints[self._i]

    @property
    def wire_framed(self) -> bool:
        """True when the CURRENT connection negotiated the frame layer."""
        return self._framed

    def _connect(self):
        if self._sock is not None:
            return
        self._sock = socket.create_connection(self.address, timeout=self.timeout)
        self._rfile = _CountingReader(self._sock.makefile("rb"), self)
        self._wfile = self._sock.makefile("wb")
        self._framed = False
        if self.wire != "json" and self.address not in self._legacy:
            self._hello()

    def _hello(self) -> None:
        """Wire negotiation: one line-JSON probe per fresh connection. A
        legacy server answers unknown-method (memoized: later reconnects to
        that endpoint skip the probe), a frames-capable one answers
        {"frames": 1} and this connection switches to the frame layer."""
        probe = json.dumps({"method": "_hello", "frames": 1}).encode() + b"\n"
        self._sock.sendall(probe)
        self.bytes_sent += len(probe)
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("master closed connection during hello")
        if json.loads(line).get("frames") == 1:
            self._framed = True
            return
        if self.wire == "frames":
            raise ConnectionError(
                f"endpoint {self.address} refused the frame layer and "
                f"wire='frames' forbids the line-JSON downgrade"
            )
        self._legacy.add(self.address)

    def _rotate(self) -> None:
        if len(self.endpoints) > 1:
            self._i = (self._i + 1) % len(self.endpoints)
            stats.FT_EVENTS.incr("master_failover")
            log.warning("master failover: trying endpoint %s:%d", *self.address)

    def _send(self, req: dict) -> int:
        """Write one request on the current wire; returns its req_id (0 on
        line JSON). frames.write_frame is THE frame-encode site — no
        json.dumps on the framed path (hot-loop lint)."""
        if self._framed:
            self._req_seq = ((self._req_seq + 1) & 0xFFFFFFFF) or 1
            self.bytes_sent += frames.write_frame(
                self._wfile, req, req_id=self._req_seq
            )
            return self._req_seq
        msg = json.dumps(req).encode() + b"\n"
        self._sock.sendall(msg)
        self.bytes_sent += len(msg)
        return 0

    def _recv(self, want_rid: int) -> dict:
        if self._framed:
            got = frames.read_frame(self._rfile)
            if got is None:
                raise ConnectionError("master closed connection")
            resp, rid, flags, blob = got
            if rid != want_rid:
                raise frames.FrameError(
                    f"reply id {rid} does not match request {want_rid}"
                )
            return frames.decode_payload(resp, rid, flags, blob)
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("master closed connection")
        return json.loads(line)

    def _absorb(self, resp: dict) -> dict:
        """Per-reply bookkeeping: stamp data-plane freshness (heartbeat
        suppression) and strip piggybacked control signals to the
        on_piggyback hook."""
        self.last_rpc = time.monotonic()
        if isinstance(resp, dict) and "_rz" in resp:
            rz = resp.pop("_rz")
            if self.on_piggyback is not None:
                try:
                    self.on_piggyback(rz)
                except Exception:
                    log.exception("piggyback callback failed")
        return resp

    def call(self, method: str, **kw) -> dict:
        """One RPC (with reconnect/failover/backoff). With tracing enabled
        the call runs inside a client span and piggybacks its context on the
        frame (`_trace` — moved into the binary header on a framed
        connection), so the server's handler span joins this trace."""
        if obs_trace.TRACER.enabled:
            with obs_trace.span("rpc." + method, side="client") as sp:
                kw["_trace"] = {"t": sp.trace_id, "s": sp.span_id}
                return self._call(method, kw)
        return self._call(method, kw)

    def call_many(self, calls: Sequence[Tuple[str, dict]]) -> List[dict]:
        """Pipelined batch (ISSUE 20): write every request back-to-back on
        the ONE socket, then collect the replies in order, matched by
        request id — N calls for one round trip of latency (the server
        processes a connection's frames sequentially and answers in
        arrival order). On a line-JSON connection this degrades to serial
        `call`s. A connection failure retries the WHOLE batch through the
        same reconnect/failover/backoff path as `call`, so callers pass
        retry-exact requests (idempotency keys) — the discipline every RPC
        here already follows."""
        if not calls:
            return []
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            try:
                self._connect()
                if not self._framed:
                    return [self._call(m, dict(kw)) for m, kw in calls]
                if faults.get().fire("conn_reset"):
                    # chaos hook: the socket resets with the batch in
                    # flight — the retry must re-send ALL of it
                    raise ConnectionResetError("injected conn_reset (chaos)")
                rids = [self._send({"method": m, **kw}) for m, kw in calls]
                out = [self._absorb(self._recv(rid)) for rid in rids]
                self.round_trips += 1
                return out
            except (OSError, ConnectionError, json.JSONDecodeError) as e:
                last_err = e
                self.close()
                stats.FT_EVENTS.incr("master_reconnect")
                self._rotate()
                if attempt + 1 < self.retries:
                    delay = min(self.backoff_max, self.backoff_base * 2 ** attempt)
                    delay *= 0.5 + random.random() / 2
                    log.warning(
                        "pipelined batch of %d failed (%s: %s); reconnecting "
                        "in %.0fms (attempt %d/%d)", len(calls),
                        type(e).__name__, e, delay * 1e3, attempt + 1,
                        self.retries,
                    )
                    time.sleep(delay)
        raise ConnectionError(
            f"pipelined batch of {len(calls)} to {self.endpoints} failed "
            f"after {self.retries} attempts; giving up (last error: "
            f"{type(last_err).__name__}: {last_err})"
        ) from last_err

    def _call(self, method: str, kw: dict) -> dict:
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            try:
                self._connect()
                if faults.get().fire("conn_reset"):
                    # chaos hook: network partition/RST between trainer and
                    # master — the reconnect/failover path must absorb it
                    raise ConnectionResetError("injected conn_reset (chaos)")
                rid = self._send({"method": method, **kw})
                resp = self._recv(rid)
                self.round_trips += 1
                return self._absorb(resp)
            except (OSError, ConnectionError, json.JSONDecodeError) as e:
                last_err = e
                self.close()
                stats.FT_EVENTS.incr("master_reconnect")
                self._rotate()
                if attempt + 1 < self.retries:
                    delay = min(self.backoff_max, self.backoff_base * 2 ** attempt)
                    delay *= 0.5 + random.random() / 2  # full-jitter in [.5d, d)
                    log.warning(
                        "master RPC %r failed (%s: %s); reconnecting in %.0fms "
                        "(attempt %d/%d)", method, type(e).__name__, e,
                        delay * 1e3, attempt + 1, self.retries,
                    )
                    time.sleep(delay)
        raise ConnectionError(
            f"master RPC {method!r} to {self.endpoints} failed after "
            f"{self.retries} attempts; giving up (last error: "
            f"{type(last_err).__name__}: {last_err})"
        ) from last_err

    def call_stream(self, method: str, **kw) -> Iterator[dict]:
        """One request whose reply is a FRAME STREAM (serving push
        streaming, ISSUE 16): the request and its FIRST reply line go
        through the normal reconnect/backoff path, then every subsequent
        line on the same connection is yielded as a frame until one
        carries `done` (or the first reply was an error). Delivered
        frames are never replayed — a mid-stream failure raises
        ConnectionError and resumable callers reattach with their token
        cursor (the serving `from` cursor), on a FRESH call. The
        connection is reusable after a clean `done`; an abandoned or
        broken stream drops it (frames may still be buffered). On a framed
        connection the pushed frames are BINARY (compact token deltas,
        runtime/frames.py) — decoded here back to the exact dicts a
        line-JSON peer would see."""
        first = self._call(method, kw)
        yield first
        if "err" in first:
            return
        clean = False
        try:
            if self._framed:
                while True:
                    got = frames.read_frame(self._rfile)
                    if got is None:
                        raise ConnectionError(
                            "stream closed before its final frame"
                        )
                    obj, rid, flags, blob = got
                    frame = self._absorb(
                        frames.decode_payload(obj, rid, flags, blob)
                    )
                    if frame.get("done"):
                        clean = True
                        yield frame
                        return
                    yield frame
            for line in self._rfile:
                try:
                    frame = json.loads(line)
                except json.JSONDecodeError as e:
                    raise ConnectionError(f"bad stream frame: {e}") from e
                if frame.get("done"):
                    clean = True
                    yield frame
                    return
                yield frame
            raise ConnectionError("stream closed before its final frame")
        except OSError as e:
            raise ConnectionError(f"stream broke mid-flight: {e}") from e
        finally:
            if not clean:
                self.close()

    def close(self) -> None:
        # hygiene (ISSUE 20): the buffered reader/writer makefile objects
        # are closed WITH the socket — the old path nulled the reader
        # without closing it, leaking the buffer until GC on every
        # reconnect of a long-lived client
        for f in (self._rfile, self._wfile):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        self._rfile = None
        self._wfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._framed = False


class _Heartbeater:
    """Background lease renewal on its OWN connection (the reader's socket is
    busy inside blocking calls; sharing it would interleave frames).

    Heartbeat REPLIES carry the master's piggybacked resize drain signal
    while an epoch is active; it is stashed on the shared `ident` dict
    (`ident["resize"]`) for the reader's between-task drain check and handed
    to `on_resize` (the ResizeClient's watcher) when given."""

    def __init__(
        self,
        address: EndpointsLike,
        ident: Dict[str, Any],
        client_kw: Optional[dict] = None,
        on_resize: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self._ident = ident
        self._client = MasterClient(address, **(client_kw or {}))
        self._on_resize = on_resize
        self.skipped = 0
        self._skip_streak = 0
        self._evt = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="master-heartbeat", daemon=True
        )

    def start(self) -> "_Heartbeater":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            period = max(0.05, float(self._ident.get("lease_s", 10.0)) / 3.0)
            if self._evt.wait(period):
                return
            tid = self._ident.get("trainer_id")
            if tid is None:
                continue
            last = self._ident.get("last_rpc")
            if (
                last is not None
                and time.monotonic() - last < period
                and self._skip_streak < 2
            ):
                # piggyback discipline (ISSUE 20): fresh data-plane traffic
                # bearing this trainer_id already renewed the lease
                # (note_seen fires on every RPC) and carried any resize
                # signal on its framed reply (`_rz`) — an explicit
                # heartbeat would be a pure extra round trip. Capped at 2
                # consecutive skips so the metrics snapshot still reaches
                # the fleet aggregate at a third of the usual cadence.
                self.skipped += 1
                self._skip_streak += 1
                stats.FT_EVENTS.incr("heartbeat_piggybacked")
                continue
            self._skip_streak = 0
            try:
                # metrics snapshot piggybacks on the lease renewal — the
                # master aggregates these into its fleet-wide stats() view
                hb_kw: Dict[str, Any] = {
                    "trainer_id": tid,
                    "metrics": obs_metrics.snapshot(),
                }
                if self._ident.get("role"):
                    # re-assert the lease role so an adoption after master
                    # failover heals the type (reader vs trainer) too
                    hb_kw["role"] = self._ident["role"]
                resp = self._client.call("heartbeat", **hb_kw)
            except ConnectionError:
                # terminal after retries+failover — the lease will lapse and
                # the master re-queues our tasks; the reader's own calls will
                # surface the outage, nothing more to do here
                stats.FT_EVENTS.incr("heartbeat_lost")
                continue
            rz = resp.get("resize") if isinstance(resp, dict) else None
            if rz:
                self._ident["resize"] = rz
                if self._on_resize is not None:
                    try:
                        self._on_resize(rz)
                    except Exception:
                        log.exception("resize watcher callback failed")

    def stop(self) -> None:
        self._evt.set()
        self._thread.join(timeout=5.0)
        self._client.close()


def _barrier_master_lost(
    epoch: int, fallback_world: int, err: Exception
) -> int:
    """The master died mid-epoch (retries exhausted): the documented
    proceed-alone fallback, not a crash of the training pass."""
    stats.FT_EVENTS.incr("resize_barrier_master_lost")
    log.warning(
        "resize epoch %d: master unreachable at the drain barrier (%s) — "
        "proceeding alone with world=%d", epoch, err, fallback_world,
    )
    return fallback_world


# cluster_reader idents living in THIS process, so a co-resident trainer's
# drain barrier can ack on their behalf (see _service_reader_drains)
_READER_IDENTS: List[Dict[str, Any]] = []
_READER_IDENTS_LOCK = threading.Lock()


def _service_reader_drains(client: MasterClient) -> None:
    """Ack the drain for any cluster_reader lease in THIS process whose
    consuming loop cannot reach its own between-task boundary right now —
    in the two-lease setup the reader feeds the very train loop that is
    parked inside the trainer's drain barrier (same thread), so without
    this the barrier and the reader serialize into a circular wait the
    master could only break by timing the healthy reader lease out. A
    reader lease holds no in-flight RESIZE obligation beyond its ack (task
    accounting is lease-based either way); its resumed ack still rides the
    reader's next boundary poll."""
    with _READER_IDENTS_LOCK:
        idents = list(_READER_IDENTS)
    for ident in idents:
        info = ident.get("resize")
        tid = ident.get("trainer_id")
        if not info or tid is None or info.get("state") != "draining":
            continue
        try:
            epoch = int(info.get("epoch", 0))
        except (TypeError, ValueError):
            continue
        key = (info.get("instance"), epoch)
        if key == ident.get("resize_done"):
            continue
        try:
            client.call("resize_drained", trainer_id=tid, epoch=epoch)
        except ConnectionError:
            continue  # the reader's own boundary (or eviction) handles it
        stats.FT_EVENTS.incr("reader_resize_drain")
        ident["resize_done"] = key
        ident["resize_resume"] = epoch
        ident.pop("resize", None)


def _drain_barrier(
    client: MasterClient,
    trainer_id: str,
    epoch: int,
    fallback_world: int,
    poll_s: float = 0.1,
    max_wait_s: float = 120.0,
) -> int:
    """One member's walk through the drain barrier: ack `resize_drained`,
    poll `resize_status` until the epoch leaves `draining` (every live member
    acked, or the stragglers were evicted), mark resumed, and return the
    final world size. A barrier that never resolves within `max_wait_s`
    (master gone mid-epoch) falls back to the announced world so the member
    can proceed alone."""
    # chaos hook: wedge INSIDE the barrier without acking — the master's
    # drain timeout (or lease eviction, if heartbeats stop too) must remove
    # this member for the epoch to complete
    faults.maybe_stall("resize_drain_stall")
    try:
        info = client.call("resize_drained", trainer_id=trainer_id, epoch=epoch)
    except ConnectionError as e:
        return _barrier_master_lost(epoch, fallback_world, e)
    deadline = time.monotonic() + max_wait_s
    while info.get("state") == "draining" and info.get("epoch") == epoch:
        if time.monotonic() > deadline:
            log.warning(
                "resize epoch %d: drain barrier unresolved after %.0fs — "
                "proceeding alone with world=%d", epoch, max_wait_s,
                fallback_world,
            )
            return fallback_world
        # co-resident reader leases can't ack while we hold their consumer
        # thread here; their heartbeat stash may land at any poll, so
        # service them every iteration (no-op when nothing is stashed)
        _service_reader_drains(client)
        time.sleep(poll_s)
        try:
            info = client.call(
                "resize_status", trainer_id=trainer_id, epoch=epoch
            )
        except ConnectionError as e:
            return _barrier_master_lost(epoch, fallback_world, e)
    if info.get("state") == "go" and info.get("epoch") == epoch:
        # the status poll that observes `go` is the resumed ack; make sure
        # one landed even when the drained reply itself already said go
        try:
            info = client.call(
                "resize_status", trainer_id=trainer_id, epoch=epoch
            )
        except ConnectionError:
            # the master decided `go` and then died: the observed world IS
            # the decision — proceed with it; there is nobody left to ack
            stats.FT_EVENTS.incr("resize_barrier_master_lost")
    if info.get("epoch") == epoch and info.get("world"):
        return int(info["world"])
    last = info.get("last") or {}
    if last.get("epoch") == epoch and last.get("world"):
        # the epoch completed (and went idle) before we looked
        return int(last["world"])
    return fallback_world


class ResizeClient:
    """Trainer-side fleet hook for elastic resize (ISSUE 8).

    Registers a membership lease, heartbeats it from a background thread,
    and watches the heartbeat replies for an announced resize epoch: on
    `draining` it parks a resize order on the core.preempt guard, which the
    train loop claims at its next dispatch boundary. Pass `barrier` as
    `SGDTrainer.train(resize_barrier=...)` — after the trainer's mid-pass
    drain checkpoint it acks `resize_drained`, blocks until every live
    member drained (or was evicted), and returns the final world size to
    re-shard to.

        rc = ResizeClient("host:p1,host:p2")
        trainer.train(reader, resize_barrier=rc.barrier, ...)
        rc.close()

    A trainer that ALSO consumes tasks via cluster_reader holds two
    membership leases (the reader's and this one); both join the drain
    barrier and both ack — the reader between tasks (without blocking for
    go), this client at the trainer's batch boundary. When the resize lands
    mid-task the trainer drains first while it holds the reader's consumer
    thread, so the barrier acks the reader lease on its behalf
    (_service_reader_drains) instead of waiting for a boundary that cannot
    come."""

    def __init__(
        self,
        address: EndpointsLike,
        client_kw: Optional[dict] = None,
        poll_s: float = 0.1,
        max_wait_s: float = 120.0,
    ):
        self._client = MasterClient(address, **(client_kw or {}))
        resp = self._client.call("register")
        if "trainer_id" not in resp:
            raise ConnectionError(
                f"resize client could not register with the master: {resp}"
            )
        self.trainer_id = resp["trainer_id"]
        self._ident: Dict[str, Any] = {
            "trainer_id": self.trainer_id,
            "lease_s": float(resp.get("lease_s", 10.0)),
        }
        self.poll_s = poll_s
        self.max_wait_s = max_wait_s
        self._seen: Optional[Tuple[Any, int]] = None
        self._hb = _Heartbeater(
            address, self._ident, client_kw=client_kw, on_resize=self._watch
        ).start()

    def _watch(self, info: Dict[str, Any]) -> None:
        """Heartbeat-thread hook: turn a newly-announced epoch into a parked
        resize order (idempotent per epoch — re-announcements of an epoch we
        already claimed must not re-trigger a drain). The epoch's identity
        is (master instance, epoch number), compared by equality: epoch
        numbers are per-master-instance counters, so a restarted/standby
        master announcing a number we already handled — equal OR lower — is
        a genuinely new epoch, and suppressing it would silently exempt
        this trainer from every resize the new master coordinates."""
        from paddle_tpu.core import preempt

        try:
            epoch = int(info.get("epoch", 0))
            world = int(info.get("world", 0))
        except (TypeError, ValueError):
            return
        if info.get("state") != "draining" or world < 1:
            return
        key = (info.get("instance"), epoch)
        if key == self._seen:
            return
        self._seen = key
        preempt.get().request_resize(
            world, epoch=epoch, instance=info.get("instance") or "",
            reason="master resize epoch",
        )

    def barrier(self, req, pass_id: int, batches_done: int) -> int:
        """The train(resize_barrier=...) callable (see _drain_barrier)."""
        return _drain_barrier(
            self._client, self.trainer_id, req.epoch, req.world,
            poll_s=self.poll_s, max_wait_s=self.max_wait_s,
        )

    def close(self) -> None:
        self._hb.stop()
        try:
            self._client.call("deregister", trainer_id=self.trainer_id)
        except ConnectionError:
            pass  # lease will simply expire
        self._client.close()


def cluster_reader(
    master_address: EndpointsLike,
    deserialize: Callable[[bytes], Any] = None,
    poll_interval: float = 0.5,
    register: bool = True,
    client_kw: Optional[dict] = None,
    lease_batch: int = 1,
) -> Callable[[], Iterator[Any]]:
    """v2 cluster reader (master/client.py:15): pull tasks from the master,
    stream their recordio shards, ack on completion, report failures. One
    call of the returned reader = one pass.

    `master_address` may be a failover list (see MasterClient). With
    `register=True` the reader takes out a membership lease and renews it
    from a background heartbeat thread, so a trainer that dies mid-task is
    evicted and its tasks re-queued eagerly rather than after the per-task
    timeout; the lease is released (`deregister`) on a clean pass end.

    Wire economics (ISSUE 20): tasks are leased through the bulk
    `get_tasks` form — up to `lease_batch` tasks per round trip, with the
    PREVIOUS batch's done acks piggybacked on the same request, so the
    steady-state cost is 1/lease_batch round trips per task where the
    single-task surface paid 2 (lease + ack). Failure acks flush eagerly.
    Deferred done acks are flushed before joining a resize drain barrier
    (the lease must hold no half-acked task across an epoch) and on every
    exit path; an ack lost to a crash replays its task — exactly the
    at-least-once delivery the single-task ack-loss path already had. On a
    framed connection the resize drain signal also piggybacks on data
    replies and the heartbeat thread stands down while data-plane traffic
    is fresh (_Heartbeater), cutting the idle control chatter too.

    Elastic resize: a registered reader is a drain-barrier MEMBER. When the
    heartbeat thread sees an announced epoch it stashes the signal on the
    shared ident; the reader drains at its natural boundary — between task
    acks, holding no in-flight task (so the master's exactly-once accounting
    needs no special casing) — and acks `resize_drained` WITHOUT blocking
    for go (in the two-lease setup the trainer lease's ack, which go also
    needs, can only happen after this reader yields back to the train loop);
    the resumed ack rides a `resize_status` poll at a later boundary."""
    import pickle

    deserialize = deserialize or pickle.loads

    def _maybe_drain(client: MasterClient, ident: Dict[str, Any]) -> None:
        tid = ident.get("trainer_id")
        if tid is None:
            return
        pending = ident.get("resize_resume")
        if pending is not None:
            # a previous boundary acked the drain without blocking; finish
            # the epoch's bookkeeping now — a resize_status poll that
            # observes `go` IS this lease's resumed ack (any other state
            # means the epoch moved on without us, e.g. closed by the
            # go-phase timeout or eviction — nothing left to ack)
            try:
                st = client.call("resize_status", trainer_id=tid, epoch=pending)
            except ConnectionError:
                st = {}
            if st.get("state") != "draining" or st.get("epoch") != pending:
                ident.pop("resize_resume", None)
        info = ident.get("resize")
        if not info:
            return
        try:
            epoch = int(info.get("epoch", 0))
        except (TypeError, ValueError):
            ident.pop("resize", None)
            return
        # the epoch's identity is (master instance, number) — see
        # ResizeClient._watch: a restarted master re-counts from 1, so a
        # number collision with the last drained epoch of a PREVIOUS
        # master must not make this reader skip the new master's barrier
        key = (info.get("instance"), epoch)
        if info.get("state") != "draining" or key == ident.get("resize_done"):
            ident.pop("resize", None)
            return
        # ack the drain WITHOUT blocking for go: when the process also runs
        # a ResizeClient-coordinated trainer on this thread (the documented
        # two-lease setup), go needs the trainer lease's ack too — and that
        # ack only happens once this reader yields back to the train loop's
        # dispatch boundary, so waiting here would serialize into a circular
        # wait the master could only break by timing out a healthy lease.
        # The reader holds no in-flight task at this point either way, which
        # is all the exactly-once accounting needs; the resumed ack rides
        # the status poll at a later boundary (or, for a pass that ends
        # first, deregister's barrier drop closes the epoch).
        faults.maybe_stall("resize_drain_stall")
        try:
            client.call("resize_drained", trainer_id=tid, epoch=epoch)
        except ConnectionError as e:
            _barrier_master_lost(epoch, int(info.get("world", 0) or 0), e)
            ident.pop("resize", None)
            return
        stats.FT_EVENTS.incr("reader_resize_drain")
        ident["resize_resume"] = epoch
        ident["resize_done"] = key
        ident.pop("resize", None)

    def reader() -> Iterator[Any]:
        client = MasterClient(master_address, **(client_kw or {}))
        # reader-role lease: joins resize drain barriers (and is drained
        # between task acks) but does not count toward a membership-
        # triggered world size — the process's ResizeClient lease does
        ident: Dict[str, Any] = {
            "trainer_id": None, "lease_s": 10.0, "role": "reader",
        }
        # a resize drain signal piggybacked on a framed data reply lands in
        # the same slot the heartbeat thread uses — one consumption path
        client.on_piggyback = lambda rz: ident.__setitem__("resize", rz)
        # done acks deferred onto the next get_tasks request (failed acks
        # flush eagerly); lists, drained atomically after a successful call
        pending_done: List[int] = []
        pending_failed: List[int] = []

        def _id_kw() -> Dict[str, Any]:
            return (
                {"trainer_id": ident["trainer_id"]}
                if ident["trainer_id"] is not None
                else {}
            )

        def _flush_acks() -> None:
            """Push deferred acks NOW (drain barrier / failure / pass exit
            paths) — an acks-only get_tasks (n=0) leases nothing."""
            if not (pending_done or pending_failed):
                return
            client.call(
                "get_tasks", n=0, done_ids=list(pending_done),
                failed_ids=list(pending_failed), **_id_kw(),
            )
            pending_done.clear()
            pending_failed.clear()

        hb: Optional[_Heartbeater] = None
        try:
            if register:
                resp = client.call("register", role="reader")
                if "trainer_id" in resp:
                    ident["trainer_id"] = resp["trainer_id"]
                    ident["lease_s"] = float(resp.get("lease_s", 10.0))
                    hb = _Heartbeater(
                        master_address, ident, client_kw=client_kw
                    ).start()
                    # visible to a co-resident trainer's drain barrier, which
                    # acks on our behalf while it holds our consumer thread
                    # (see _service_reader_drains)
                    with _READER_IDENTS_LOCK:
                        _READER_IDENTS.append(ident)
            while True:
                # between-task boundary: no task leased to us right now, so
                # joining a resize drain barrier here keeps the master's
                # todo/pending/done books untouched. Flush deferred acks
                # FIRST when an epoch is announced — the lease must hold no
                # half-acked task across the barrier.
                if ident.get("resize") is not None:
                    _flush_acks()
                _maybe_drain(client, ident)
                resp = client.call(
                    "get_tasks", n=max(1, int(lease_batch)),
                    done_ids=list(pending_done),
                    failed_ids=list(pending_failed), **_id_kw(),
                )
                pending_done.clear()
                pending_failed.clear()
                if client.wire_framed:
                    # signal the heartbeat thread that data-plane traffic is
                    # carrying the lease (note_seen) + the resize piggyback
                    ident["last_rpc"] = client.last_rpc
                if resp.get("pass_finished"):
                    return
                tasks = resp.get("tasks") or []
                if not tasks:
                    time.sleep(poll_interval)
                    continue
                for t in tasks:
                    task_id, shards = t["task_id"], t["shards"]
                    try:
                        yield from recordio.read_shards(shards, deserialize)
                    except BaseException:
                        # the failure ack itself can fail (master died too) —
                        # it must never mask the original shard-read error;
                        # the lease timeout replays the task either way.
                        # Unconsumed tasks from this batch replay the same
                        # way (crash semantics).
                        pending_failed.append(task_id)
                        try:
                            _flush_acks()
                        except ConnectionError as ack_err:
                            stats.FT_EVENTS.incr("task_ack_failed")
                            log.warning(
                                "failure ack for task %d lost (%s); the task "
                                "replays after its lease times out",
                                task_id, ack_err,
                            )
                            # drop them: the finally-flush would only repeat
                            # the terminal retry loop against a dead master
                            pending_done.clear()
                            pending_failed.clear()
                        raise
                    # the done ack rides the NEXT get_tasks request — one
                    # round trip per lease_batch tasks, not one per ack
                    pending_done.append(task_id)
        finally:
            if hb is not None:
                hb.stop()
            with _READER_IDENTS_LOCK:
                _READER_IDENTS[:] = [
                    d for d in _READER_IDENTS if d is not ident
                ]
            try:
                _flush_acks()
            except ConnectionError as ack_err:
                stats.FT_EVENTS.incr("task_ack_failed")
                log.warning(
                    "final ack flush of %d task(s) failed (%s); they replay "
                    "after their leases time out — records from them will be "
                    "delivered again", len(pending_done), ack_err,
                )
            if ident["trainer_id"] is not None:
                try:
                    client.call("deregister", trainer_id=ident["trainer_id"])
                except ConnectionError:
                    pass  # lease will simply expire
            client.close()

    return reader


if __name__ == "__main__":
    import sys

    sys.exit(_main())
