"""Elastic task master — go/master parity (SURVEY §2.2, §5 failure recovery).

TaskMaster wraps the native dispatcher (csrc/master.cc): todo/pending/done
queues, lease timeouts with re-queue, failureMax discard, snapshot/restore.
MasterServer exposes it over TCP (newline-delimited JSON — the Go master's
net/rpc role) so multi-host trainers share one queue; MasterClient +
`cluster_reader` replace python/paddle/v2/master/client.py:15 (the ctypes→Go
reader shim): trainers are stateless task consumers pulling recordio shard
lists.

Cluster-level failure is a first-class code path here:

- **Failover**: MasterClient takes an endpoint *list* ("a:p,b:p") and rotates
  through it inside its existing reconnect/backoff loop; `standby_master`
  watches a primary and takes over from the shared snapshot the moment it
  dies (pending tasks snapshot as todo, so lost leases re-dispatch — the Go
  master's etcd-recovery discipline, service.go:166).
- **Membership**: trainers `register` for a lease and renew it via
  `heartbeat` (every RPC bearing a trainer_id renews implicitly — RPCs stay
  retry-exact, per "RPC Considered Harmful"). An expired trainer's pending
  tasks are re-queued *eagerly*, not left to the per-task timeout; live and
  evicted counts ride in `stats()`.
- **Chaos**: the seeded sites `master_drop` (RPC vanishes), `master_kill`
  (server dies mid-RPC, no final snapshot) and `conn_reset` (client socket
  resets) make every failover path deterministic and testable.
"""

from __future__ import annotations

import ctypes as C
import json
import logging
import os
import random
import socket
import socketserver
import threading
import time
import uuid
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple, Union

from paddle_tpu.core import faults, stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.runtime import native
from paddle_tpu.runtime import recordio

log = logging.getLogger("paddle_tpu.master")

Endpoint = Tuple[str, int]
EndpointsLike = Union[str, Endpoint, Sequence[Union[str, Endpoint]]]


def parse_endpoints(address: EndpointsLike) -> List[Endpoint]:
    """Normalize one endpoint or a failover list into [(host, port), ...].

    Accepts a (host, port) tuple, "host:port", the CLI's comma form
    "a:p1,b:p2", or any sequence mixing those."""
    if isinstance(address, str):
        parts = [p.strip() for p in address.split(",") if p.strip()]
    elif (
        isinstance(address, (tuple, list))
        and len(address) == 2
        and isinstance(address[0], str)
        and isinstance(address[1], int)
    ):
        parts = [address]
    else:
        parts = list(address)
    out: List[Endpoint] = []
    for p in parts:
        if isinstance(p, str):
            host, sep, port = p.rpartition(":")
            if not sep:
                raise ValueError(f"bad master endpoint {p!r}: want host:port")
            out.append((host, int(port)))
        else:
            host, port = p
            out.append((str(host), int(port)))
    if not out:
        raise ValueError(f"no master endpoints in {address!r}")
    return out


class TaskMaster:
    """In-process dispatcher. Payload per task = newline-joined shard paths."""

    PASS_FINISHED = -2

    def __init__(self, timeout_s: float = 60.0, failure_max: int = 3):
        L = native.lib()
        if L is None:
            raise RuntimeError("native runtime unavailable (g++ build failed?)")
        self._lib = L
        self._m = L.pt_master_create(timeout_s, failure_max)
        self._buf = C.create_string_buffer(1 << 20)

    def set_dataset(
        self, shard_paths: Sequence[str], chunks_per_task: int = 1
    ) -> None:
        """Group shards into tasks of `chunks_per_task` (go master
        NewService(chunksPerTask), service.go:140)."""
        payloads: List[str] = []
        group: List[str] = []
        for p in shard_paths:
            group.append(p)
            if len(group) >= chunks_per_task:
                payloads.append("\n".join(group))
                group = []
        if group:
            payloads.append("\n".join(group))
        blob = b"".join(p.encode() + b"\0" for p in payloads)
        self._lib.pt_master_set_dataset(self._m, blob, len(payloads))

    def get_task(self) -> Optional[tuple]:
        """→ (task_id, [shard paths]) | None (retry later) | raises StopIteration
        on pass end? No — returns ('pass_finished') sentinel via id==-2."""
        tid = self._lib.pt_master_get_task(self._m, self._buf, len(self._buf))
        while tid == -3:  # buffer too small: grow until the payload fits
            self._buf = C.create_string_buffer(len(self._buf) * 4)
            tid = self._lib.pt_master_get_task(self._m, self._buf, len(self._buf))
        if tid < 0:
            return None if tid == -1 else (self.PASS_FINISHED, [])
        return int(tid), self._buf.value.decode().split("\n")

    def task_finished(self, task_id: int) -> bool:
        return self._lib.pt_master_task_finished(self._m, task_id) == 0

    def task_failed(self, task_id: int) -> bool:
        return self._lib.pt_master_task_failed(self._m, task_id) == 0

    def pass_finished(self, start_next: bool = False) -> bool:
        return self._lib.pt_master_pass_finished(self._m, int(start_next)) == 1

    def stats(self) -> dict:
        out = (C.c_int64 * 5)()
        self._lib.pt_master_stats(self._m, out)
        return {
            "todo": out[0], "pending": out[1], "done": out[2],
            "discarded": out[3], "pass": out[4],
        }

    def snapshot(self, path: str) -> None:
        if self._m is None:  # killed under a debounced writer — not a segfault
            raise OSError("snapshot on a closed TaskMaster")
        if self._lib.pt_master_snapshot(self._m, path.encode()) != 0:
            raise OSError(f"snapshot to {path} failed")

    def restore(self, path: str) -> None:
        if self._lib.pt_master_restore(self._m, path.encode()) != 0:
            raise OSError(f"restore from {path} failed")

    @property
    def closed(self) -> bool:
        return self._m is None

    def close(self) -> None:
        if self._m:
            self._lib.pt_master_destroy(self._m)
            self._m = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Trainer membership: register/heartbeat leases + eager re-queue on eviction
# ---------------------------------------------------------------------------


class _Membership:
    """Soft-state trainer leases (go/master's etcd TTL keys, in-process).

    Any RPC bearing a trainer_id renews — or adopts — the lease, so a
    failover to a standby that never saw `register` heals itself on the next
    request instead of erroring (retry-exact RPCs). Pending-task ownership is
    tracked so an expired trainer's tasks can be re-queued eagerly."""

    def __init__(self, lease_s: float):
        self.lease_s = float(lease_s)
        self._lock = threading.Lock()
        self._last_seen: Dict[str, float] = {}
        self._owned: Dict[str, Set[int]] = {}
        self._owner: Dict[int, str] = {}
        self._next = 0
        # server-unique prefix: ids minted by a primary and its standby never
        # collide, so an adopted lease is unambiguous
        self._prefix = uuid.uuid4().hex[:6]
        self.evicted = 0

    def register(self) -> str:
        with self._lock:
            tid = f"tr-{self._prefix}-{self._next}"
            self._next += 1
            self._last_seen[tid] = time.monotonic()
            return tid

    def note_seen(self, tid: Optional[str]) -> None:
        if not tid:
            return
        with self._lock:
            self._last_seen[tid] = time.monotonic()

    def own(self, tid: Optional[str], task_id: int) -> None:
        if not tid:
            return
        with self._lock:
            self._owned.setdefault(tid, set()).add(task_id)
            self._owner[task_id] = tid

    def release(self, task_id: int) -> None:
        with self._lock:
            tid = self._owner.pop(task_id, None)
            if tid is not None:
                self._owned.get(tid, set()).discard(task_id)

    def drop(self, tid: str) -> Set[int]:
        """Forget a trainer (graceful deregister or eviction); returns the
        task ids it still held, for the caller to re-queue."""
        with self._lock:
            self._last_seen.pop(tid, None)
            tasks = self._owned.pop(tid, set())
            for t in tasks:
                self._owner.pop(t, None)
            return tasks

    def expired(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return [
                tid for tid, seen in self._last_seen.items()
                if now - seen > self.lease_s
            ]

    @property
    def live(self) -> int:
        with self._lock:
            return len(self._last_seen)


class _SnapshotPolicy:
    """Debounced, atomic snapshot writes OUTSIDE the RPC lock.

    The native snapshot takes the master's own mutex, so the only thing the
    RPC lock was buying during the write was a full stall of every other
    trainer behind one fsync. Writes go to a temp file + rename (never a torn
    snapshot for a standby to restore), rate-limited to at most once per
    `every` acks and once per `interval_s` seconds."""

    def __init__(self, path: str, every: int = 1, interval_s: float = 0.0):
        self.path = path
        self.every = max(1, int(every))
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._acks = 0
        self._last = 0.0  # monotonic; 0 = never written
        self.failures = 0

    def note_ack(self) -> bool:
        """Record one durable-progress event; True when a snapshot is due."""
        with self._lock:
            self._acks += 1
            return self._due_locked()

    def _due_locked(self) -> bool:
        if self._acks < self.every:
            return False
        if self.interval_s and time.monotonic() - self._last < self.interval_s:
            return False
        return True

    def pending(self) -> bool:
        """Acks recorded but not yet made durable (reaper/stop flush them).
        Before the FIRST write, sub-threshold acks stay debounced (stop()
        still flushes them) — `_last == 0` must not read as 'interval long
        since elapsed'."""
        with self._lock:
            if self._acks == 0:
                return False
            if not self.interval_s:
                return True
            if self._last == 0.0:
                return False
            return time.monotonic() - self._last >= self.interval_s

    def write(self, master: TaskMaster) -> None:
        with self._lock:
            self._acks = 0
            self._last = time.monotonic()
        with self._write_lock:
            tmp = f"{self.path}.{os.getpid()}.tmp"
            try:
                master.snapshot(tmp)
                os.replace(tmp, self.path)
            except OSError as e:
                # progress was acked to the trainer but NOT made durable — a
                # crash now replays those tasks; say so instead of silently
                # losing recovery fidelity
                self.failures += 1
                log.warning(
                    "master snapshot to %s failed (%s); a crash before the "
                    "next successful snapshot will re-dispatch acked tasks",
                    self.path, e,
                )
            finally:
                if os.path.exists(tmp):
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass


# ---------------------------------------------------------------------------
# TCP service (the Go master's RPC role), newline-delimited JSON
# ---------------------------------------------------------------------------


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        ms: MasterServer = self.server.ctx  # type: ignore[attr-defined]
        for line in self.rfile:
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                self._reply({"err": "bad json"})
                continue
            # span per RPC, adopting the caller's piggybacked trace context
            # (`_trace` on the line-JSON frame) so a task's or request's
            # spans stitch client → master under one trace id
            with obs_trace.server_span(
                "rpc." + str(req.get("method")), req.get("_trace"),
                side="server",
            ):
                keep = self._handle_one(ms, req)
            if not keep:
                return

    def _handle_one(self, ms: "MasterServer", req: dict) -> bool:
        """Process one request line; False severs the connection (chaos
        sites, master killed under us)."""
        master = ms.master
        lock = ms.master_lock
        method = req.get("method")
        if faults.get().fire("master_drop"):
            # chaos hook: the RPC vanishes in transit — drop the
            # connection without processing or replying; the client's
            # reconnect/backoff path has to absorb it
            return False
        if faults.get().fire("master_kill"):
            # chaos hook: the master process dies mid-RPC — no reply, no
            # final snapshot, every open connection severed; only a
            # standby restoring the last on-disk snapshot saves the pass
            log.warning("chaos: master_kill fired — dying without reply")
            ms.kill()
            return False
        trainer_id = req.get("trainer_id")
        ms.membership.note_seen(trainer_id)
        # (expired leases are swept by the reaper thread every lease_s/4 —
        # that bound IS the eager-requeue guarantee; scanning again per
        # RPC would only add membership-lock traffic to the hot path)
        # membership + observability RPCs never touch the native queue —
        # answered outside master_lock (drop_trainer takes it itself)
        if method == "register":
            self._reply({
                "trainer_id": ms.membership.register(),
                "lease_s": ms.membership.lease_s,
            })
            return True
        if method == "heartbeat":
            # note_seen above already renewed (or adopted) the lease; a
            # piggybacked metrics snapshot joins the fleet aggregate
            if trainer_id and "metrics" in req:
                ms.fleet.update(trainer_id, req["metrics"])
            self._reply({"ok": bool(trainer_id)})
            return True
        if method == "deregister":
            self._reply({"ok": ms.drop_trainer(trainer_id, evict=False)})
            return True
        if method == "metrics":
            fleet = ms.fleet.aggregate()
            self._reply({
                "text": obs_metrics.to_prometheus_text(fleet=fleet),
                "fleet": fleet,
            })
            return True
        if method == "trace_export":
            self._reply({"chrome_trace": obs_trace.export_chrome()})
            return True
        snapshot_due = False
        with lock:
            if master.closed:  # killed under us — sever like a crash
                return False
            if method == "get_task":
                got = master.get_task()
                if got is None:
                    resp = {"retry": True}
                elif got[0] == TaskMaster.PASS_FINISHED:
                    resp = {"pass_finished": True}
                else:
                    resp = {"task_id": got[0], "shards": got[1]}
                    ms.membership.own(trainer_id, got[0])
            elif method == "task_finished":
                tid = int(req["task_id"])
                ok = master.task_finished(tid)
                ms.membership.release(tid)
                resp = {"ok": ok}
                if ok and ms.snap is not None:
                    snapshot_due = ms.snap.note_ack()
            elif method == "task_failed":
                tid = int(req["task_id"])
                ok = master.task_failed(tid)
                ms.membership.release(tid)
                resp = {"ok": ok}
            elif method == "set_dataset":
                master.set_dataset(
                    req["shards"], int(req.get("chunks_per_task", 1))
                )
                resp = {"ok": True}
            elif method == "pass_finished":
                resp = {
                    "finished": master.pass_finished(
                        bool(req.get("start_next", False))
                    )
                }
            elif method == "stats":
                resp = master.stats()
                resp["snapshot_failures"] = ms.snapshot_failures
                resp["live_trainers"] = ms.membership.live
                resp["evicted_trainers"] = ms.membership.evicted
                # fleet-wide aggregate of the heartbeat metric snapshots:
                # one stats() answers for every reporting trainer
                resp["fleet"] = ms.fleet.aggregate()
            else:
                resp = {"err": f"unknown method {method!r}"}
        if snapshot_due:
            # the write happens OUTSIDE master_lock: other trainers keep
            # getting tasks while this thread does file I/O (the native
            # snapshot takes its own internal mutex for a consistent view)
            ms.snap.write(master)
        self._reply(resp)
        return True

    def _reply(self, obj: Any) -> None:
        try:
            self.wfile.write(json.dumps(obj).encode() + b"\n")
            self.wfile.flush()
        except (OSError, ValueError):
            pass  # peer vanished mid-reply; its retry path handles it


class MasterServer:
    """Threaded TCP wrapper; start()/stop(); port 0 picks a free port (the
    reference's in-process-localhost test idiom, test_CompareSparse.cpp:65).

    lease_s: trainer membership lease — a trainer silent for longer is
    evicted and its pending tasks are re-queued immediately.
    snapshot_every / snapshot_interval_s: debounce for the per-ack snapshot
    (at most once per N acks and once per T seconds; the reaper thread and
    stop() flush anything still pending)."""

    def __init__(
        self,
        master: Optional[TaskMaster] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        snapshot_path: Optional[str] = None,
        lease_s: float = 10.0,
        snapshot_every: int = 1,
        snapshot_interval_s: float = 0.0,
    ):
        self.master = master or TaskMaster()
        self.master_lock = threading.Lock()
        self.membership = _Membership(lease_s)
        # per-trainer heartbeat metric snapshots → fleet aggregate in stats();
        # entries expire a few leases after the last heartbeat
        self.fleet = obs_metrics.FleetMetrics(ttl_s=max(3.0 * lease_s, 30.0))
        self.snap = (
            _SnapshotPolicy(snapshot_path, snapshot_every, snapshot_interval_s)
            if snapshot_path
            else None
        )
        self.snapshot_path = snapshot_path
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.ctx = self  # type: ignore[attr-defined]
        if snapshot_path and os.path.exists(snapshot_path):
            self.master.restore(snapshot_path)  # crash recovery (service.go:166)
        self._thread: Optional[threading.Thread] = None
        self._reaper: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._stopped = False
        self._killed = False

    @property
    def address(self) -> tuple:
        return self._srv.server_address

    @property
    def snapshot_failures(self) -> int:
        return self.snap.failures if self.snap is not None else 0

    @property
    def alive(self) -> bool:
        return (
            self._thread is not None
            and self._thread.is_alive()
            and not self._stopped
            and not self._killed
        )

    def evict_expired(self) -> int:
        """Drop trainers whose lease lapsed; re-queue their pending tasks NOW
        (the per-task timeout would get there eventually — minutes later)."""
        n = 0
        for tid in self.membership.expired():
            if self.drop_trainer(tid, evict=True):
                n += 1
        return n

    def drop_trainer(self, tid: Optional[str], evict: bool) -> bool:
        if not tid:
            return False
        tasks = self.membership.drop(tid)
        self.fleet.drop(tid)
        requeued = 0
        with self.master_lock:
            if not self.master.closed:
                for t in tasks:
                    if self.master.task_failed(t):
                        requeued += 1
        if evict:
            self.membership.evicted += 1
            stats.FT_EVENTS.incr("trainer_evicted")
            log.warning(
                "trainer %s lease expired (%gs); evicted, %d pending task(s) "
                "re-queued eagerly", tid, self.membership.lease_s, requeued,
            )
        elif requeued:
            log.info(
                "trainer %s deregistered with %d task(s) in flight; re-queued",
                tid, requeued,
            )
        return True

    def _reap_loop(self) -> None:
        period = max(0.05, min(1.0, self.membership.lease_s / 4.0))
        while not self._stop_evt.wait(period):
            self.evict_expired()
            if self.snap is not None and self.snap.pending():
                # quiet-period flush: acks below the debounce threshold still
                # become durable without waiting for the next burst
                with self.master_lock:
                    closed = self.master.closed
                if not closed:
                    self.snap.write(self.master)

    def start(self) -> "MasterServer":
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()
        return self

    def stop(self) -> None:
        """Graceful shutdown: stop serving, flush a final snapshot, close the
        native handle. Idempotent (and safe after kill())."""
        if self._stopped:
            return
        self._stopped = True
        self._stop_evt.set()
        if self._thread is not None:  # shutdown() hangs if serve never ran
            self._srv.shutdown()
        self._srv.server_close()
        if self._reaper is not None:
            self._reaper.join(timeout=5.0)
        if not self._killed and self.snap is not None and not self.master.closed:
            self.snap.write(self.master)
        # the native TaskMaster handle used to leak here — close it (close()
        # is a no-op on an already-closed handle)
        self._close_master()

    def _close_master(self) -> None:
        """Destroy the native handle serialized against BOTH in-flight RPC
        dispatch (master_lock) and any debounced snapshot writer that runs
        outside it (_write_lock) — never a use-after-free under the lib."""
        if self.snap is not None:
            with self.snap._write_lock, self.master_lock:
                self.master.close()
        else:
            with self.master_lock:
                self.master.close()

    def kill(self) -> None:
        """Crash semantics (chaos master_kill): stop serving abruptly — NO
        final snapshot, so recovery exercises the last debounced on-disk
        state, exactly like a real master death."""
        if self._killed or self._stopped:
            return
        self._killed = True
        self._stop_evt.set()

        def _die():
            try:
                if self._thread is not None:
                    self._srv.shutdown()
                self._srv.server_close()
            except OSError:
                pass
            self._close_master()

        # shutdown() must not run on a handler thread holding the serve loop's
        # attention — a dedicated thread severs everything without deadlock
        threading.Thread(target=_die, daemon=True).start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


def standby_master(
    primary: EndpointsLike,
    host: str = "127.0.0.1",
    port: int = 0,
    snapshot_path: Optional[str] = None,
    poll_s: float = 0.2,
    confirm_failures: int = 2,
    max_wait_s: Optional[float] = None,
    stop_evt: Optional[threading.Event] = None,
    **server_kw,
) -> Optional[MasterServer]:
    """Warm-standby loop: watch `primary`; when it stays unreachable for
    `confirm_failures` consecutive probes, restore the shared snapshot and
    start serving on (host, port). Blocks until takeover (returns the started
    server), `max_wait_s` elapses, or `stop_evt` is set (returns None).

    The standby does NOT bind its port before takeover — a client failing
    over early gets connection-refused and keeps rotating. Death evidence is
    weighed: a refused/unreachable probe counts fully, a TIMED-OUT probe
    (slow ≠ dead) only half, and a final patient probe must still fail
    before binding — a briefly-overloaded primary is not usurped. Without a
    consensus backend this is still a heuristic: a primary alive on the far
    side of a real network partition can double-serve; production
    deployments should fence via the shared snapshot storage."""
    (phost, pport) = parse_endpoints(primary)[0]
    misses = 0.0
    deadline = time.monotonic() + max_wait_s if max_wait_s is not None else None
    while True:
        if stop_evt is not None and stop_evt.is_set():
            return None
        if deadline is not None and time.monotonic() > deadline:
            return None
        try:
            socket.create_connection((phost, pport), timeout=1.0).close()
            misses = 0.0
        except TimeoutError:
            misses += 0.5  # slow ≠ dead: timeouts need twice the evidence
        except OSError:
            misses += 1.0
        if misses >= confirm_failures:
            try:  # final confirmation, patient timeout: live beats standby
                socket.create_connection((phost, pport), timeout=3.0).close()
                misses = 0.0
            except OSError:
                break
        time.sleep(poll_s)
    log.warning(
        "standby: primary %s:%d unreachable %d times — taking over on "
        "%s:%d from snapshot %s", phost, pport, misses, host, port,
        snapshot_path,
    )
    stats.FT_EVENTS.incr("master_takeover")
    return MasterServer(
        host=host, port=port, snapshot_path=snapshot_path, **server_kw
    ).start()


# exit code of a served master that died to the master_kill chaos site —
# distinct from 0 (clean stop) so a supervisor/test can tell crash from stop
KILLED_EXIT = 17


def _main(argv: Optional[List[str]] = None) -> int:
    """`python -m paddle_tpu.runtime.master serve|standby ...` — a master (or
    warm standby) as its own OS process, for the multi-process chaos
    scenarios in benchmarks/chaos_bench.py and tests/test_cluster.py."""
    import argparse
    import signal as _signal

    ap = argparse.ArgumentParser(prog="paddle_tpu.runtime.master")
    sub = ap.add_subparsers(dest="role", required=True)
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--host", default="127.0.0.1")
    common.add_argument("--port", type=int, required=True)
    common.add_argument("--snapshot", default=None)
    common.add_argument("--lease_s", type=float, default=10.0)
    common.add_argument("--snapshot_every", type=int, default=1)
    common.add_argument("--snapshot_interval_s", type=float, default=0.0)
    common.add_argument("--timeout_s", type=float, default=60.0)
    common.add_argument("--failure_max", type=int, default=3)
    common.add_argument("--faults", default=None)
    common.add_argument("--faults_seed", type=int, default=0)
    common.add_argument(
        "--trace", type=int, default=0,
        help="1 = record RPC spans into the ring buffer (also settable via "
             "PADDLE_TPU_TRACE); fetch them with the trace_export RPC or "
             "`python -m paddle_tpu.obs trace --endpoint host:port`",
    )
    sub.add_parser("serve", parents=[common])
    st = sub.add_parser("standby", parents=[common])
    st.add_argument("--primary", required=True, help="host:port to watch")
    st.add_argument("--poll_s", type=float, default=0.2)
    st.add_argument("--max_wait_s", type=float, default=None)
    args = ap.parse_args(argv)

    if args.faults:
        faults.get().configure(args.faults, args.faults_seed)
    if args.trace:
        obs_trace.enable_tracing(True)

    def build() -> MasterServer:
        return MasterServer(
            TaskMaster(timeout_s=args.timeout_s, failure_max=args.failure_max),
            host=args.host,
            port=args.port,
            snapshot_path=args.snapshot,
            lease_s=args.lease_s,
            snapshot_every=args.snapshot_every,
            snapshot_interval_s=args.snapshot_interval_s,
        ).start()

    if args.role == "serve":
        server = build()
    else:
        got = standby_master(
            args.primary,
            host=args.host,
            port=args.port,
            snapshot_path=args.snapshot,
            poll_s=args.poll_s,
            max_wait_s=args.max_wait_s,
            master=TaskMaster(
                timeout_s=args.timeout_s, failure_max=args.failure_max
            ),
            lease_s=args.lease_s,
            snapshot_every=args.snapshot_every,
            snapshot_interval_s=args.snapshot_interval_s,
        )
        if got is None:
            print(json.dumps({"role": args.role, "takeover": False}), flush=True)
            return 3
        server = got

    _signal.signal(_signal.SIGTERM, lambda *_: server.stop())
    _signal.signal(_signal.SIGINT, lambda *_: server.stop())
    print(
        json.dumps({"role": args.role, "address": list(server.address)}),
        flush=True,
    )
    while server.alive:
        time.sleep(0.05)
    # distinguish the chaos master_kill crash from a clean SIGTERM stop
    return KILLED_EXIT if server._killed else 0


class MasterClient:
    """Blocking line-JSON client with reconnect + endpoint failover
    (go/master/client.go parity).

    `address` may be one endpoint or a failover list ((h, p), "h:p",
    "a:p1,b:p2", or a sequence of those — the CLI's --master_endpoints form).
    Failed calls reconnect and retry with bounded exponential backoff plus
    jitter (the Go client's backoff discipline; jitter keeps a restarted
    master from being stampeded by every trainer retrying in lockstep),
    rotating to the next endpoint on every reconnect so a dead primary's
    standby is found inside the same loop. After `retries` attempts
    (default: enough for several full rotations) the terminal ConnectionError
    names the method, the endpoints, the attempt count and the last
    underlying error."""

    def __init__(
        self,
        address: EndpointsLike,
        timeout: float = 30.0,
        retries: Optional[int] = None,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
    ):
        self.endpoints = parse_endpoints(address)
        self.timeout = timeout
        self.retries = (
            max(1, int(retries))
            if retries is not None
            else max(5, 4 * len(self.endpoints))
        )
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._i = 0
        self._sock: Optional[socket.socket] = None
        self._rfile = None

    @property
    def address(self) -> Endpoint:
        """The endpoint currently in use (compat with the single-address API)."""
        return self.endpoints[self._i]

    def _connect(self):
        if self._sock is None:
            self._sock = socket.create_connection(self.address, timeout=self.timeout)
            self._rfile = self._sock.makefile("rb")

    def _rotate(self) -> None:
        if len(self.endpoints) > 1:
            self._i = (self._i + 1) % len(self.endpoints)
            stats.FT_EVENTS.incr("master_failover")
            log.warning("master failover: trying endpoint %s:%d", *self.address)

    def call(self, method: str, **kw) -> dict:
        """One RPC (with reconnect/failover/backoff). With tracing enabled
        the call runs inside a client span and piggybacks its context on the
        frame (`_trace`), so the server's handler span joins this trace."""
        if obs_trace.TRACER.enabled:
            with obs_trace.span("rpc." + method, side="client") as sp:
                kw["_trace"] = {"t": sp.trace_id, "s": sp.span_id}
                return self._call(method, kw)
        return self._call(method, kw)

    def _call(self, method: str, kw: dict) -> dict:
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            try:
                self._connect()
                if faults.get().fire("conn_reset"):
                    # chaos hook: network partition/RST between trainer and
                    # master — the reconnect/failover path must absorb it
                    raise ConnectionResetError("injected conn_reset (chaos)")
                msg = json.dumps({"method": method, **kw}).encode() + b"\n"
                self._sock.sendall(msg)
                line = self._rfile.readline()
                if not line:
                    raise ConnectionError("master closed connection")
                return json.loads(line)
            except (OSError, ConnectionError, json.JSONDecodeError) as e:
                last_err = e
                self.close()
                stats.FT_EVENTS.incr("master_reconnect")
                self._rotate()
                if attempt + 1 < self.retries:
                    delay = min(self.backoff_max, self.backoff_base * 2 ** attempt)
                    delay *= 0.5 + random.random() / 2  # full-jitter in [.5d, d)
                    log.warning(
                        "master RPC %r failed (%s: %s); reconnecting in %.0fms "
                        "(attempt %d/%d)", method, type(e).__name__, e,
                        delay * 1e3, attempt + 1, self.retries,
                    )
                    time.sleep(delay)
        raise ConnectionError(
            f"master RPC {method!r} to {self.endpoints} failed after "
            f"{self.retries} attempts; giving up (last error: "
            f"{type(last_err).__name__}: {last_err})"
        ) from last_err

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._rfile = None


class _Heartbeater:
    """Background lease renewal on its OWN connection (the reader's socket is
    busy inside blocking calls; sharing it would interleave frames)."""

    def __init__(
        self,
        address: EndpointsLike,
        ident: Dict[str, Any],
        client_kw: Optional[dict] = None,
    ):
        self._ident = ident
        self._client = MasterClient(address, **(client_kw or {}))
        self._evt = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="master-heartbeat", daemon=True
        )

    def start(self) -> "_Heartbeater":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            period = max(0.05, float(self._ident.get("lease_s", 10.0)) / 3.0)
            if self._evt.wait(period):
                return
            tid = self._ident.get("trainer_id")
            if tid is None:
                continue
            try:
                # metrics snapshot piggybacks on the lease renewal — the
                # master aggregates these into its fleet-wide stats() view
                self._client.call(
                    "heartbeat", trainer_id=tid,
                    metrics=obs_metrics.snapshot(),
                )
            except ConnectionError:
                # terminal after retries+failover — the lease will lapse and
                # the master re-queues our tasks; the reader's own calls will
                # surface the outage, nothing more to do here
                stats.FT_EVENTS.incr("heartbeat_lost")

    def stop(self) -> None:
        self._evt.set()
        self._thread.join(timeout=5.0)
        self._client.close()


def cluster_reader(
    master_address: EndpointsLike,
    deserialize: Callable[[bytes], Any] = None,
    poll_interval: float = 0.5,
    register: bool = True,
    client_kw: Optional[dict] = None,
) -> Callable[[], Iterator[Any]]:
    """v2 cluster reader (master/client.py:15): pull tasks from the master,
    stream their recordio shards, ack on completion, report failures. One
    call of the returned reader = one pass.

    `master_address` may be a failover list (see MasterClient). With
    `register=True` the reader takes out a membership lease and renews it
    from a background heartbeat thread, so a trainer that dies mid-task is
    evicted and its tasks re-queued eagerly rather than after the per-task
    timeout; the lease is released (`deregister`) on a clean pass end."""
    import pickle

    deserialize = deserialize or pickle.loads

    def reader() -> Iterator[Any]:
        client = MasterClient(master_address, **(client_kw or {}))
        ident: Dict[str, Any] = {"trainer_id": None, "lease_s": 10.0}
        hb: Optional[_Heartbeater] = None
        try:
            if register:
                resp = client.call("register")
                if "trainer_id" in resp:
                    ident["trainer_id"] = resp["trainer_id"]
                    ident["lease_s"] = float(resp.get("lease_s", 10.0))
                    hb = _Heartbeater(
                        master_address, ident, client_kw=client_kw
                    ).start()
            id_kw = (
                {"trainer_id": ident["trainer_id"]}
                if ident["trainer_id"] is not None
                else {}
            )
            while True:
                resp = client.call("get_task", **id_kw)
                if resp.get("pass_finished"):
                    return
                if resp.get("retry"):
                    time.sleep(poll_interval)
                    continue
                task_id, shards = resp["task_id"], resp["shards"]
                try:
                    yield from recordio.read_shards(shards, deserialize)
                except BaseException:
                    # the failure ack itself can fail (master died too) — it
                    # must never mask the original shard-read error; the lease
                    # timeout replays the task either way
                    try:
                        client.call("task_failed", task_id=task_id, **id_kw)
                    except ConnectionError as ack_err:
                        stats.FT_EVENTS.incr("task_ack_failed")
                        log.warning(
                            "task_failed ack for task %d lost (%s); the task "
                            "replays after its lease times out", task_id, ack_err,
                        )
                    raise
                try:
                    client.call("task_finished", task_id=task_id, **id_kw)
                except ConnectionError as ack_err:
                    # terminal (retries + failover exhausted): progress was
                    # made but not recorded — the task WILL be re-dispatched
                    # after its lease expires, so downstream consumers see its
                    # records twice; count it and say so
                    stats.FT_EVENTS.incr("task_ack_failed")
                    log.warning(
                        "task_finished ack for task %d failed terminally (%s); "
                        "the task will replay after its lease times out — "
                        "records from it will be delivered again", task_id, ack_err,
                    )
        finally:
            if hb is not None:
                hb.stop()
            if ident["trainer_id"] is not None:
                try:
                    client.call("deregister", trainer_id=ident["trainer_id"])
                except ConnectionError:
                    pass  # lease will simply expire
            client.close()

    return reader


if __name__ == "__main__":
    import sys

    sys.exit(_main())
