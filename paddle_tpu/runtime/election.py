"""Warm-standby election: the ONE implementation every control plane uses.

PR 6's `standby_master` proved the shape — a standby process that binds its
port only at takeover, watching the primary with weighted death evidence —
and ISSUE 18 extracts it here so the router and the autoscaler (the two
remaining singleton control planes) stand on the same primitive instead of
growing three divergent copies of the probe loop:

  * watch — a raw TCP connect probe against the primary's endpoint every
    `poll_s`; no RPC protocol is assumed, so anything that LISTENS (a
    MasterServer, a RouterServer, the autoscaler's liveness socket) is
    watchable;
  * weighted strikes — a refused/unreachable probe counts 1.0, a TIMED-OUT
    probe only 0.5 (slow ≠ dead: an overloaded primary must not be usurped
    on latency alone);
  * patient confirmation — once the strike budget (`confirm_failures`) is
    spent, one final probe with a 3× patient timeout must STILL fail before
    the standby declares takeover;
  * bind-at-takeover — the watcher never binds anything; the caller starts
    its server/controller only after `wait_for_takeover` returns, so an
    early-failing client gets connection-refused and keeps rotating its
    endpoint list instead of talking to a cold standby;
  * instance token — every takeover mints a fresh per-incarnation token
    (the `_ResizeEpoch.instance` idiom): downstream fencing compares it so
    a healed old primary's stale replies are recognizably from a dead
    incarnation, never adopted;
  * observability — each takeover bumps `FT_EVENTS["<plane>_takeover"]`
    and `paddle_tpu_takeovers_total{plane=...}`.

Without a consensus backend this stays a heuristic: a primary alive on the
far side of a true network partition can double-serve for a window. Every
consumer therefore pairs election with data-plane fencing — the master via
shared snapshot storage, the router via instance-token heartbeat fencing +
the (tenant, client_req_id) dedup latch, the autoscaler via the resize
epoch's (instance, epoch) identity."""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Optional

from paddle_tpu.core import stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.runtime.master import EndpointsLike, parse_endpoints

log = logging.getLogger("paddle_tpu.runtime.election")


def mint_instance_token() -> str:
    """A fresh per-incarnation identity (8 hex chars — the resize-epoch
    idiom): two incarnations of the same control plane never share one, so
    replies can be fenced by WHICH incarnation produced them."""
    return uuid.uuid4().hex[:8]


class StandbyWatcher:
    """The election loop, as an object so drills can stop() it and the
    hot-loop lint can budget its clock/RPC sites by name.

    `wait_for_takeover()` blocks until the primary is confirmed dead
    (returns the freshly minted instance token), `max_wait_s` elapses, or
    `stop()` / the shared `stop_evt` fires (returns None)."""

    def __init__(
        self,
        primary: EndpointsLike,
        plane: str,
        poll_s: float = 0.2,
        confirm_failures: float = 2,
        probe_timeout_s: float = 1.0,
        confirm_timeout_s: float = 3.0,
        max_wait_s: Optional[float] = None,
        stop_evt: Optional[threading.Event] = None,
    ):
        self.primary = parse_endpoints(primary)[0]
        self.plane = str(plane)
        self.poll_s = float(poll_s)
        self.confirm_failures = float(confirm_failures)
        self.probe_timeout_s = float(probe_timeout_s)
        self.confirm_timeout_s = float(confirm_timeout_s)
        self.max_wait_s = max_wait_s
        self._stop_evt = stop_evt if stop_evt is not None else threading.Event()
        self.misses = 0.0
        self.probes = 0

    def stop(self) -> None:
        self._stop_evt.set()

    def _probe_once(self, timeout_s: float) -> float:
        """One connect probe; returns the miss WEIGHT it earned (0.0 alive,
        0.5 timed out — slow ≠ dead, timeouts need twice the evidence —
        1.0 refused/unreachable)."""
        self.probes += 1
        try:
            socket.create_connection(
                self.primary, timeout=timeout_s
            ).close()
            return 0.0
        except TimeoutError:
            return 0.5
        except OSError:
            return 1.0

    def wait_for_takeover(self) -> Optional[str]:
        (phost, pport) = self.primary
        # clock-ok: one deadline stamp per watch, checked once per probe
        # cycle (poll_s-paced — this loop IS the cold path)
        deadline = (
            time.monotonic() + self.max_wait_s
            if self.max_wait_s is not None else None
        )
        while True:
            if self._stop_evt.is_set():
                return None
            # clock-ok: one expiry check per poll_s-paced probe cycle
            if deadline is not None and time.monotonic() > deadline:
                return None
            w = self._probe_once(self.probe_timeout_s)
            self.misses = 0.0 if w == 0.0 else self.misses + w
            if self.misses >= self.confirm_failures:
                # final confirmation, patient timeout: live beats standby
                if self._probe_once(self.confirm_timeout_s) == 0.0:
                    self.misses = 0.0
                else:
                    break
            time.sleep(self.poll_s)
        token = mint_instance_token()
        log.warning(
            "%s standby: primary %s:%d unreachable (%.1f strikes) — taking "
            "over as incarnation %s", self.plane, phost, pport, self.misses,
            token,
        )
        # the <plane>_takeover FT key keeps PR 6's "master_takeover" name
        # alive for plane="master"; the labeled Prometheus counter is the
        # cross-plane view the HA drill gates on
        stats.FT_EVENTS.incr(f"{self.plane}_takeover")
        obs_metrics.observe_takeover(self.plane)
        return token


def watch_primary(
    primary: EndpointsLike,
    plane: str,
    poll_s: float = 0.2,
    confirm_failures: float = 2,
    max_wait_s: Optional[float] = None,
    stop_evt: Optional[threading.Event] = None,
) -> Optional[str]:
    """Block until `primary` is confirmed dead; returns the new incarnation's
    instance token (takeover counters already bumped), or None on stop /
    `max_wait_s` expiry. The functional face of `StandbyWatcher` every
    standby role (`standby_master`, `RouterStandby`, `AutoscalerStandby`)
    consumes."""
    return StandbyWatcher(
        primary, plane, poll_s=poll_s, confirm_failures=confirm_failures,
        max_wait_s=max_wait_s, stop_evt=stop_evt,
    ).wait_for_takeover()
