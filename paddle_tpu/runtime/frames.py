"""Binary control-plane framing (ISSUE 20): the length-prefixed frame layer
under the line-JSON RPC surface.

Every control-plane message used to be one `json.dumps(obj) + b"\\n"` line —
one encode, one round trip, one blocking read per message ("RPC Considered
Harmful", PAPERS.md). This module is the wire layer that replaces it for
peers that negotiate it, WITHOUT changing the method surface: the same dicts
go in and come out, so handlers and clients are wire-agnostic above the
seam.

Frame layout (little-endian, 16-byte fixed header)::

    u8  magic      0xF7 — rejects a line-JSON peer that skipped negotiation
    u8  version    1
    u8  flags      FLAG_* below
    u8  method_id  compact id for well-known methods (0 = name in JSON)
    u32 req_id     request id: pipelining match key; stream frames reuse it
                   as the serving request id
    u32 json_len   length of the JSON control payload (0 allowed)
    u32 bin_len    length of the raw binary payload (0 allowed)
    [24-byte trace block when FLAG_TRACE]
    [json_len bytes JSON]
    [bin_len bytes raw]

Control fields stay JSON (schema-free, debuggable); BULK bodies ride the
raw binary payload: token runs as packed int32 (FLAG_BIN_TOKENS), opaque
blobs like master snapshots (FLAG_BIN_BLOB), and the compact stream-delta
form (FLAG_STREAM with json_len == 0: req_id is the serving request id and
the binary payload is `<u32 from><int32 tokens...>` — a pushed token costs
4 bytes plus its share of a 20-byte frame, not a JSON object). A stream's
common ending (finish_reason "length", not cancelled) stays compact too:
FLAG_EOS on the delta replaces the whole JSON `done` tail.

Trace context moves INTO the header: the `_trace` dict that used to ride
every JSON object becomes a fixed 24-byte block (8-byte raw trace id +
16-byte NUL-padded span id) gated by FLAG_TRACE, so tracing-enabled runs
stop re-encoding two hex strings per RPC; an id that does not fit the fixed
block falls back to the JSON field, transparently.

Negotiation is deliberately NOT framed: a client opens with the line-JSON
`{"method": "_hello", "frames": 1}` probe; a frame-capable server answers
`{"frames": 1}` and switches THAT connection to the framed loop, a legacy
server answers unknown-method and the client stays on line JSON (memoized
per endpoint). A legacy client never sends the probe, so it is served
bit-for-bit by the unchanged line path. `PADDLE_TPU_WIRE` picks the client
policy: `auto` (default — probe, fall back), `json` (never probe),
`frames` (downgrade is an error).

The decoder REJECTS garbage with named errors instead of wedging a handler
thread: `BadMagic`, `BadVersion`, `FrameTooLarge` (length caps below —
a hostile/corrupt length field must not allocate gigabytes), and
`TruncatedFrame` (EOF mid-frame). All subclass `FrameError`, itself a
`ConnectionError`, so every existing reconnect/failover path absorbs them.

`write_frame` is THE control-frame encode site (the hot-loop lint pins it:
clients and handlers call here instead of sprinkling `json.dumps` over the
pump/heartbeat paths); `encode_stream` is the stream-frame twin behind
serving's `encode_frame` seam."""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "FLAG_BIN_BLOB",
    "FLAG_BIN_TOKENS",
    "FLAG_EOS",
    "FLAG_PIGGY",
    "FLAG_STREAM",
    "FLAG_TRACE",
    "BadMagic",
    "BadVersion",
    "FrameError",
    "FrameTooLarge",
    "TruncatedFrame",
    "decode_payload",
    "encode_stream",
    "pack_tokens",
    "read_frame",
    "unpack_tokens",
    "write_frame",
]

MAGIC = 0xF7
VERSION = 1

# <BBBBIII: magic, version, flags, method_id, req_id, json_len, bin_len
_HEADER = struct.Struct("<BBBBIII")
HEADER_SIZE = _HEADER.size  # 16

FLAG_BIN_TOKENS = 0x01  # bin payload backs the JSON's "_ntok" markers
FLAG_TRACE = 0x02       # 24-byte trace block follows the header
FLAG_PIGGY = 0x04       # reply carries a piggybacked control signal (_rz)
FLAG_STREAM = 0x08      # push-stream frame; json_len == 0 => compact delta
FLAG_BIN_BLOB = 0x10    # bin payload is an opaque blob (resp["_bin"])
FLAG_EOS = 0x20         # compact stream delta is FINAL: done, length-capped

# length caps: a corrupt/hostile length field must fail NAMED, not allocate
MAX_JSON = 16 << 20   # 16 MiB of control fields is already a bug
MAX_BIN = 256 << 20   # snapshots/param blobs; far above anything real

_TRACE_ID_BYTES = 8    # trace ids are os.urandom(8).hex() — 8 raw bytes
_SPAN_ID_BYTES = 16    # "<pid hex>.<n>", NUL-padded
TRACE_BLOCK_SIZE = _TRACE_ID_BYTES + _SPAN_ID_BYTES

# well-known methods get a 1-byte id and drop the JSON "method" field;
# id 0 means the method name (if any) stays in the JSON payload
METHOD_IDS: Dict[str, int] = {
    "get_task": 1, "task_finished": 2, "task_failed": 3, "get_tasks": 4,
    "heartbeat": 5, "register": 6, "deregister": 7, "set_dataset": 8,
    "pass_finished": 9, "stats": 10, "resize": 11, "resize_drained": 12,
    "resize_status": 13, "metrics": 14, "trace_export": 15,
    "snapshot_fetch": 16, "submit": 17, "generate": 18, "poll": 19,
    "poll_many": 20, "cancel": 21, "stream": 22, "replica_register": 23,
    "replica_heartbeat": 24, "replica_deregister": 25, "outstanding": 26,
    "generate_config": 27, "drain": 28, "replicas": 29,
}
METHOD_NAMES = {v: k for k, v in METHOD_IDS.items()}


class FrameError(ConnectionError):
    """Any framed-wire protocol violation. A ConnectionError on purpose:
    every client retry/failover path and every handler's sever-on-error
    path already knows what to do with one."""


class BadMagic(FrameError):
    """First byte was not MAGIC — a line-JSON peer (or garbage) on a framed
    connection."""


class BadVersion(FrameError):
    """Frame version this build does not speak."""


class FrameTooLarge(FrameError):
    """Declared payload length exceeds the caps (corrupt length field)."""


class TruncatedFrame(FrameError):
    """EOF mid-frame: the peer died between header and payload."""


# -- trace block --------------------------------------------------------------


def _encode_trace(ctx: Any) -> Optional[bytes]:
    """`_trace` dict -> fixed 24-byte block, or None when it does not fit
    (caller leaves the JSON field in place — the fallback path)."""
    if not isinstance(ctx, dict):
        return None
    t, s = ctx.get("t"), str(ctx.get("s") or "")
    if not isinstance(t, str) or len(t) != 2 * _TRACE_ID_BYTES:
        return None
    if len(s) > _SPAN_ID_BYTES:
        return None
    try:
        raw = bytes.fromhex(t)
        span = s.encode("ascii")
    except (ValueError, UnicodeEncodeError):
        return None
    return raw + span.ljust(_SPAN_ID_BYTES, b"\0")


def _decode_trace(block: bytes) -> Dict[str, str]:
    return {
        "t": block[:_TRACE_ID_BYTES].hex(),
        "s": block[_TRACE_ID_BYTES:].rstrip(b"\0").decode("ascii", "replace"),
    }


# -- token packing ------------------------------------------------------------


def _int32s(toks: Any) -> bool:
    """True when every element is a plain int that fits int32 — anything
    else (numpy scalars, bools, out-of-range ids) stays JSON rather than
    raising struct.error mid-reply."""
    return (
        isinstance(toks, list) and bool(toks)
        and all(
            type(t) is int and -0x80000000 <= t <= 0x7FFFFFFF for t in toks
        )
    )


def _pack_one(d: dict, segs: list) -> dict:
    toks = d.get("tokens")
    if _int32s(toks):
        d = dict(d)
        d["_ntok"] = len(toks)
        del d["tokens"]
        segs.append(struct.pack(f"<{len(toks)}i", *toks))
    return d


def pack_tokens(obj: dict) -> Tuple[dict, bytes]:
    """Strip token runs out of a reply into one packed-int32 binary payload.

    Walks the top-level "tokens" list and each item of a top-level
    "results" list (the poll / poll_many / stream shapes), replacing each
    with an "_ntok" count; `unpack_tokens` reverses in the same order.
    Returns (new obj, bin payload) — (obj, b"") when nothing packed."""
    segs: list = []
    out = _pack_one(obj, segs)
    res = out.get("results")
    if isinstance(res, list):
        packed = [
            _pack_one(it, segs) if isinstance(it, dict) else it for it in res
        ]
        if segs:
            out = dict(out) if out is obj else out
            out["results"] = packed
    return out, b"".join(segs)


def _unpack_one(d: dict, blob: bytes, off: int) -> Tuple[dict, int]:
    n = d.get("_ntok")
    if not isinstance(n, int):
        return d, off
    end = off + 4 * n
    if end > len(blob):
        raise TruncatedFrame(
            f"token payload short: need {end} bytes, have {len(blob)}"
        )
    d = dict(d)
    del d["_ntok"]
    d["tokens"] = list(struct.unpack_from(f"<{n}i", blob, off))
    return d, end


def unpack_tokens(obj: dict, blob: bytes) -> dict:
    """Reverse pack_tokens: fold the binary token runs back into the dict
    (same walk order: top-level first, then results items)."""
    out, off = _unpack_one(obj, blob, 0)
    res = out.get("results")
    if isinstance(res, list):
        items = []
        for it in res:
            if isinstance(it, dict):
                it, off = _unpack_one(it, blob, off)
            items.append(it)
        out = dict(out) if out is obj else out
        out["results"] = items
    return out


# -- encode / decode ----------------------------------------------------------


def write_frame(
    wfile,
    obj: dict,
    req_id: int = 0,
    flags: int = 0,
    bin_payload: bytes = b"",
) -> int:
    """THE control-frame encode site (hot-loop lint pins call sites): pack
    one dict (+ optional binary payload) as a frame onto `wfile` and flush.
    Returns bytes written. Well-known methods and a fitting `_trace` move
    out of the JSON into the header/trace block."""
    method_id = 0
    trace_block = b""
    if "method" in obj or "_trace" in obj:
        obj = dict(obj)
        mid = METHOD_IDS.get(obj.get("method"))
        if mid:
            method_id = mid
            del obj["method"]
        tb = _encode_trace(obj.get("_trace"))
        if tb is not None:
            trace_block = tb
            flags |= FLAG_TRACE
            del obj["_trace"]
    payload = json.dumps(obj, separators=(",", ":")).encode()
    if len(payload) > MAX_JSON:
        raise FrameTooLarge(f"json payload {len(payload)}B exceeds cap")
    if len(bin_payload) > MAX_BIN:
        raise FrameTooLarge(f"binary payload {len(bin_payload)}B exceeds cap")
    buf = (
        _HEADER.pack(
            MAGIC, VERSION, flags & 0xFF, method_id,
            req_id & 0xFFFFFFFF, len(payload), len(bin_payload),
        )
        + trace_block + payload + bin_payload
    )
    wfile.write(buf)
    wfile.flush()
    return len(buf)


def encode_stream(obj: dict) -> bytes:
    """Stream-frame encode seam (serving's `encode_frame(framed=True)` body):
    a pure token delta becomes the compact header-only form (req_id = the
    serving request id, bin = `<u32 from><int32 tokens...>`, NO JSON); a
    final/irregular frame keeps its JSON with tokens packed binary."""
    rid = obj.get("request_id")
    toks = obj.get("tokens")
    frm = obj.get("from", 0)
    if (
        isinstance(rid, int) and 0 <= rid <= 0xFFFFFFFF
        and isinstance(frm, int) and 0 <= frm <= 0xFFFFFFFF
        and _int32s(toks)
        and obj.get("tokens_so_far") == frm + len(toks)
    ):
        compact = None
        if not obj.get("done") and len(obj) <= 4:
            # request_id, from, tokens, tokens_so_far only
            compact = FLAG_STREAM | FLAG_BIN_TOKENS
        elif (
            obj.get("done") is True
            and obj.get("finish_reason") == "length"
            and obj.get("cancelled") is False
            and len(obj) == 7  # base four + done/finish_reason/cancelled
        ):
            # the overwhelmingly common ending (max_new reached, not
            # cancelled) needs no JSON either: FLAG_EOS stands in for the
            # whole `_stream_final` dict and the decoder reconstitutes it
            compact = FLAG_STREAM | FLAG_BIN_TOKENS | FLAG_EOS
        if compact is not None:
            blob = struct.pack(f"<I{len(toks)}i", frm, *toks)
            return _HEADER.pack(
                MAGIC, VERSION, compact, 0,
                rid, 0, len(blob),
            ) + blob
    packed, blob = pack_tokens(obj)
    payload = json.dumps(packed, separators=(",", ":")).encode()
    flags = FLAG_STREAM | (FLAG_BIN_TOKENS if blob else 0)
    return _HEADER.pack(
        MAGIC, VERSION, flags, 0,
        (rid or 0) & 0xFFFFFFFF, len(payload), len(blob),
    ) + payload + blob


def _read_exact(rfile, n: int, what: str) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = rfile.read(n - got)
        if not chunk:
            raise TruncatedFrame(f"EOF after {got}/{n} bytes of {what}")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(rfile) -> Optional[Tuple[dict, int, int, bytes]]:
    """Read one frame -> (obj, req_id, flags, bin_payload); None on clean
    EOF (no bytes at a frame boundary). Raises the named FrameError
    subclasses on anything malformed — a garbage or truncated frame must
    sever the connection, never wedge the reader."""
    first = rfile.read(1)
    if not first:
        return None
    head = first + _read_exact(rfile, HEADER_SIZE - 1, "frame header")
    magic, version, flags, method_id, req_id, json_len, bin_len = (
        _HEADER.unpack(head)
    )
    if magic != MAGIC:
        raise BadMagic(f"bad frame magic 0x{magic:02x} (want 0x{MAGIC:02x})")
    if version != VERSION:
        raise BadVersion(f"frame version {version} (speak {VERSION})")
    if json_len > MAX_JSON or bin_len > MAX_BIN:
        raise FrameTooLarge(
            f"declared lengths json={json_len} bin={bin_len} exceed caps"
        )
    trace = None
    if flags & FLAG_TRACE:
        trace = _decode_trace(_read_exact(rfile, TRACE_BLOCK_SIZE, "trace block"))
    obj: Dict[str, Any] = {}
    if json_len:
        raw = _read_exact(rfile, json_len, "json payload")
        try:
            obj = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise FrameError(f"unparseable json payload: {e}") from e
        if not isinstance(obj, dict):
            raise FrameError(
                f"json payload is {type(obj).__name__}, not an object"
            )
    blob = _read_exact(rfile, bin_len, "binary payload") if bin_len else b""
    if method_id and "method" not in obj:
        name = METHOD_NAMES.get(method_id)
        if name is None:
            raise FrameError(f"unknown method id {method_id}")
        obj["method"] = name
    if trace is not None and "_trace" not in obj:
        obj["_trace"] = trace
    return obj, req_id, flags, blob


def decode_payload(obj: dict, req_id: int, flags: int, blob: bytes) -> dict:
    """Fold a frame's binary payload back into its dict: the compact stream
    delta reconstitutes the full frame shape, FLAG_BIN_TOKENS unpacks token
    runs, FLAG_BIN_BLOB attaches the raw blob as `_bin`. Callers above this
    line see exactly what a line-JSON peer would have seen."""
    if flags & FLAG_STREAM and not obj and blob:
        if len(blob) < 4 or (len(blob) - 4) % 4:
            raise TruncatedFrame(
                f"compact stream delta has odd length {len(blob)}"
            )
        n = (len(blob) - 4) // 4
        frm, *toks = struct.unpack(f"<I{n}i", blob)
        out = {
            "request_id": req_id,
            "from": frm,
            "tokens": list(toks),
            "tokens_so_far": frm + n,
        }
        if flags & FLAG_EOS:
            out["done"] = True
            out["finish_reason"] = "length"
            out["cancelled"] = False
        return out
    if flags & FLAG_BIN_TOKENS and blob:
        return unpack_tokens(obj, blob)
    if flags & FLAG_BIN_BLOB and blob:
        obj = dict(obj)
        obj["_bin"] = blob
        return obj
    return obj
