"""Process-level initialization + global flags.

Replaces the reference's gflags surface (paddle/utils/Flags.h:19-43: use_gpu,
trainer_count, trainer_id, num_gradient_servers, ...) and ``paddle.init``
(python/paddle/v2/__init__.py:65 → initPaddle). Here ``trainer_count`` maps to the
data axis of a `jax.sharding.Mesh`; multi-host topology comes from
``jax.distributed.initialize`` (see paddle_tpu/parallel/distributed.py).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional

log = logging.getLogger("paddle_tpu")


@dataclasses.dataclass
class GlobalFlags:
    # Device topology (reference: --use_gpu, --trainer_count; Flags.h:19-43).
    use_tpu: bool = True
    trainer_count: int = 1
    trainer_id: int = 0
    num_hosts: int = 1
    # Logging / stats (reference: --log_period, --show_param_stats_period).
    log_period: int = 100
    show_param_stats_period: int = 0
    # Random seed (reference: --seed).
    seed: int = 0
    # Dtype policy name ("float32" | "bfloat16").
    dtype_policy: str = "float32"
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


_flags = GlobalFlags()
_initialized = False


def flags() -> GlobalFlags:
    return _flags


def is_initialized() -> bool:
    return _initialized


def init(**kwargs: Any) -> GlobalFlags:
    """paddle.init analog. Accepts the v1 flag names; unknown flags are kept in
    ``extras`` rather than rejected (the reference forwards argv to gflags)."""
    global _initialized
    from paddle_tpu.core import dtypes

    for key, value in kwargs.items():
        if key == "use_gpu":  # v1 compat: GPU flag means "use the accelerator"
            _flags.use_tpu = bool(value)
        elif hasattr(_flags, key) and key != "extras":
            setattr(_flags, key, type(getattr(_flags, key))(value))
        else:
            _flags.extras[key] = value
    dtypes.set_policy(dtypes.get(_flags.dtype_policy))
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s",
        )
    _initialized = True
    return _flags
