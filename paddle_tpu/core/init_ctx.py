"""Process-level initialization + global flags.

Replaces the reference's gflags surface (paddle/utils/Flags.h:19-43: use_gpu,
trainer_count, trainer_id, num_gradient_servers, ...) and ``paddle.init``
(python/paddle/v2/__init__.py:65 → initPaddle). Here ``trainer_count`` maps to the
data axis of a `jax.sharding.Mesh`; multi-host topology comes from
``jax.distributed.initialize`` (see paddle_tpu/parallel/distributed.py).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Dict, Optional

log = logging.getLogger("paddle_tpu")


@dataclasses.dataclass
class GlobalFlags:
    # Device topology (reference: --use_gpu, --trainer_count; Flags.h:19-43).
    use_tpu: bool = True
    trainer_count: int = 1
    trainer_id: int = 0
    num_hosts: int = 1
    # Logging / stats (reference: --log_period, --show_param_stats_period).
    log_period: int = 100
    show_param_stats_period: int = 0
    # Random seed (reference: --seed).
    seed: int = 0
    # Dtype policy name ("float32" | "bfloat16").
    dtype_policy: str = "float32"
    # Persistent XLA compilation-cache directory ("" = PADDLE_TPU_COMPILE_CACHE
    # env, which itself defaults to off).
    compile_cache: str = ""
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


_flags = GlobalFlags()
_initialized = False


def flags() -> GlobalFlags:
    return _flags


def is_initialized() -> bool:
    return _initialized


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Wire jax's persistent compilation cache to `cache_dir` (or the
    PADDLE_TPU_COMPILE_CACHE env var). Repeat bench/profiling/test runs then
    skip XLA compilation for unchanged programs — tracing still happens, but
    the compile (the dominant cost) is served from disk. Returns the active
    directory, or None when disabled.

    The min-size/min-compile-time thresholds are zeroed so even the small CPU
    oracle programs cache; cache entries are keyed on serialized HLO + backend
    so a stale entry cannot be served for changed code."""
    cache_dir = cache_dir or os.environ.get("PADDLE_TPU_COMPILE_CACHE")
    if not cache_dir:
        return None
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    redirecting = jax.config.jax_compilation_cache_dir != cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    if redirecting:
        # jax latches its cache object (even a None one, if a compile ran
        # before any dir was configured); any dir change — including
        # None → dir — needs an explicit reset or the setting is a no-op
        from jax.experimental.compilation_cache import compilation_cache

        compilation_cache.reset_cache()
    from paddle_tpu.core import stats

    stats.install_cache_listener()
    log.info("persistent compilation cache at %s", cache_dir)
    return cache_dir


def detach_compilation_cache(reason: str = "") -> bool:
    """PERMANENTLY detach the persistent compilation cache from this
    process (sticky; True when a cache was actually detached).

    Exists for elastic resize: once a process re-shapes its mesh, later
    small EAGER multi-device programs (cost-sum adds, canonical
    gather/re-flatten, placement moves) repeat byte-identically across
    trainer generations and carry no per-trainer cache salt — on jax
    0.4.37's CPU backend, executing a persistent-cache-DESERIALIZED
    multi-device program in such a process corrupts memory or segfaults
    (the same bug the SGDTrainer `_cache_salt` works around for the
    compiled step; empirically, a region-scoped opt-out around the re-shard
    alone is NOT sufficient — the poisoned execution can be any later
    deserialized multi-device program, so the opt-out must be sticky).
    Mesh step programs never used the persistent cache anyway (the salt),
    so a resize-performing trainer process loses only the single-device
    program cache from the first resize onward. No-op when the cache was
    never enabled. jax_enable_compilation_cache alone does not reliably
    gate cache READS on jax 0.4.37 — the directory itself is detached and
    the latched cache object reset."""
    import jax

    if jax.config.jax_compilation_cache_dir is None:
        return False
    from jax.experimental.compilation_cache import compilation_cache

    log.warning(
        "detaching the persistent compilation cache for the rest of this "
        "process%s — deserialized multi-device programs are unsafe on this "
        "backend after a mesh resize (jax 0.4.37 CPU corruption bug; see "
        "core/init_ctx.detach_compilation_cache)",
        f" ({reason})" if reason else "",
    )
    jax.config.update("jax_compilation_cache_dir", None)
    compilation_cache.reset_cache()
    return True


def init(**kwargs: Any) -> GlobalFlags:
    """paddle.init analog. Accepts the v1 flag names; unknown flags are kept in
    ``extras`` rather than rejected (the reference forwards argv to gflags)."""
    global _initialized
    from paddle_tpu.core import dtypes

    for key, value in kwargs.items():
        if key == "use_gpu":  # v1 compat: GPU flag means "use the accelerator"
            _flags.use_tpu = bool(value)
        elif hasattr(_flags, key) and key != "extras":
            setattr(_flags, key, type(getattr(_flags, key))(value))
        else:
            _flags.extras[key] = value
    dtypes.set_policy(dtypes.get(_flags.dtype_policy))
    enable_compilation_cache(_flags.compile_cache or None)
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s",
        )
    _initialized = True
    return _flags
