"""Process-level initialization + global flags.

Replaces the reference's gflags surface (paddle/utils/Flags.h:19-43: use_gpu,
trainer_count, trainer_id, num_gradient_servers, ...) and ``paddle.init``
(python/paddle/v2/__init__.py:65 → initPaddle). Here ``trainer_count`` maps to the
data axis of a `jax.sharding.Mesh`; multi-host topology comes from
``jax.distributed.initialize`` (see paddle_tpu/parallel/distributed.py).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Dict, Optional

log = logging.getLogger("paddle_tpu")


@dataclasses.dataclass
class GlobalFlags:
    # Device topology (reference: --use_gpu, --trainer_count; Flags.h:19-43).
    use_tpu: bool = True
    trainer_count: int = 1
    trainer_id: int = 0
    num_hosts: int = 1
    # Logging / stats (reference: --log_period, --show_param_stats_period).
    log_period: int = 100
    show_param_stats_period: int = 0
    # Random seed (reference: --seed).
    seed: int = 0
    # Dtype policy name ("float32" | "bfloat16").
    dtype_policy: str = "float32"
    # Persistent XLA compilation-cache directory ("" = PADDLE_TPU_COMPILE_CACHE
    # env, which itself defaults to off).
    compile_cache: str = ""
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


_flags = GlobalFlags()
_initialized = False


def flags() -> GlobalFlags:
    return _flags


def is_initialized() -> bool:
    return _initialized


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Wire jax's persistent compilation cache to `cache_dir` (or the
    PADDLE_TPU_COMPILE_CACHE env var). Repeat bench/profiling/test runs then
    skip XLA compilation for unchanged programs — tracing still happens, but
    the compile (the dominant cost) is served from disk. Returns the active
    directory, or None when disabled.

    The min-size/min-compile-time thresholds are zeroed so even the small CPU
    oracle programs cache; cache entries are keyed on serialized HLO + backend
    so a stale entry cannot be served for changed code."""
    cache_dir = cache_dir or os.environ.get("PADDLE_TPU_COMPILE_CACHE")
    if not cache_dir:
        return None
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    redirecting = jax.config.jax_compilation_cache_dir != cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    if redirecting:
        # jax latches its cache object (even a None one, if a compile ran
        # before any dir was configured); any dir change — including
        # None → dir — needs an explicit reset or the setting is a no-op
        from jax.experimental.compilation_cache import compilation_cache

        compilation_cache.reset_cache()
    from paddle_tpu.core import stats

    stats.install_cache_listener()
    log.info("persistent compilation cache at %s", cache_dir)
    return cache_dir


def init(**kwargs: Any) -> GlobalFlags:
    """paddle.init analog. Accepts the v1 flag names; unknown flags are kept in
    ``extras`` rather than rejected (the reference forwards argv to gflags)."""
    global _initialized
    from paddle_tpu.core import dtypes

    for key, value in kwargs.items():
        if key == "use_gpu":  # v1 compat: GPU flag means "use the accelerator"
            _flags.use_tpu = bool(value)
        elif hasattr(_flags, key) and key != "extras":
            setattr(_flags, key, type(getattr(_flags, key))(value))
        else:
            _flags.extras[key] = value
    dtypes.set_policy(dtypes.get(_flags.dtype_policy))
    enable_compilation_cache(_flags.compile_cache or None)
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s",
        )
    _initialized = True
    return _flags
