"""Shared step-timing harness for all benchmark entry points (bench.py,
benchmarks/*.py).

The execution barrier is a VALUE fetch (float(cost)), not
jax.block_until_ready: on the remote-tunnel TPU backend block_until_ready
returns before the work runs, which produced impossible >100%-MFU readings.
Fetching the final cost forces the whole dependent step chain."""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Tuple

import numpy as np


def time_train_steps(
    step: Callable,
    state: Any,
    batch: Dict[str, Any],
    steps: int = 10,
    warmup: int = 2,
) -> Tuple[float, Any]:
    """Returns (seconds_per_step, final_state). `step(state, batch)` must
    return (new_state, cost_scalar, extras)."""
    for _ in range(max(warmup, 1)):
        state, cost, _ = step(state, batch)
    cost_v = float(cost)  # barrier: forces compile + warmup chain
    assert np.isfinite(cost_v), f"non-finite cost during warmup: {cost_v}"

    t0 = time.perf_counter()
    for _ in range(steps):
        state, cost, _ = step(state, batch)
    final = float(cost)  # barrier: forces the timed chain
    dt = time.perf_counter() - t0
    assert np.isfinite(final), f"non-finite cost during timing: {final}"
    return dt / steps, state


def time_multi_steps(
    multi: Callable,
    state: Any,
    batches: Dict[str, Any],
    k: int,
    dispatches: int = 4,
    warmup: int = 1,
) -> Tuple[float, Any]:
    """Times the K-step scan driver (SGDTrainer.make_multi_step): each
    dispatch runs `k` train steps in one compiled program. Returns
    (seconds_per_step, final_state); the barrier is a value fetch of the
    last scanned cost (see module docstring for why not block_until_ready)."""
    for _ in range(max(warmup, 1)):
        state, costs = multi(state, batches)
    warm = float(costs[-1])
    assert np.isfinite(warm), f"non-finite cost during warmup: {warm}"

    t0 = time.perf_counter()
    for _ in range(dispatches):
        state, costs = multi(state, batches)
    final = float(costs[-1])
    dt = time.perf_counter() - t0
    assert np.isfinite(final), f"non-finite cost during timing: {final}"
    return dt / (dispatches * k), state
