"""Layer-name crash context — utils/CustomStackTrace.h parity.

The reference pushes each layer's name while executing forward/backward
(NeuralNetwork.cpp:259-261) and dumps the stack from the glog failure handler
on crash (Logging.cpp:30). Here the same stack is kept per-thread and woven
into the exception chain, so a shape error deep in jax tracing reports WHICH
layer was being built."""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, List

_tls = threading.local()


def _stack() -> List[str]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


@contextlib.contextmanager
def layer_frame(name: str) -> Iterator[None]:
    stack = _stack()
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()


def current_stack() -> List[str]:
    return list(_stack())


def format_stack() -> str:
    s = _stack()
    if not s:
        return ""
    return " -> ".join(s)


class LayerError(RuntimeError):
    """Raised when a layer's forward fails; carries the layer stack."""

    def __init__(self, layer_name: str, stack: List[str], cause: BaseException):
        self.layer_name = layer_name
        self.layer_stack = stack
        super().__init__(
            f"error in layer {layer_name!r} "
            f"(layer stack: {' -> '.join(stack) or layer_name}): "
            f"{type(cause).__name__}: {cause}"
        )
