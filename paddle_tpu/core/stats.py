"""Timers + profiler hooks (SURVEY §5 tracing/profiling).

Parity: utils/Stat.h:63 StatSet / :114 Stat / :189 TimerOnce and the
REGISTER_TIMER* macros (:215-224) that the hot loop stamps
(TrainerInternal.cpp:94-152, per-layer timers NeuralNetwork.cpp:258/298);
hl_profiler_start/end (hl_cuda.h:338) maps to jax.profiler traces.

Gating: the reference compiles timers out unless WITH_TIMER=ON; here the
equivalent is the PADDLE_TPU_TIMER env var / enable_timers() — disabled
timers cost one dict lookup and a truth test."""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, Optional


class Stat:
    """Accumulates wall time + call count for one named timer (Stat.h:114)."""

    __slots__ = ("name", "total", "count", "max")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def add(self, seconds: float) -> None:
        self.total += seconds
        self.count += 1
        if seconds > self.max:
            self.max = seconds

    def __repr__(self):
        avg = self.total / max(self.count, 1)
        return (
            f"{self.name}: total={self.total * 1e3:.2f}ms count={self.count} "
            f"avg={avg * 1e3:.3f}ms max={self.max * 1e3:.3f}ms"
        )


class StatSet:
    """Global registry of Stats (Stat.h:63 StatSet + BarrierStatSet)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Stat] = {}
        self.enabled = os.environ.get("PADDLE_TPU_TIMER", "").lower() not in (
            "", "0", "false", "off",
        )

    def get(self, name: str) -> Stat:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = Stat(name)
            return s

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def report(self) -> str:
        with self._lock:
            stats = sorted(self._stats.values(), key=lambda s: -s.total)
        lines = ["======= StatSet: [GlobalStatInfo] status ======"]
        lines += [f"  {s!r}" for s in stats]
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                n: {"total_ms": s.total * 1e3, "count": s.count, "max_ms": s.max * 1e3}
                for n, s in self._stats.items()
            }


GLOBAL_STATS = StatSet()


def enable_timers(on: bool = True) -> None:
    GLOBAL_STATS.enabled = on


@contextlib.contextmanager
def timer(name: str) -> Iterator[None]:
    """REGISTER_TIMER_INFO analog: `with timer("forwardBackward"): ...`."""
    if not GLOBAL_STATS.enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        GLOBAL_STATS.get(name).add(time.perf_counter() - t0)


class TimerOnce:
    """Stat.h:189 TimerOnce: manual start/stop object form."""

    def __init__(self, name: str):
        self.name = name
        self._t0: Optional[float] = None

    def start(self) -> "TimerOnce":
        self._t0 = time.perf_counter()
        return self

    def stop(self) -> None:
        if self._t0 is not None and GLOBAL_STATS.enabled:
            GLOBAL_STATS.get(self.name).add(time.perf_counter() - self._t0)
        self._t0 = None


# -- device profiler (hl_profiler_start/end → jax.profiler) -----------------


def profiler_start(logdir: str = "/tmp/paddle_tpu_profile") -> None:
    import jax

    jax.profiler.start_trace(logdir)


def profiler_stop() -> None:
    import jax

    jax.profiler.stop_trace()


@contextlib.contextmanager
def profile_region(name: str) -> Iterator[None]:
    """Named trace annotation inside a profiler capture."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
