"""Timers + profiler hooks (SURVEY §5 tracing/profiling).

Parity: utils/Stat.h:63 StatSet / :114 Stat / :189 TimerOnce and the
REGISTER_TIMER* macros (:215-224) that the hot loop stamps
(TrainerInternal.cpp:94-152, per-layer timers NeuralNetwork.cpp:258/298);
hl_profiler_start/end (hl_cuda.h:338) maps to jax.profiler traces.

Gating: the reference compiles timers out unless WITH_TIMER=ON; here the
equivalent is the PADDLE_TPU_TIMER env var / enable_timers() — disabled
timers cost one dict lookup and a truth test."""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, Optional


class Stat:
    """Accumulates wall time + call count for one named timer (Stat.h:114)."""

    __slots__ = ("name", "total", "count", "max")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def add(self, seconds: float) -> None:
        self.total += seconds
        self.count += 1
        if seconds > self.max:
            self.max = seconds

    def __repr__(self):
        avg = self.total / max(self.count, 1)
        return (
            f"{self.name}: total={self.total * 1e3:.2f}ms count={self.count} "
            f"avg={avg * 1e3:.3f}ms max={self.max * 1e3:.3f}ms"
        )


class StatSet:
    """Global registry of Stats (Stat.h:63 StatSet + BarrierStatSet)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Stat] = {}
        self.enabled = os.environ.get("PADDLE_TPU_TIMER", "").lower() not in (
            "", "0", "false", "off",
        )

    def get(self, name: str) -> Stat:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = Stat(name)
            return s

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def report(self) -> str:
        # deterministic order (total desc, then name) and a percent-of-total
        # column, so timer splits are diffable across bench runs — equal
        # totals no longer land in dict-insertion order
        with self._lock:
            stats = sorted(self._stats.values(), key=lambda s: (-s.total, s.name))
        grand = sum(s.total for s in stats)
        lines = ["======= StatSet: [GlobalStatInfo] status ======"]
        lines += [
            f"  {s!r} ({100.0 * s.total / grand if grand else 0.0:5.1f}%)"
            for s in stats
        ]
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                n: {"total_ms": s.total * 1e3, "count": s.count, "max_ms": s.max * 1e3}
                for n, s in self._stats.items()
            }


GLOBAL_STATS = StatSet()


def enable_timers(on: bool = True) -> None:
    GLOBAL_STATS.enabled = on


# every NAMED EventCounter registers here so the observability plane
# (paddle_tpu/obs/metrics.py) can absorb them behind one read interface
# without touching their hot-path increment cost
EVENT_COUNTERS: Dict[str, "EventCounter"] = {}


class EventCounter:
    """Thread-safe named counters for rare-but-load-bearing runtime events
    (divergence guard trips, feeder retries, pipeline stalls, master
    reconnects). Unlike Stat these are unconditional — failure telemetry must
    not hide behind PADDLE_TPU_TIMER.

    A `name` registers the counter group in EVENT_COUNTERS for the metrics
    exporter; anonymous counters stay private."""

    def __init__(self, name: Optional[str] = None):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self.name = name
        if name:
            EVENT_COUNTERS[name] = self

    def incr(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n
            return self._counts[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


# fault-tolerance event counters (trainer divergence guard — incremented at
# guard POLLS by the device counter's delta, so one entry may cover a whole
# guard_check_every window — pipeline retries/stalls, master client
# reconnects/failovers, trainer-lease evictions, lost task acks, preemption
# drains, standby takeovers)
FT_EVENTS = EventCounter("ft")

# data-path events that are normal but worth counting: `padded_batches`
# (trailing batches padded to the mesh data-axis multiple instead of
# dropped — trainer + DevicePrefetcher increment it per padded batch)
DATA_EVENTS = EventCounter("data")


# -- memory / collective byte accounting (ISSUE 5 observability) -------------
#
# The sharded-update claims ("opt state 1/N per chip", "collective bytes cut
# 2-4x") are backed by numbers, not vibes: per-chip resident bytes come from
# sharding metadata (no device sync, usable at pass end inside the hot-loop
# discipline), HBM peaks from the backend's memory_stats() where the platform
# exposes it (TPU; CPU returns None and callers fall back to tree sizes).


def per_chip_tree_bytes(tree) -> int:
    """Bytes one chip holds for `tree`: per-leaf shard size from sharding
    metadata (replicated leaves count fully, P('data')-sharded leaves count
    1/N). Pure metadata — never fetches or syncs device buffers."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                shard = leaf.sharding.shard_shape(leaf.shape)
            except Exception:  # uncommitted/fully-replicated fallback
                shard = leaf.shape
            total += int(np.prod(shard, dtype=np.int64)) * leaf.dtype.itemsize
        else:
            total += np.asarray(leaf).nbytes
    return total


def device_memory_stats() -> Dict[str, int]:
    """`jax.local_devices()[0].memory_stats()` where the backend implements
    it (TPU: bytes_in_use / peak_bytes_in_use / ...), else {} — callers use
    per_chip_tree_bytes as the portable fallback."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return {}
    if not stats:
        return {}
    return {k: int(v) for k, v in stats.items() if isinstance(v, (int, float))}

# Timer names stamped by the async execution runtime (PADDLE_TPU_TIMER):
#   hostFeed / h2d        input-pipeline legs (trainer or prefetcher worker)
#   forwardBackward       the device-step segment (syncs only when timing on)
#   ckptFetch             non-blocking device→host snapshot copy (train thread)
#   ckptWrite             npz/CRC/v1/retention on the async writer thread


# -- recompile / input-pipeline telemetry ------------------------------------
#
# Every distinct batch-shape signature traces and compiles the jitted step
# again (SURVEY §7 hard-part (2): XLA recompiles per shape). The trainer
# records one signature per batch; the counter exposes per-pass and all-time
# distinct counts and warns once when shape churn crosses a threshold —
# the usual culprit is a missing/too-fine `seq_bucket` on a sequence slot.


def batch_signature(batch) -> tuple:
    """Hashable shape/dtype signature of a feed-ready batch dict — the same
    information XLA keys its compiled-executable cache on."""
    import numpy as np

    return tuple(
        sorted(
            (k, tuple(np.shape(v)), str(getattr(v, "dtype", type(v).__name__)))
            for k, v in batch.items()
        )
    )


class RecompileStats:
    """Counts distinct batch-shape signatures (== step recompiles) plus
    persistent-compilation-cache hits/misses reported by jax.monitoring."""

    def __init__(self, warn_threshold: int = 0):
        self._lock = threading.Lock()
        self._all: set = set()
        self._pass: set = set()
        self._warned = False
        self.cache_hits = 0
        self.cache_misses = 0
        self.warn_threshold = warn_threshold or int(
            os.environ.get("PADDLE_TPU_SHAPE_WARN", "8")
        )

    def record(self, signature: tuple) -> bool:
        """Record one batch signature; True when it is new this pass (i.e.
        the compiled step for it was not yet built this pass)."""
        with self._lock:
            new = signature not in self._pass
            self._pass.add(signature)
            self._all.add(signature)
            n = len(self._pass)
            should_warn = (
                new and not self._warned and n == self.warn_threshold
            )
            if should_warn:
                self._warned = True
        if should_warn:
            import logging

            logging.getLogger("paddle_tpu.stats").warning(
                "input pipeline produced %d distinct batch shapes this pass; "
                "each one recompiles the train step — check seq_bucket / "
                "batch-size settings for shape churn", n,
            )
        return new

    def start_pass(self) -> None:
        with self._lock:
            self._pass = set()

    def pass_signatures(self) -> int:
        with self._lock:
            return len(self._pass)

    def total_signatures(self) -> int:
        with self._lock:
            return len(self._all)

    def reset(self) -> None:
        with self._lock:
            self._all = set()
            self._pass = set()
            self._warned = False
            self.cache_hits = 0
            self.cache_misses = 0

    def report(self) -> str:
        return (
            f"shape signatures: pass={self.pass_signatures()} "
            f"total={self.total_signatures()} "
            f"persistent-cache hits={self.cache_hits} "
            f"misses={self.cache_misses}"
        )


RECOMPILES = RecompileStats()

_cache_listener_installed = False


def install_cache_listener() -> None:
    """Count persistent-compilation-cache hits/misses into RECOMPILES via
    jax.monitoring (events /jax/compilation_cache/cache_hits|cache_misses).
    Idempotent; importing jax here is fine — callers already run under it."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    import jax

    def _on_event(event: str, **_kw) -> None:
        if event.endswith("/cache_hits"):
            RECOMPILES.cache_hits += 1
        elif event.endswith("/cache_misses"):
            RECOMPILES.cache_misses += 1

    jax.monitoring.register_event_listener(_on_event)
    _cache_listener_installed = True


@contextlib.contextmanager
def timer(name: str) -> Iterator[None]:
    """REGISTER_TIMER_INFO analog: `with timer("forwardBackward"): ...`."""
    if not GLOBAL_STATS.enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        GLOBAL_STATS.get(name).add(time.perf_counter() - t0)


class TimerOnce:
    """Stat.h:189 TimerOnce: manual start/stop object form."""

    def __init__(self, name: str):
        self.name = name
        self._t0: Optional[float] = None

    def start(self) -> "TimerOnce":
        self._t0 = time.perf_counter()
        return self

    def stop(self) -> None:
        if self._t0 is not None and GLOBAL_STATS.enabled:
            GLOBAL_STATS.get(self.name).add(time.perf_counter() - self._t0)
        self._t0 = None


# -- device profiler (hl_profiler_start/end → jax.profiler) -----------------
#
# Idempotent on purpose: jax.profiler raises RuntimeError on a second
# start_trace and on stop without start; a double-wrapped event handler or a
# crashed profiled pass must degrade to a warning, not kill training.

_profiler_active = False


def profiler_start(logdir: str = "/tmp/paddle_tpu_profile") -> None:
    """Start a jax.profiler trace. A second start while one is active warns
    and no-ops instead of propagating jax's "already started" RuntimeError."""
    global _profiler_active
    import logging

    import jax

    if _profiler_active:
        logging.getLogger("paddle_tpu.stats").warning(
            "profiler_start: a trace is already active — ignoring the "
            "second start (stop the first with profiler_stop())"
        )
        return
    try:
        jax.profiler.start_trace(logdir)
    except RuntimeError as e:
        # started outside our bookkeeping (e.g. by user code calling jax
        # directly); adopt it so profiler_stop() still works
        logging.getLogger("paddle_tpu.stats").warning(
            "profiler_start: jax reports a trace already running (%s); "
            "adopting it", e,
        )
    _profiler_active = True


def profiler_stop() -> None:
    """Stop the active trace; a stop without a start is a silent no-op."""
    global _profiler_active
    import jax

    if not _profiler_active:
        return
    try:
        jax.profiler.stop_trace()
    finally:
        _profiler_active = False


@contextlib.contextmanager
def profile_region(name: str) -> Iterator[None]:
    """Named trace annotation inside a profiler capture."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
