"""String→factory registries.

TPU-native analog of the reference's ``ClassRegistrar`` (paddle/utils/ClassRegistrar.h)
which backs REGISTER_LAYER (paddle/gserver/layers/Layer.h:31), the activation registry
(gserver/activations/ActivationFunction.cpp:40-63), the evaluator registry
(gserver/evaluators/Evaluator.h:32) and the data-provider registry
(gserver/dataproviders/DataProvider.h:46).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional, Tuple


class Registry:
    """A named string→factory map with decorator-style registration."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Any] = {}

    def register(self, *names: str) -> Callable[[Any], Any]:
        def deco(obj: Any) -> Any:
            for name in names:
                if name in self._entries:
                    raise KeyError(f"{self.kind} {name!r} already registered")
                self._entries[name] = obj
            return obj

        return deco

    def get(self, name: str) -> Any:
        try:
            return self._entries[name]
        except KeyError:
            known = ", ".join(sorted(self._entries))
            raise KeyError(f"unknown {self.kind} {name!r}; known: {known}") from None

    def maybe_get(self, name: str) -> Optional[Any]:
        return self._entries.get(name)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(self._entries.items())

    def names(self):
        return sorted(self._entries)


LAYERS = Registry("layer")
ACTIVATIONS = Registry("activation")
EVALUATORS = Registry("evaluator")
DATA_PROVIDERS = Registry("data provider")
OPTIMIZERS = Registry("optimizer")
LR_SCHEDULES = Registry("learning-rate schedule")
