"""Deterministic chaos-injection harness.

The reference runtime treats failure as the common case — lease timeouts
re-queue tasks (go/master/service.go:166), `failureMax` discards poison
tasks, pserver checkpoints carry CRCs (go/pserver/service.go:146) — but
nothing in a test suite exercises those paths unless failures can be
*produced on demand*. This module is the single switchboard for injected
faults: every fault-tolerance hook point (pipeline worker, master RPC
handler, checkpoint writer, train step) asks the active injector whether to
misbehave, so chaos tests and `benchmarks/chaos_bench.py` are seeded and
reproducible ("RPC Considered Harmful": failure handling must be a tested
code path, not a comment).

Spec grammar (env `PADDLE_TPU_FAULTS` or `configure()`/`inject()`):

    site:value[,site:value...]

where `value` is one of
    0.05        fire with probability 0.05 per hit (seeded per-site RNG)
    5ms / 0.5s  fire on every hit, with that delay (for *_delay sites)
    step=37     fire exactly once, on the site's 37th hit (0-based)

Known sites (hooks live next to the code they sabotage):
    feeder_raise   pipeline worker raises before prepare()   (pipeline.iter_async)
    h2d_delay      sleep inside the prefetcher's H2D leg     (pipeline.DevicePrefetcher)
    master_drop    master drops an RPC without replying      (runtime.master._Handler)
    ckpt_truncate  torn write: truncate an .npz post-rename  (trainer.checkpoint.save_pass)
    nan_loss       poison a float batch slot with NaN        (trainer.SGDTrainer.train)
    kill           raise InjectedKill before a train step    (trainer.SGDTrainer.train)
    master_kill    master process dies mid-RPC: the server   (runtime.master._Handler)
                   shuts down abruptly, no reply, no final
                   snapshot — failover/standby must absorb
    preempt        simulated preemption notice: sets the     (trainer.SGDTrainer.train)
                   core.preempt drain flag (SIGTERM analog)
    conn_reset     client-side partition: the master RPC     (runtime.master.MasterClient)
                   socket resets after connect; reconnect/
                   failover path must absorb
    resize_drain_stall  trainer wedges INSIDE the resize      (trainer._drain_resize /
                   drain barrier — never acks resize_drained, runtime.master.ResizeClient,
                   so the master must evict it on lease       cluster_reader drain)
                   expiry for the epoch to complete; stall
                   length via PADDLE_TPU_RESIZE_STALL_S
                   (default 300)
    reshard_kill   process dies mid-re-shard, AFTER the       (trainer.SGDTrainer.resize_to)
                   drain checkpoint and barrier — auto_resume
                   must replay the pass from the drained
                   boundary on the NEW mesh
    decode_raise   serving engine raises mid-decode — the     (serving.session._decode_once)
                   session supervisor must restart the
                   engine, re-init the page pool and replay
                   in-flight requests (result-transparent)
    page_exhaust   KV page pool fails at admission            (serving.session._admit)
                   (exhaustion/corruption analog); same
                   supervisor recovery as decode_raise
    engine_stall   serving engine thread wedges between       (serving.session._engine_loop)
                   steps — no fault raised, no progress; the
                   supervisor's stall watchdog must supersede
                   and restart it; stall length via
                   PADDLE_TPU_SERVING_STALL_S (default 300)
    controller_kill  autoscaler controller dies at the top of (runtime.autoscaler
                   a tick — the fleet it steered must degrade  .AutoscalerController.tick)
                   to a static fleet (liveness never depends
                   on the controller); a restarted controller
                   reconciles from observed state
    scale_decision_stall  autoscaler tick wedges before        (runtime.autoscaler
                   deciding — must stall only the controller,  .AutoscalerController.tick)
                   never serving/training; stall length via
                   PADDLE_TPU_SCALE_STALL_S (default 300)

Seeding: `PADDLE_TPU_FAULTS_SEED` (or the `seed` argument). Each site gets
its own `random.Random(f"{seed}:{site}")` stream, so the fire pattern of one
site is independent of how often the others are polled.
"""

from __future__ import annotations

import contextlib
import os
import random
import re
import threading
import time
from typing import Dict, Iterator, Optional


class InjectedFault(RuntimeError):
    """A fault raised on purpose by the chaos harness."""


class InjectedKill(InjectedFault):
    """Simulated process death (SIGKILL analog) mid-training."""


_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s)$")


class FaultSpec:
    """One parsed `site:value` entry."""

    __slots__ = ("site", "prob", "step", "delay_s")

    def __init__(self, site: str, prob=None, step=None, delay_s=None):
        self.site = site
        self.prob = prob
        self.step = step
        self.delay_s = delay_s

    def __repr__(self):
        for k in ("prob", "step", "delay_s"):
            v = getattr(self, k)
            if v is not None:
                return f"FaultSpec({self.site}:{k}={v})"
        return f"FaultSpec({self.site})"


def parse_spec(spec: str) -> Dict[str, FaultSpec]:
    out: Dict[str, FaultSpec] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        site, sep, value = entry.partition(":")
        site = site.strip()
        value = value.strip()
        if not sep or not site or not value:
            raise ValueError(
                f"bad fault entry {entry!r}: want site:prob, site:<N>ms|<N>s "
                f"or site:step=<N>"
            )
        m = _DURATION_RE.match(value)
        if m:
            if not site.endswith("_delay"):
                # a duration on a raise/drop site would silently mean
                # "fire every hit" — reject it instead of surprising
                raise ValueError(
                    f"duration value {value!r} only makes sense for *_delay "
                    f"sites, not {site!r} (use a probability or step=N)"
                )
            scale = 1e-3 if m.group(2) == "ms" else 1.0
            out[site] = FaultSpec(site, delay_s=float(m.group(1)) * scale)
        elif site.endswith("_delay"):
            # the mirror-image mistake: sleep() hooks only honor durations,
            # so a probability/step here would parse but never fire
            raise ValueError(
                f"*_delay site {site!r} needs a duration value "
                f"(<N>ms or <N>s), got {value!r}"
            )
        elif value.startswith("step="):
            out[site] = FaultSpec(site, step=int(value[len("step="):]))
        else:
            try:
                prob = float(value)
            except ValueError:
                raise ValueError(f"bad fault value {value!r} for site {site!r}")
            if not 0.0 <= prob <= 1.0:
                raise ValueError(
                    f"fault probability for {site!r} must be in [0,1], got {prob}"
                )
            out[site] = FaultSpec(site, prob=prob)
    return out


class FaultInjector:
    """Seeded, thread-safe fault decision engine.

    `fire(site)` counts a hit and decides whether the fault triggers; hits
    and trigger counts are exposed (`hits` / `fired`) so tests can assert
    both "the fault happened" and "the hook point was actually reached".
    """

    def __init__(self, spec: str = "", seed: Optional[int] = None):
        self._lock = threading.Lock()
        self.configure(spec, seed)

    def configure(self, spec: str = "", seed: Optional[int] = None) -> None:
        with self._lock:
            self.spec_str = spec or ""
            self.seed = (
                seed
                if seed is not None
                else int(os.environ.get("PADDLE_TPU_FAULTS_SEED", "0"))
            )
            self.spec = parse_spec(self.spec_str)
            self._rngs = {
                site: random.Random(f"{self.seed}:{site}") for site in self.spec
            }
            self.hits: Dict[str, int] = {site: 0 for site in self.spec}
            self.fired: Dict[str, int] = {}

    @property
    def active(self) -> bool:
        return bool(self.spec)

    def fire(self, site: str) -> bool:
        """Count one hit of `site`; True when the fault should trigger now."""
        if site not in self.spec:  # racy pre-check: cheap fast path only
            return False
        with self._lock:
            # re-read under the lock: a concurrent configure() (inject()
            # exit while a worker thread lingers) swaps spec/hits together
            fs = self.spec.get(site)
            if fs is None:
                return False
            n = self.hits[site]
            self.hits[site] = n + 1
            if fs.step is not None:
                hit = n == fs.step
            elif fs.prob is not None:
                hit = self._rngs[site].random() < fs.prob
            else:  # pure-delay spec: fires every hit
                hit = True
            if hit:
                self.fired[site] = self.fired.get(site, 0) + 1
        return hit

    def maybe_raise(self, site: str) -> None:
        if self.fire(site):
            raise InjectedFault(f"injected fault {site!r} (chaos harness)")

    def sleep(self, site: str) -> None:
        """Delay-site hook: sleep the configured duration when firing."""
        with self._lock:
            fs = self.spec.get(site)
            delay = fs.delay_s if fs is not None else None
        if delay and self.fire(site):
            time.sleep(delay)

    def reset(self) -> None:
        self.configure(self.spec_str, self.seed)


ACTIVE = FaultInjector(os.environ.get("PADDLE_TPU_FAULTS", ""))


def get() -> FaultInjector:
    return ACTIVE


def maybe_stall(
    site: str,
    env: str = "PADDLE_TPU_RESIZE_STALL_S",
    default_s: float = 300.0,
) -> bool:
    """Wedge-the-thread hook shared by the stall sites (resize drain,
    serving engine): when `site` fires, sleep for `$env` seconds (default
    `default_s`) — long enough for whichever watchdog owns this thread
    (master barrier timeout, lease eviction, serving stall supervisor) to
    remove or supersede it — then return True. One definition so every
    stall drill wedges identically."""
    if not (ACTIVE.active and ACTIVE.fire(site)):
        return False
    stall_s = float(os.environ.get(env, str(default_s)))
    import logging

    logging.getLogger("paddle_tpu.faults").warning(
        "chaos: %s fired — wedging %.0fs (no ack, no progress; the owning "
        "watchdog must remove or supersede this thread)", site, stall_s,
    )
    time.sleep(stall_s)
    return True


@contextlib.contextmanager
def inject(spec: str, seed: int = 0) -> Iterator[FaultInjector]:
    """Temporarily activate a fault spec (tests / chaos bench):

        with faults.inject("nan_loss:step=3") as inj:
            trainer.train(...)
        assert inj.fired["nan_loss"] == 1
    """
    prev_spec, prev_seed = ACTIVE.spec_str, ACTIVE.seed
    ACTIVE.configure(spec, seed)
    try:
        yield ACTIVE
    finally:
        ACTIVE.configure(prev_spec, prev_seed)
