"""Preemption-safe shutdown (SIGTERM/SIGINT drain).

On TPU/cloud infrastructure the canonical preemption notice is SIGTERM with a
short grace period; the reference had no story for it — a preempted trainer
simply died and lost everything since its last pass-boundary dump. Here the
signal only sets a flag; the train loop polls it at batch boundaries
(`requested()`), finishes the in-flight step, writes a CRC-valid mid-pass
checkpoint + `latest` pointer, and raises `trainer.Preempted`, which the CLI
turns into the distinct exit code `EXIT_PREEMPTED`. A supervisor that
restarts the job with `auto_resume=True` continues from exactly the drained
batch boundary — bitwise-identically to a never-preempted run on a
deterministic reader (tested in tests/test_cluster.py).

Semantics:
- first SIGTERM/SIGINT: request a drain (flag + deadline = now + grace_s)
- second signal while draining: give up immediately — restore the previous
  handler and re-deliver (the classic double-ctrl-C escape hatch)
- past the grace deadline the trainer skips the checkpoint write and exits
  with whatever the last durable checkpoint was (`deadline_passed()`)

The guard is also the landing point for the seeded `preempt` chaos site
(core/faults.py): the injector calls `request()` directly, so the whole
drain path is a deterministic, tested code path without real signals.

Elastic resize (ISSUE 8) reuses the same poll-at-batch-boundary discipline as
a COOPERATIVE drain — no process exit: `request_resize(world)` parks a
`ResizeRequest` on the guard; the train loop sees `resize_requested()` at the
next dispatch boundary, writes a mid-pass checkpoint, re-shards the train
state from the canonical layout onto the new mesh, and CONTINUES the pass.
The request is claimed with `take_resize()` (one drain per request), and a
fleet-coordinated trainer gets the request set by its master heartbeat watcher
(runtime.master.ResizeClient) rather than a signal.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Dict, Optional, Tuple

from paddle_tpu.core import stats

log = logging.getLogger("paddle_tpu.preempt")

# Distinct exit code for "checkpointed and exited on a preemption notice" —
# chosen outside the 128+signum band so a supervisor can tell a clean drain
# (restart with auto_resume) from an unhandled kill.
EXIT_PREEMPTED = 77

DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class ResizeRequest:
    """One pending elastic-resize order: re-shape the mesh data axis to
    `world` chips. `epoch` is the master's resize-epoch id (0 for local,
    uncoordinated requests) and `instance` the announcing master's resize-
    plane instance token ("" for local) — epoch numbers restart when a
    standby is promoted, so only the (instance, epoch) pair identifies an
    epoch; `requested_at` anchors the drain-latency split reported by the
    trainer."""

    __slots__ = ("world", "epoch", "instance", "reason", "requested_at")

    def __init__(
        self, world: int, epoch: int = 0, instance: str = "",
        reason: str = "resize",
    ):
        if int(world) < 1:
            raise ValueError(f"resize world must be >= 1, got {world}")
        self.world = int(world)
        self.epoch = int(epoch)
        self.instance = instance or ""
        self.reason = reason
        self.requested_at = time.monotonic()

    def __repr__(self):
        return (
            f"ResizeRequest(world={self.world}, epoch={self.epoch}, "
            f"instance={self.instance!r}, reason={self.reason!r})"
        )


class PreemptionGuard:
    """Flag + deadline the train loop polls at batch boundaries."""

    def __init__(self, grace_s: float = 30.0):
        self.grace_s = float(grace_s)
        self._lock = threading.Lock()
        self._requested_at: Optional[float] = None
        self._reason: Optional[str] = None
        self._resize: Optional[ResizeRequest] = None
        self._old_handlers: Dict[int, object] = {}

    # -- signal wiring -------------------------------------------------------
    def install(self, signals: Tuple[int, ...] = DEFAULT_SIGNALS) -> "PreemptionGuard":
        """Install drain handlers. Only possible from the main thread
        (signal.signal's rule); elsewhere the guard still works via
        `request()` — e.g. the chaos injector — so failure is non-fatal."""
        for sig in signals:
            try:
                self._old_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError as e:  # non-main thread
                log.warning("cannot install handler for signal %d: %s", sig, e)
        return self

    def uninstall(self) -> None:
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):
                pass
        self._old_handlers.clear()

    def _on_signal(self, signum, frame) -> None:
        if self.requested:
            # second notice while draining: stop being graceful — put the
            # previous handler back and re-deliver so default semantics
            # (KeyboardInterrupt / process death) take over immediately
            old = self._old_handlers.get(signum, signal.SIG_DFL)
            signal.signal(signum, old)
            os.kill(os.getpid(), signum)
            return
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = f"signal {signum}"
        self.request(name)

    # -- flag ----------------------------------------------------------------
    def request(self, reason: str = "preempt") -> None:
        """Mark the run as preempted; idempotent (first reason/deadline win)."""
        with self._lock:
            if self._requested_at is not None:
                return
            self._requested_at = time.monotonic()
            self._reason = reason
        stats.FT_EVENTS.incr("preempt_request")
        log.warning(
            "preemption notice (%s): draining — will finish the current step, "
            "checkpoint, and exit with code %d (grace %.1fs)",
            reason, EXIT_PREEMPTED, self.grace_s,
        )

    @property
    def requested(self) -> bool:
        return self._requested_at is not None

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    def deadline_passed(self) -> bool:
        """True once the grace budget is exhausted — the drain should stop
        doing durable work (checkpoint writes) and just exit."""
        with self._lock:
            if self._requested_at is None:
                return False
            return time.monotonic() - self._requested_at > self.grace_s

    # -- elastic resize (cooperative drain, no exit) -------------------------
    def request_resize(
        self, world: int, epoch: int = 0, instance: str = "",
        reason: str = "resize",
    ) -> bool:
        """Park a resize order for the train loop's next dispatch boundary.
        A strictly LATER epoch from the SAME master instance supersedes an
        unclaimed earlier request (the master may re-announce after
        membership churn), and any epoch from a DIFFERENT instance does too
        (a heartbeat reply reflects the live master's current state — a
        promoted standby's epoch 1 outranks a dead primary's parked epoch
        5). Stale/duplicate same-instance epochs, and a local epoch-0
        order while any request is already parked, are ignored — a local
        request can never clobber a pending master-coordinated one.
        Returns True when the request was accepted."""
        req = ResizeRequest(world, epoch, instance, reason)
        with self._lock:
            cur = self._resize
            if cur is not None:
                if epoch == 0:
                    return False  # local order never clobbers a parked one
                if cur.instance == req.instance and cur.epoch >= epoch:
                    return False  # duplicate/stale within one master's numbering
            self._resize = req
        stats.FT_EVENTS.incr("resize_request")
        log.warning(
            "resize notice (%s): will drain at the next batch boundary and "
            "re-shard onto %d chip(s) (epoch %d)", reason, req.world, req.epoch,
        )
        return True

    @property
    def resize_pending(self) -> bool:
        return self._resize is not None

    def resize_request(self) -> Optional[ResizeRequest]:
        with self._lock:
            return self._resize

    def take_resize(self) -> Optional[ResizeRequest]:
        """Claim the pending resize (clears the flag) — exactly one drain per
        request, even with several pollers."""
        with self._lock:
            req, self._resize = self._resize, None
            return req

    def reset(self) -> None:
        with self._lock:
            self._requested_at = None
            self._reason = None
            self._resize = None


# -- module-level singleton (what the trainer and CLI talk to) ---------------

_GUARD: Optional[PreemptionGuard] = None
_GUARD_LOCK = threading.Lock()


def install(grace_s: float = 30.0, signals: Tuple[int, ...] = DEFAULT_SIGNALS) -> PreemptionGuard:
    """Create (or reconfigure) the process-wide guard and hook the signals."""
    global _GUARD
    with _GUARD_LOCK:
        if _GUARD is None:
            _GUARD = PreemptionGuard(grace_s)
        else:
            _GUARD.grace_s = float(grace_s)
        return _GUARD.install(signals)


def get() -> PreemptionGuard:
    """The process-wide guard, created flag-only (no signal handlers) on
    first use — this is how the chaos `preempt` site requests a drain in
    processes that never called install()."""
    global _GUARD
    with _GUARD_LOCK:
        if _GUARD is None:
            _GUARD = PreemptionGuard()
        return _GUARD


def requested() -> bool:
    """Cheap poll for the train loop: no guard → never preempted."""
    g = _GUARD
    return g is not None and g.requested


def resize_requested() -> bool:
    """Cheap per-boundary poll for the train loop: no guard → no resize."""
    g = _GUARD
    return g is not None and g.resize_pending


def reset() -> None:
    """Clear the flag and detach handlers (test isolation)."""
    global _GUARD
    with _GUARD_LOCK:
        if _GUARD is not None:
            _GUARD.uninstall()
            _GUARD.reset()
        _GUARD = None
