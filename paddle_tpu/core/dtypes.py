"""Dtype policy for TPU execution.

The reference is float32-only (``real`` typedef, paddle/math). On TPU the MXU wants
bfloat16 inputs with float32 accumulation, so compute dtype and parameter dtype are
split: parameters/optimizer state stay float32, matmul/conv inputs may be cast to
bfloat16, and accumulation uses ``preferred_element_type=float32``.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional

import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    accum_dtype: jnp.dtype = jnp.float32
    # Dot/conv precision. For f32 compute we must request HIGHEST: XLA's DEFAULT
    # runs reduced-precision passes even on CPU, which breaks the numeric-oracle
    # tests. For bf16 compute the inputs are already bf16 — DEFAULT is right.
    precision: lax.Precision = lax.Precision.HIGHEST

    @property
    def name(self) -> str:
        """Canonical short name ("f32" | "bf16") for JSON/log reporting."""
        return "bf16" if self.compute_dtype == jnp.bfloat16 else "f32"

    def cast(self, x):
        """THE sanctioned precision-cast boundary: floating arrays move to
        the compute dtype, everything else (ints, bools, already-converted
        arrays) passes through. Every dot/conv input cast in the compiled
        train step must go through here — tests/test_lint_hotloop.py bans
        raw `.astype(` in the step body so the policy stays auditable."""
        if x.dtype != self.compute_dtype and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.compute_dtype)
        return x

    # pre-PR-9 name; ops call sites use cast() now, kept for any out-of-tree
    # callers of the old spelling
    cast_compute = cast


_F32 = Policy()
# bf16 end-to-end for dots/convs: the MXU accumulates in f32 internally and
# rounds the result; asking for a f32 *output* (preferred_element_type) breaks
# autodiff transpose rules with mixed-dtype operands, so accum == compute here.
_BF16 = Policy(
    compute_dtype=jnp.bfloat16,
    accum_dtype=jnp.bfloat16,
    precision=lax.Precision.DEFAULT,
)

# Context-local, not a module global: Network.init/apply wrap every trace in
# policy_scope, and traces can run concurrently (a serving/inference thread
# jitting a forward while the trainer traces its step) — a module global
# would let one thread's scope leak bf16 dots into another thread's program.
_current: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_dtype_policy", default=_F32
)


def current() -> Policy:
    return _current.get()


def set_policy(policy: Policy) -> None:
    """Sets the ambient policy for THIS thread/context (contextvar
    semantics: other threads keep their own ambient, defaulting to f32)."""
    _current.set(policy)


@contextlib.contextmanager
def policy_scope(policy: Policy):
    token = _current.set(policy)
    try:
        yield policy
    finally:
        _current.reset(token)


def f32_policy() -> Policy:
    return _F32


def bf16_policy() -> Policy:
    return _BF16


# names accepted by get() / SGDTrainer(precision=) / the CLI --precision flag
PRECISIONS = ("f32", "bf16")


def get(name: Optional[str]) -> Policy:
    if name is None or name == "float32" or name == "f32":
        return _F32
    if name in ("bfloat16", "bf16", "mixed"):
        return _BF16
    raise ValueError(
        f"unknown dtype policy {name!r}; expected one of {PRECISIONS} "
        f"(or the long spellings float32/bfloat16)"
    )
