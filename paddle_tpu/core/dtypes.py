"""Dtype policy for TPU execution.

The reference is float32-only (``real`` typedef, paddle/math). On TPU the MXU wants
bfloat16 inputs with float32 accumulation, so compute dtype and parameter dtype are
split: parameters/optimizer state stay float32, matmul/conv inputs may be cast to
bfloat16, and accumulation uses ``preferred_element_type=float32``.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional

import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    accum_dtype: jnp.dtype = jnp.float32
    # Dot/conv precision. For f32 compute we must request HIGHEST: XLA's DEFAULT
    # runs reduced-precision passes even on CPU, which breaks the numeric-oracle
    # tests. For bf16 compute the inputs are already bf16 — DEFAULT is right.
    precision: lax.Precision = lax.Precision.HIGHEST

    def cast_compute(self, x):
        if x.dtype != self.compute_dtype and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.compute_dtype)
        return x


_F32 = Policy()
# bf16 end-to-end for dots/convs: the MXU accumulates in f32 internally and
# rounds the result; asking for a f32 *output* (preferred_element_type) breaks
# autodiff transpose rules with mixed-dtype operands, so accum == compute here.
_BF16 = Policy(
    compute_dtype=jnp.bfloat16,
    accum_dtype=jnp.bfloat16,
    precision=lax.Precision.DEFAULT,
)

_current: Policy = _F32


def current() -> Policy:
    return _current


def set_policy(policy: Policy) -> None:
    global _current
    _current = policy


@contextlib.contextmanager
def policy_scope(policy: Policy):
    global _current
    prev = _current
    _current = policy
    try:
        yield policy
    finally:
        _current = prev


def f32_policy() -> Policy:
    return _F32


def bf16_policy() -> Policy:
    return _BF16


def get(name: Optional[str]) -> Policy:
    if name is None or name == "float32" or name == "f32":
        return _F32
    if name in ("bfloat16", "bf16", "mixed"):
        return _BF16
    raise ValueError(f"unknown dtype policy {name!r}")
