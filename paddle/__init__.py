"""`paddle` compatibility namespace.

The v1 stack's import surface (SURVEY §2.4): config scripts do
`from paddle.trainer_config_helpers import *`, data providers do
`from paddle.trainer.PyDataProvider2 import *`, and v2 user scripts do
`import paddle.v2 as paddle`. Each submodule here is a thin re-export of the
corresponding paddle_tpu implementation — the real code lives in
paddle_tpu/, this package only provides the historical import paths so
unmodified reference scripts run.
"""

__version__ = "0.11.0-tpu"


def init(**kwargs):
    """paddle.init(use_gpu=..., trainer_count=...) — v2 entry point."""
    from paddle_tpu.core import init_ctx

    use_gpu = kwargs.pop("use_gpu", None)
    if use_gpu is not None:
        kwargs.setdefault("use_tpu", use_gpu)
    allowed = {"use_tpu", "trainer_count", "log_period", "seed", "dtype_policy"}
    init_ctx.init(**{k: v for k, v in kwargs.items() if k in allowed})
