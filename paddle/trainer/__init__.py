"""paddle.trainer — config_parser + PyDataProvider2 import paths."""
