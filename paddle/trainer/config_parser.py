"""paddle.trainer.config_parser — parse_config entry points.

The reference's C++ trainer calls parse_config_and_serialize through embedded
Python (TrainerConfigHelper.cpp:34-56); here the same names resolve to the
paddle_tpu config pipeline.
"""

from paddle_tpu.config.config_parser import (  # noqa: F401
    ParsedConfig,
    define_py_data_sources2,
    get_config_arg,
    inputs,
    outputs,
    parse_config,
    parse_config_and_serialize,
)
from paddle_tpu.config.optimizers import settings  # noqa: F401
