"""paddle.trainer.PyDataProvider2 — the @provider data-provider surface.

Re-exports the paddle_tpu implementation of the reference module
(python/paddle/trainer/PyDataProvider2.py:365 @provider + input types
:63-236): `@provider`, input-type constructors, CacheType.
"""

from paddle_tpu.data.provider import (  # noqa: F401
    CacheType,
    DataProviderWrapper,
    Settings,
    provider,
)
from paddle_tpu.data.feeder import (  # noqa: F401
    dense_array,
    dense_vector,
    dense_vector_sequence,
    dense_vector_sub_sequence,
    integer_value,
    integer_value_sequence,
    integer_value_sub_sequence,
    sparse_binary_vector,
    sparse_value_slot,
)

# sequence variants the reference exposes under several historical names
sparse_binary_vector_sequence = sparse_binary_vector
integer_sequence = integer_value_sequence


__all__ = [
    "provider",
    "CacheType",
    "DataProviderWrapper",
    "Settings",
    "dense_vector",
    "dense_array",
    "dense_vector_sequence",
    "integer_value",
    "integer_value_sequence",
    "integer_value_sub_sequence",
    "dense_vector_sub_sequence",
    "integer_sequence",
    "sparse_binary_vector",
    "sparse_binary_vector_sequence",
    "sparse_value_slot",
]
