"""paddle.trainer_config_helpers — the v1 config-script DSL.

Star-import surface of the reference package (layers.py + activations.py +
poolings.py + attrs.py + optimizers.py + evaluators.py + data_sources.py,
plus the config_parser built-ins the reference re-exports: settings,
get_config_arg, define_py_data_sources2, outputs). Implementations live in
paddle_tpu.config; signatures match the reference (see
paddle_tpu/config/v1_layers.py).
"""

from paddle_tpu.config.helpers import *  # noqa: F401,F403
from paddle_tpu.config.helpers import __all__ as _helpers_all
from paddle_tpu.config.config_parser import (  # noqa: F401
    define_py_data_sources2,
    get_config_arg,
    inputs,
    outputs,
)

# define_py_data_sources (the older single-module variant) aliases the v2 one
define_py_data_sources = define_py_data_sources2

__all__ = list(_helpers_all) + [
    "outputs",
    "inputs",
    "get_config_arg",
    "define_py_data_sources2",
    "define_py_data_sources",
]
