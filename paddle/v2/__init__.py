"""paddle.v2 — the v2 user API import path (`import paddle.v2 as paddle`).

Aliases the paddle_tpu.v2 package and its submodules under the historical
names so reference v2 scripts (layer/trainer/dataset/reader/event usage per
python/paddle/v2) import unchanged.
"""

import sys as _sys

import paddle_tpu.v2 as _v2
from paddle_tpu.v2 import *  # noqa: F401,F403

# submodule aliases: make `import paddle.v2.layer`, `paddle.v2.dataset.mnist`
# etc. resolve to the paddle_tpu implementations (same module objects, so
# state like dataset caches is shared no matter which path imported them)
_SUBMODULES = [
    "activation", "attr", "data_type", "event", "inference", "layer",
    "minibatch", "networks", "optimizer", "parameters", "plot", "pooling",
    "topology", "trainer",
]
for _name in _SUBMODULES:
    _mod = getattr(
        __import__(f"paddle_tpu.v2.{_name}", fromlist=[_name]), "__dict__", None
    )
    _sys.modules[f"{__name__}.{_name}"] = _sys.modules[f"paddle_tpu.v2.{_name}"]
    globals()[_name] = _sys.modules[f"paddle_tpu.v2.{_name}"]

# data/reader/dataset live under paddle_tpu.data but are paddle.v2.* names
import paddle_tpu.data.reader as _reader  # noqa: E402

_sys.modules[f"{__name__}.reader"] = _reader
reader = _reader

try:
    import paddle_tpu.data.datasets as _datasets  # noqa: E402

    _sys.modules[f"{__name__}.dataset"] = _datasets
    dataset = _datasets
    for _dn in getattr(_datasets, "__all__", []):
        try:
            _dm = __import__(f"paddle_tpu.data.datasets.{_dn}", fromlist=[_dn])
            _sys.modules[f"{__name__}.dataset.{_dn}"] = _dm
        except Exception:
            pass
except ImportError:
    pass

init = __import__("paddle").init
