"""Capture a jax.profiler trace + compiled cost analysis of the ResNet-50
bench step on the real chip, and emit a top-op time table (PROFILE_r03.md).

Usage:  python benchmarks/profile_resnet.py [--batch 256] [--image 224]
Outputs: profiles/rN/ (xplane trace) + markdown table on stdout.

The op table is parsed from the xplane.pb protobuf with tensorflow's profiler
protos (tensorflow is present in the image for exactly this kind of tooling).
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_step(batch_size: int, image_size: int):
    import jax

    from paddle_tpu.core import dtypes
    from paddle_tpu import models
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    dtypes.set_policy(dtypes.bf16_policy())
    reset_name_scope()
    img, label, logits, cost = models.resnet50(image_size=image_size)
    trainer = SGDTrainer(cost, SGD(learning_rate=0.1, momentum=0.9))
    rs = np.random.RandomState(0)
    batch = {
        "image": rs.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "label": rs.randint(0, 1000, batch_size),
    }
    trainer.init_state(batch)
    step = jax.jit(trainer._build_step(), donate_argnums=0)
    batch = jax.device_put(batch)
    return trainer, step, batch


def parse_xplane(trace_dir: str, n_steps: int = 3):
    """Aggregate device time by HLO category and by source line from the
    xplane dump (proto mirror compiled from benchmarks/xplane.proto — the
    image has no tensorboard profiler plugin)."""
    import xplane_pb2  # generated next to this file

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        return None, "no xplane.pb found under " + trace_dir
    xs = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xs.ParseFromString(f.read())

    planes = [p for p in xs.planes if p.name.startswith("/device:TPU")]
    if not planes:
        return None, "no TPU plane in trace"
    plane = planes[0]
    md = plane.event_metadata
    sm = {k: v.name for k, v in plane.stat_metadata.items()}

    def meta_stats(mid):
        m = md.get(mid)
        out = {}
        if m is None:
            return out
        for s in m.stats:
            out[sm.get(s.metadata_id)] = (
                s.uint64_value or s.int64_value or s.double_value or s.str_value
            )
        return out

    by_cat = defaultdict(lambda: [0.0, 0.0, 0.0])  # ps, flops, bytes
    by_src = defaultdict(lambda: [0.0, 0.0, 0.0])
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            ms = meta_stats(ev.metadata_id)
            cat = str(ms.get("hlo_category", "?"))
            fl = float(ms.get("flops") or 0)
            by = float(ms.get("bytes_accessed") or 0)
            src = str(ms.get("source", "-"))
            for table, key in ((by_cat, cat), (by_src, src)):
                table[key][0] += ev.duration_ps
                table[key][1] += fl
                table[key][2] += by
    return (by_cat, by_src, n_steps), None


def fmt_tables(by_cat, by_src, n_steps: int, top: int = 15) -> str:
    lines = ["| HLO category | ms/step | TFLOP/s | GB/s | % time |", "|---|---|---|---|---|"]
    total = sum(v[0] for v in by_cat.values())
    for cat, (ps, fl, by) in sorted(by_cat.items(), key=lambda kv: -kv[1][0])[:top]:
        sec = ps / 1e12
        if sec <= 0:
            continue
        lines.append(
            f"| {cat} | {ps / 1e9 / n_steps:.2f} | {fl / sec / 1e12:.1f} "
            f"| {by / sec / 1e9:.0f} | {100 * ps / total:.1f} |"
        )
    lines.append("")
    lines.append("| source line | ms/step | TFLOP/s | GB/s |")
    lines.append("|---|---|---|---|")
    for src, (ps, fl, by) in sorted(by_src.items(), key=lambda kv: -kv[1][0])[:top]:
        sec = ps / 1e12
        if sec <= 0:
            continue
        lines.append(
            f"| {src} | {ps / 1e9 / n_steps:.2f} | {fl / sec / 1e12:.1f} "
            f"| {by / sec / 1e9:.0f} |"
        )
    lines.append("")
    lines.append(f"device busy: {total / 1e9 / n_steps:.2f} ms/step")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--out", default="profiles/r03")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} platform={dev.platform}", flush=True)

    trainer, step, batch = build_step(args.batch, args.image)
    state = trainer.state

    t0 = time.perf_counter()
    state, cost, _ = step(state, batch)
    cost_v = float(cost)
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s cost={cost_v:.3f}", flush=True)

    # steady-state timing
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, cost, _ = step(state, batch)
    final = float(cost)
    dt = (time.perf_counter() - t0) / args.steps
    print(f"steady: {dt * 1000:.1f} ms/step  {args.batch / dt:.0f} img/s  cost={final:.3f}", flush=True)

    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        for _ in range(3):
            state, cost, _ = step(state, batch)
        jax.block_until_ready(cost)
        float(cost)

    (res, err) = parse_xplane(args.out)
    if res is None:
        print("xplane parse failed:", err)
        return
    by_cat, by_src, n_steps = res
    print()
    print(fmt_tables(by_cat, by_src, n_steps))


if __name__ == "__main__":
    main()
