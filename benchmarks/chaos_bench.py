"""Chaos benchmark: training throughput under injected faults, plus a
multi-process cluster failover scenario.

--mode local (default) measures steps/sec for the same toy workload three
ways — clean, under an input-side fault mix (flaky feeder + slowed H2D), and
with periodic NaN batches absorbed by the skip_batch divergence guard — all
through the seeded injector in paddle_tpu/core/faults.py, so a run is
reproducible bit-for-bit. The interesting number is the ratio: how much
throughput the fault-tolerance machinery (retries, guard sync, watchdog)
costs when faults actually happen, and (via --faults "") what the guard
alone costs when they never do.

--mode cluster spawns a REAL master process that dies to the seeded
`master_kill` fault mid-pass, a warm-standby process that takes over from
the shared snapshot, and N consumer threads failing over through their
endpoint list — and reports the wall-clock cost of the failover plus the
exactly-once bookkeeping (done == ntasks, discarded == 0, replayed records).

Usage:
  JAX_PLATFORMS=cpu python benchmarks/chaos_bench.py [--mode local|cluster]
      [--faults SPEC] [--seed N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_FAULTS = "feeder_raise:0.05,h2d_delay:2ms"


def build_trainer(args, policy=None):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(args.dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, args.hidden, act="relu")
    logits = L.Fc(h, args.classes, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(
        cost, SGD(learning_rate=0.01), seed=0, divergence_policy=policy
    )


def run_mode(args, spec: str, policy=None) -> dict:
    """steps/sec over the timed (second) pass; first pass compiles."""
    import numpy as np

    from paddle_tpu.core import faults, stats
    from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
    from paddle_tpu.data.pipeline import DevicePrefetcher
    from paddle_tpu.trainer import EndPass

    rs = np.random.RandomState(0)
    raws = [
        [
            (rs.randn(args.dim).astype(np.float32), int(i % args.classes))
            for i in range(args.batch_size)
        ]
        for _ in range(args.batches)
    ]
    feeder = DataFeeder(
        {"x": dense_vector(args.dim), "label": integer_value(args.classes)}
    )
    reader = DevicePrefetcher(
        lambda: iter(raws), feeder, prefetch_depth=2, feed_retries=3
    )
    trainer = build_trainer(args, policy=policy)
    pass_stats = []
    stats.FT_EVENTS.reset()
    with faults.inject(spec, seed=args.seed) as inj:
        trainer.train(
            reader, num_passes=2, feeder=feeder,
            event_handler=lambda e: pass_stats.append(e.metrics)
            if isinstance(e, EndPass) else None,
        )
        fired = dict(inj.fired)
    m = pass_stats[-1]
    return {
        "steps_per_sec": round(m["batches"] / m["pass_seconds"], 2),
        "faults_fired": fired,
        "divergence_events": m["divergence_events"],
        "ft_events": stats.FT_EVENTS.as_dict(),
    }


def run_cluster(args) -> dict:
    """Kill-the-master failover drill with real OS processes (see module
    docstring); returns the JSON-able result dict."""
    import shutil
    import socket
    import subprocess
    import tempfile
    import threading

    from paddle_tpu.core import stats
    from paddle_tpu.runtime import recordio
    from paddle_tpu.runtime.master import (
        KILLED_EXIT, MasterClient, cluster_reader, standby_master,
    )

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    tmp = tempfile.mkdtemp(prefix="chaos_cluster_")
    nrec = args.cluster_tasks * args.records_per_task
    standby_holder = {}
    primary = None
    try:
        shards = recordio.convert(
            os.path.join(tmp, "ds"),
            lambda: ({"sid": i} for i in range(nrec)),
            records_per_file=args.records_per_task,
        )
        p1, p2 = free_port(), free_port()
        snap = os.path.join(tmp, "m.snap")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = os.pathsep.join(
            [sys.path[0]] + env.get("PYTHONPATH", "").split(os.pathsep)
        ).strip(os.pathsep)
        primary = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.runtime.master", "serve",
             "--port", str(p1), "--snapshot", snap, "--lease_s", "2",
             "--timeout_s", "30", "--failure_max", "10",
             "--faults", f"master_kill:step={args.kill_rpc}",
             "--faults_seed", str(args.seed)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", p1), 0.5).close()
                break
            except OSError:
                time.sleep(0.1)
        boot = MasterClient(("127.0.0.1", p1))
        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        boot.close()

        def run_standby():
            standby_holder["srv"] = standby_master(
                ("127.0.0.1", p1), port=p2, snapshot_path=snap,
                poll_s=0.1, max_wait_s=120, lease_s=2.0,
            )

        threading.Thread(target=run_standby, daemon=True).start()

        endpoints = [("127.0.0.1", p1), ("127.0.0.1", p2)]
        consumed = [[] for _ in range(args.consumers)]
        stats.FT_EVENTS.reset()

        def consume(i):
            reader = cluster_reader(
                endpoints, client_kw={"retries": 40, "timeout": 5}
            )
            for s in reader():
                consumed[i].append(s["sid"])
                time.sleep(args.work_ms / 1e3)

        threads = [
            threading.Thread(target=consume, args=(i,))
            for i in range(args.consumers)
        ]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        elapsed = time.time() - t0
        primary.wait(timeout=10)
        srv = standby_holder.get("srv")
        st = {}
        if srv is not None:
            post = MasterClient(("127.0.0.1", p2))
            st = post.call("stats")
            post.close()
        flat = [x for c in consumed for x in c]
        return {
            "metric": "cluster_failover_wall_s",
            "value": round(elapsed, 3),
            "unit": "s",
            "tasks": args.cluster_tasks,
            "records": nrec,
            "consumers": args.consumers,
            "primary_exit": primary.returncode,
            "primary_killed_by_chaos": primary.returncode == KILLED_EXIT,
            "standby_takeover": srv is not None,
            "done": st.get("done"),
            "discarded": st.get("discarded"),
            "exactly_once_tasks": (
                st.get("done") == args.cluster_tasks
                and st.get("discarded") == 0
            ),
            "records_delivered": len(flat),
            "records_replayed": len(flat) - len(set(flat)),
            "coverage_complete": set(flat) == set(range(nrec)),
            "ft_events": stats.FT_EVENTS.as_dict(),
            "seed": args.seed,
        }
    finally:
        if primary is not None and primary.poll() is None:
            primary.kill()
        srv = standby_holder.get("srv")
        if srv is not None:
            srv.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="local", choices=["local", "cluster"],
                    help="local: in-process throughput-under-faults; "
                         "cluster: multi-process master-failover drill")
    ap.add_argument("--faults", default=DEFAULT_FAULTS,
                    help="input-side fault mix for the chaos mode")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cluster_tasks", type=int, default=16)
    ap.add_argument("--records_per_task", type=int, default=4)
    ap.add_argument("--consumers", type=int, default=2)
    ap.add_argument("--work_ms", type=float, default=10.0,
                    help="per-record consumer work, keeps the pass alive "
                         "long enough for the kill to land mid-pass")
    ap.add_argument("--kill_rpc", type=int, default=9,
                    help="cluster mode: the RPC hit on which master_kill "
                         "fires (seeded, deterministic)")
    ap.add_argument("--batches", type=int, default=50)
    ap.add_argument("--batch_size", type=int, default=256)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--nan_every", type=int, default=10,
                    help="guard mode poisons every Nth batch (via probability "
                         "1/N) to exercise skip_batch under load")
    args = ap.parse_args()

    if args.mode == "cluster":
        print(json.dumps(run_cluster(args)))
        return

    import jax

    clean = run_mode(args, spec="")
    chaos = run_mode(args, spec=args.faults)
    guard = run_mode(
        args, spec=f"nan_loss:{1.0 / args.nan_every}", policy="skip_batch"
    )
    print(json.dumps({
        "metric": "chaos_throughput_retention",
        "value": round(chaos["steps_per_sec"] / clean["steps_per_sec"], 3),
        "unit": "x",
        "clean": clean,
        "input_faults": {"spec": args.faults, **chaos},
        "nan_guard": {"spec": f"nan_loss:{1.0 / args.nan_every}", **guard},
        "seed": args.seed,
        "batches": args.batches,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
