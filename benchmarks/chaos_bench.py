"""Chaos benchmark: training throughput under injected faults.

Measures steps/sec for the same toy workload three ways — clean, under an
input-side fault mix (flaky feeder + slowed H2D), and with periodic NaN
batches absorbed by the skip_batch divergence guard — all through the seeded
injector in paddle_tpu/core/faults.py, so a run is reproducible bit-for-bit.
The interesting number is the ratio: how much throughput the fault-tolerance
machinery (retries, guard sync, watchdog) costs when faults actually happen,
and (via --faults "") what the guard alone costs when they never do.

Usage:
  JAX_PLATFORMS=cpu python benchmarks/chaos_bench.py [--faults SPEC] [--seed N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_FAULTS = "feeder_raise:0.05,h2d_delay:2ms"


def build_trainer(args, policy=None):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(args.dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, args.hidden, act="relu")
    logits = L.Fc(h, args.classes, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(
        cost, SGD(learning_rate=0.01), seed=0, divergence_policy=policy
    )


def run_mode(args, spec: str, policy=None) -> dict:
    """steps/sec over the timed (second) pass; first pass compiles."""
    import numpy as np

    from paddle_tpu.core import faults, stats
    from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
    from paddle_tpu.data.pipeline import DevicePrefetcher
    from paddle_tpu.trainer import EndPass

    rs = np.random.RandomState(0)
    raws = [
        [
            (rs.randn(args.dim).astype(np.float32), int(i % args.classes))
            for i in range(args.batch_size)
        ]
        for _ in range(args.batches)
    ]
    feeder = DataFeeder(
        {"x": dense_vector(args.dim), "label": integer_value(args.classes)}
    )
    reader = DevicePrefetcher(
        lambda: iter(raws), feeder, prefetch_depth=2, feed_retries=3
    )
    trainer = build_trainer(args, policy=policy)
    pass_stats = []
    stats.FT_EVENTS.reset()
    with faults.inject(spec, seed=args.seed) as inj:
        trainer.train(
            reader, num_passes=2, feeder=feeder,
            event_handler=lambda e: pass_stats.append(e.metrics)
            if isinstance(e, EndPass) else None,
        )
        fired = dict(inj.fired)
    m = pass_stats[-1]
    return {
        "steps_per_sec": round(m["batches"] / m["pass_seconds"], 2),
        "faults_fired": fired,
        "divergence_events": m["divergence_events"],
        "ft_events": stats.FT_EVENTS.as_dict(),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--faults", default=DEFAULT_FAULTS,
                    help="input-side fault mix for the chaos mode")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batches", type=int, default=50)
    ap.add_argument("--batch_size", type=int, default=256)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--nan_every", type=int, default=10,
                    help="guard mode poisons every Nth batch (via probability "
                         "1/N) to exercise skip_batch under load")
    args = ap.parse_args()

    import jax

    clean = run_mode(args, spec="")
    chaos = run_mode(args, spec=args.faults)
    guard = run_mode(
        args, spec=f"nan_loss:{1.0 / args.nan_every}", policy="skip_batch"
    )
    print(json.dumps({
        "metric": "chaos_throughput_retention",
        "value": round(chaos["steps_per_sec"] / clean["steps_per_sec"], 3),
        "unit": "x",
        "clean": clean,
        "input_faults": {"spec": args.faults, **chaos},
        "nan_guard": {"spec": f"nan_loss:{1.0 / args.nan_every}", **guard},
        "seed": args.seed,
        "batches": args.batches,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
