"""Chaos benchmark: training throughput under injected faults, a
multi-process cluster failover scenario, a live elastic-resize drill, and a
serving resilience drill.

--mode local (default) measures steps/sec for the same toy workload three
ways — clean, under an input-side fault mix (flaky feeder + slowed H2D), and
with periodic NaN batches absorbed by the skip_batch divergence guard — all
through the seeded injector in paddle_tpu/core/faults.py, so a run is
reproducible bit-for-bit. The interesting number is the ratio: how much
throughput the fault-tolerance machinery (retries, guard sync, watchdog)
costs when faults actually happen, and (via --faults "") what the guard
alone costs when they never do.

--mode cluster spawns a REAL master process that dies to the seeded
`master_kill` fault mid-pass, a warm-standby process that takes over from
the shared snapshot, and N consumer threads failing over through their
endpoint list — and reports the wall-clock cost of the failover plus the
exactly-once bookkeeping (done == ntasks, discarded == 0, replayed records).

--mode resize (ISSUE 8) drills live elastic resize on a forced-host-device
CPU mesh:
  * grow: one pass trained on a 2-chip data axis that re-shards to 4 chips
    mid-pass and finishes there — the pass average must match the fixed-size
    run, and the drain / re-shard / resume latency split is reported;
  * shrink: the same 4 -> 2;
  * reshard_kill: the seeded fault kills the trainer mid-re-shard (after the
    drain checkpoint); a fresh trainer at the TARGET world auto-resumes from
    the drained boundary and must land bitwise on the uninterrupted resized
    run's params;
  * drain-barrier kill: a real master + N cluster_reader consumers; a resize
    epoch is announced mid-pass and one consumer wedges inside the barrier
    (`resize_drain_stall`) until the master's DRAIN TIMEOUT drops it from
    the barrier (its daemon heartbeat thread keeps the lease alive, so lease
    eviction alone can never catch it) — the epoch must still complete and
    task accounting stays exactly-once (done == ntasks, discarded == 0, full
    record coverage).

--mode serving (ISSUE 10) drills the serving resilience layer on the demo
LM, every leg carrying its own "platform" tag:
  * crash legs: the engine is killed mid-decode under sustained mixed-tenant
    load — once per seeded fault site (decode_raise, engine_stall,
    page_exhaust). Gates per leg: every accepted request finishes or fails
    with a NAMED reason, the KV free list is whole afterwards (zero page
    leaks), and the supervisor restarted the engine (>= 1 restart, counter
    exported via the obs plane);
  * overload leg: capacity is measured closed-loop, then an open-loop pass
    offers 1× and 2× that rate with per-request deadlines armed — the gate
    is goodput (completed-within-deadline/s) at 2× within 20% of the
    at-capacity run, i.e. load-aware shedding keeps goodput flat instead of
    letting the queue drag every request past its deadline;
  * sampling-replay leg (ISSUE 11): the decode_raise crash drill repeated
    with on-device sampling armed (temperature 0.8, top_k 20) — the gate is
    the faulted run's tokens BITWISE-equal to an unfaulted run's, proving
    the per-request seed + token-step key makes crash replay
    result-transparent beyond greedy.

--mode router (ISSUE 15) drills the multi-replica router tier: 3 demo
replicas behind the router under open-loop mixed-tenant load (half greedy,
half seeded-sampled), one replica killed mid-decode, one wedged between
steps past its lease and then healed. Gates: every accepted request ends
with a named reason, exactly-once delivery across failover (the healed
replica's late answers are dropped + counted by the fleet dedup map), zero
KV page leaks on surviving replicas, goodput retention >= 0.7 vs the
unfaulted 3-replica run, and failover re-execution token-bitwise for both
greedy and sampled streams (the router pins every request's seed).

--mode autoscale (ISSUE 17) drills the goodput-driven autoscaler: a real
router + replica fleet and a real TCP master share a fixed chip budget, and
an idle → 2× burst → idle offered-load schedule (calibrated to one
replica's measured capacity) is replayed twice over identical arrivals —
once against a static provision-for-peak fleet, once against the minimum
fleet plus the controller, which must spawn replicas into the burst
(reclaiming chips from training via resize epochs when none are free) and
drain + lend chips back when idle. The controller is KILLED by the seeded
`controller_kill` fault mid-resize-epoch and a cold restart must reconcile
from observed state. Gates: burst-phase goodput retention >= 0.8 vs static,
idle-phase serving chips >= 30% below static, zero lost requests in both
runs, exactly-once task accounting across every triggered resize epoch, no
epoch left open, the kill landed mid-epoch, and the restarted controller
went on to make decisions.

--mode fleet (ISSUE 20) drills the binary batched control plane: a
simulated 100+-trainer fleet (threads, real wire connections, no data
plane) drains the same task ledger over the legacy line-JSON
get_task/task_finished pair and over framed bulk get_tasks leases with
piggybacked done-acks. Reports tasks/sec, time-to-drain, round trips and
bytes per task; gates exactly-once delivery in both legs and a >= 3x
round-trip reduction for the framed leg.

Usage:
  JAX_PLATFORMS=cpu python benchmarks/chaos_bench.py
      [--mode local|cluster|resize|serving|router|autoscale|ha|fleet]
      [--faults SPEC] [--seed N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_FAULTS = "feeder_raise:0.05,h2d_delay:2ms"


def build_trainer(args, policy=None):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(args.dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, args.hidden, act="relu")
    logits = L.Fc(h, args.classes, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(
        cost, SGD(learning_rate=0.01), seed=0, divergence_policy=policy
    )


def run_mode(args, spec: str, policy=None) -> dict:
    """steps/sec over the timed (second) pass; first pass compiles."""
    import numpy as np

    from paddle_tpu.core import faults, stats
    from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
    from paddle_tpu.data.pipeline import DevicePrefetcher
    from paddle_tpu.trainer import EndPass

    rs = np.random.RandomState(0)
    raws = [
        [
            (rs.randn(args.dim).astype(np.float32), int(i % args.classes))
            for i in range(args.batch_size)
        ]
        for _ in range(args.batches)
    ]
    feeder = DataFeeder(
        {"x": dense_vector(args.dim), "label": integer_value(args.classes)}
    )
    reader = DevicePrefetcher(
        lambda: iter(raws), feeder, prefetch_depth=2, feed_retries=3
    )
    trainer = build_trainer(args, policy=policy)
    pass_stats = []
    stats.FT_EVENTS.reset()
    with faults.inject(spec, seed=args.seed) as inj:
        trainer.train(
            reader, num_passes=2, feeder=feeder,
            event_handler=lambda e: pass_stats.append(e.metrics)
            if isinstance(e, EndPass) else None,
        )
        fired = dict(inj.fired)
    m = pass_stats[-1]
    return {
        "steps_per_sec": round(m["batches"] / m["pass_seconds"], 2),
        "faults_fired": fired,
        "divergence_events": m["divergence_events"],
        "ft_events": stats.FT_EVENTS.as_dict(),
    }


def run_cluster(args) -> dict:
    """Kill-the-master failover drill with real OS processes (see module
    docstring); returns the JSON-able result dict."""
    import shutil
    import socket
    import subprocess
    import tempfile
    import threading

    from paddle_tpu.core import stats
    from paddle_tpu.runtime import recordio
    from paddle_tpu.runtime.master import (
        KILLED_EXIT, MasterClient, cluster_reader, standby_master,
    )

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    tmp = tempfile.mkdtemp(prefix="chaos_cluster_")
    nrec = args.cluster_tasks * args.records_per_task
    standby_holder = {}
    primary = None
    try:
        shards = recordio.convert(
            os.path.join(tmp, "ds"),
            lambda: ({"sid": i} for i in range(nrec)),
            records_per_file=args.records_per_task,
        )
        p1, p2 = free_port(), free_port()
        snap = os.path.join(tmp, "m.snap")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = os.pathsep.join(
            [sys.path[0]] + env.get("PYTHONPATH", "").split(os.pathsep)
        ).strip(os.pathsep)
        primary = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.runtime.master", "serve",
             "--port", str(p1), "--snapshot", snap, "--lease_s", "2",
             "--timeout_s", "30", "--failure_max", "10",
             "--faults", f"master_kill:step={args.kill_rpc}",
             "--faults_seed", str(args.seed)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", p1), 0.5).close()
                break
            except OSError:
                time.sleep(0.1)
        boot = MasterClient(("127.0.0.1", p1))
        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        boot.close()

        def run_standby():
            standby_holder["srv"] = standby_master(
                ("127.0.0.1", p1), port=p2, snapshot_path=snap,
                poll_s=0.1, max_wait_s=120, lease_s=2.0,
            )

        threading.Thread(target=run_standby, daemon=True).start()

        endpoints = [("127.0.0.1", p1), ("127.0.0.1", p2)]
        consumed = [[] for _ in range(args.consumers)]
        stats.FT_EVENTS.reset()

        def consume(i):
            reader = cluster_reader(
                endpoints, client_kw={"retries": 40, "timeout": 5}
            )
            for s in reader():
                consumed[i].append(s["sid"])
                time.sleep(args.work_ms / 1e3)

        threads = [
            threading.Thread(target=consume, args=(i,))
            for i in range(args.consumers)
        ]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        elapsed = time.time() - t0
        primary.wait(timeout=10)
        srv = standby_holder.get("srv")
        st = {}
        if srv is not None:
            post = MasterClient(("127.0.0.1", p2))
            st = post.call("stats")
            post.close()
        flat = [x for c in consumed for x in c]
        return {
            "metric": "cluster_failover_wall_s",
            "value": round(elapsed, 3),
            "unit": "s",
            "tasks": args.cluster_tasks,
            "records": nrec,
            "consumers": args.consumers,
            "primary_exit": primary.returncode,
            "primary_killed_by_chaos": primary.returncode == KILLED_EXIT,
            "standby_takeover": srv is not None,
            "done": st.get("done"),
            "discarded": st.get("discarded"),
            "exactly_once_tasks": (
                st.get("done") == args.cluster_tasks
                and st.get("discarded") == 0
            ),
            "records_delivered": len(flat),
            "records_replayed": len(flat) - len(set(flat)),
            "coverage_complete": set(flat) == set(range(nrec)),
            "ft_events": stats.FT_EVENTS.as_dict(),
            "seed": args.seed,
        }
    finally:
        if primary is not None and primary.poll() is None:
            primary.kill()
        srv = standby_holder.get("srv")
        if srv is not None:
            srv.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def run_fleet(args) -> dict:
    """Control-plane scaling drill (ISSUE 20): a simulated 100+-trainer
    fleet — every trainer a thread speaking the real wire protocol to ONE
    in-process master, no data plane — drains the same task ledger twice:

      * legacy leg: line-JSON wire, the classic get_task + task_finished
        pair (2 round trips per task, plus retry polls at the drain tail);
      * framed leg: binary frames, bulk `get_tasks` range leases with the
        previous batch's done-acks piggybacked on the next lease request
        (~1 round trip per lease_batch tasks).

    Reported per leg: tasks/sec, time-to-drain, round trips and wire bytes
    per task (client-side counters). Gates: exactly-once delivery in BOTH
    legs (every task seen once across the whole fleet) and the framed leg
    >= 3x fewer round trips per task."""
    import threading

    from paddle_tpu.runtime.master import (
        MasterClient, MasterServer, TaskMaster,
    )

    ntasks = args.fleet_tasks
    shards = [f"shard-{i:05d}" for i in range(ntasks)]

    def leg(wire: str) -> dict:
        server = MasterServer(
            TaskMaster(timeout_s=300.0, failure_max=10), lease_s=60.0,
        ).start()
        results = [None] * args.fleet_trainers
        try:
            boot = MasterClient(server.address)
            boot.call("set_dataset", shards=shards, chunks_per_task=1)
            boot.close()

            def worker(i: int) -> None:
                c = MasterClient(server.address, wire=wire)
                tid = c.call("register")["trainer_id"]
                got = []
                if wire == "frames":
                    pending = []  # done-acks deferred onto the next lease
                    while True:
                        resp = c.call(
                            "get_tasks", n=args.fleet_lease_batch,
                            done_ids=pending, trainer_id=tid,
                        )
                        pending = []
                        if resp.get("pass_finished"):
                            break
                        tasks = resp.get("tasks", [])
                        for t in tasks:
                            got.append(int(t["task_id"]))
                            pending.append(int(t["task_id"]))
                        if not tasks:  # drain tail: others still own tasks
                            time.sleep(0.002)
                else:
                    while True:
                        resp = c.call("get_task", trainer_id=tid)
                        if resp.get("pass_finished"):
                            break
                        if resp.get("retry"):
                            time.sleep(0.002)
                            continue
                        got.append(int(resp["task_id"]))
                        c.call("task_finished", task_id=resp["task_id"],
                               trainer_id=tid)
                results[i] = {
                    "tasks": got,
                    "round_trips": c.round_trips,
                    "bytes": c.bytes_sent + c.bytes_received,
                }
                c.close()

            threads = [
                threading.Thread(target=worker, args=(i,), daemon=True)
                for i in range(args.fleet_trainers)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            drain_s = time.perf_counter() - t0
        finally:
            server.stop()

        delivered = [tid for r in results if r for tid in r["tasks"]]
        rts = sum(r["round_trips"] for r in results if r)
        nbytes = sum(r["bytes"] for r in results if r)
        return {
            "wire": wire,
            "trainers": args.fleet_trainers,
            "tasks": ntasks,
            "tasks_per_sec": round(ntasks / drain_s, 1),
            "time_to_drain_s": round(drain_s, 3),
            "round_trips_per_task": round(rts / ntasks, 3),
            "bytes_per_task": round(nbytes / ntasks, 1),
            "exactly_once": (
                len(delivered) == ntasks
                and len(set(delivered)) == ntasks
            ),
        }

    legacy = leg("json")
    framed = leg("frames")
    reduction = (
        legacy["round_trips_per_task"] / framed["round_trips_per_task"]
    )
    return {
        "metric": "control_plane_tasks_per_sec",
        "value": framed["tasks_per_sec"],
        "unit": "tasks/s",
        "platform": "cpu-threads",
        "legacy": legacy,
        "framed": framed,
        "round_trip_reduction": round(reduction, 2),
        "gates": {
            "exactly_once_both_legs": (
                legacy["exactly_once"] and framed["exactly_once"]
            ),
            "round_trip_reduction_3x": reduction >= 3.0,
        },
        "lease_batch": args.fleet_lease_batch,
        "seed": args.seed,
    }


def _build_resize_trainer(args, world, shard):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.parallel import DataParallel, make_mesh
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(args.dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, args.hidden, act="relu", name="h")
    logits = L.Fc(h, args.classes, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")
    dp = DataParallel(make_mesh({"data": world}))
    # power-of-two lr: scale products are FMA-proof, so the bitwise gates
    # below test the resize seam, not XLA contraction luck
    return SGDTrainer(
        cost, SGD(learning_rate=0.125, momentum=0.5), parallel=dp, seed=5,
        shard_update=shard,
    )


def run_resize(args) -> dict:
    """Live elastic-resize drill (see module docstring). Every leg is seeded
    and in-process except the drain-barrier kill, which runs a real TCP
    master with cluster_reader consumer threads."""
    import numpy as np

    import jax

    from paddle_tpu.core import faults, preempt, stats
    from paddle_tpu.trainer.events import EndIteration, EndPass

    ndev = len(jax.devices())
    need = max(args.resize_from, args.resize_to_world)
    if ndev < need:
        return {
            "metric": "resize_epoch_total_s", "value": None,
            "error": f"need {need} devices, host has {ndev} "
                     "(set --force_devices before jax imports)",
        }
    backend = jax.default_backend()
    rs = np.random.RandomState(args.seed)
    xs = rs.randn(args.batches * args.batch_size, args.dim).astype(np.float32)
    ys = (rs.rand(len(xs)) * args.classes).astype(np.int32)

    def reader():
        for i in range(0, len(xs), args.batch_size):
            yield {"x": xs[i:i + args.batch_size], "label": ys[i:i + args.batch_size]}

    def run(world, target=None, spec="", save_dir=None, auto_resume=False,
            shard=False):
        preempt.reset()
        tr = _build_resize_trainer(args, world, shard)
        metrics, killed = [], False

        def handler(ev):
            if (
                target is not None
                and isinstance(ev, EndIteration)
                and (ev.pass_id, ev.batch_id) == (0, args.resize_at)
            ):
                preempt.get().request_resize(target, reason="bench resize")
            if isinstance(ev, EndPass):
                metrics.append(ev.metrics)

        with faults.inject(spec, seed=args.seed):
            try:
                tr.train(
                    reader, num_passes=1, event_handler=handler,
                    save_dir=save_dir, auto_resume=auto_resume,
                    log_period=10_000,
                )
            except faults.InjectedKill:
                killed = True
        preempt.reset()
        return tr, metrics, killed

    def params(tr):
        return {k: np.asarray(v) for k, v in tr.state["params"].items()}

    def rel_close(a, b, tol=1e-5):
        return abs(a - b) <= tol * max(abs(a), abs(b), 1e-12)

    def leg(world_from, world_to):
        t0 = time.time()
        fixed, m_fixed, _ = run(world_from)
        resized, m_rz, _ = run(world_from, target=world_to)
        split = (m_rz[0].get("resizes") or [{}])[0]
        return {
            "from": world_from, "to": world_to,
            "platform": backend,
            "fixed_avg_cost": m_fixed[0]["avg_cost"],
            "resized_avg_cost": m_rz[0]["avg_cost"],
            "pass_avg_match": rel_close(
                m_fixed[0]["avg_cost"], m_rz[0]["avg_cost"]
            ),
            "resize_epochs": m_rz[0].get("resize_epochs", 0),
            "drain_s": split.get("drain_s"),
            "reshard_s": split.get("reshard_s"),
            "resume_s": split.get("resume_s"),
            "wall_s": round(time.time() - t0, 3),
        }

    grow = leg(args.resize_from, args.resize_to_world)
    shrink = leg(args.resize_to_world, args.resize_from)

    # -- reshard_kill: death mid-re-shard, auto-resume on the NEW world ------
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="chaos_resize_")
    try:
        oracle, m_o, _ = run(args.resize_from, target=args.resize_to_world)
        _, _, killed = run(
            args.resize_from, target=args.resize_to_world,
            spec="reshard_kill:step=0", save_dir=tmp,
        )
        resumed, m_r, _ = run(
            args.resize_to_world, save_dir=tmp, auto_resume=True,
        )
        p_o, p_r = params(oracle), params(resumed)
        bitwise = all(
            np.array_equal(p_o[k].view(np.uint32), p_r[k].view(np.uint32))
            for k in p_o
        )
        reshard_kill = {
            "killed_mid_reshard": killed,
            "resume_bitwise_vs_uninterrupted": bitwise,
            "platform": backend,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    fleet = run_resize_fleet(args)

    ok = (
        grow["pass_avg_match"] and shrink["pass_avg_match"]
        # a silently-no-op resize would make pass_avg_match vacuously true:
        # each leg must have completed exactly one real epoch
        and grow["resize_epochs"] == 1 and shrink["resize_epochs"] == 1
        and reshard_kill["killed_mid_reshard"]
        and reshard_kill["resume_bitwise_vs_uninterrupted"]
        and fleet.get("exactly_once_tasks") and fleet.get("epoch_completed")
        and fleet.get("barrier_exercised")
    )
    return {
        "metric": "resize_epoch_total_s",
        "value": grow["drain_s"] + grow["reshard_s"] + grow["resume_s"]
        if grow["drain_s"] is not None else None,
        "unit": "s",
        "platform": backend,
        "all_gates_pass": bool(ok),
        "grow": grow,
        "shrink": shrink,
        "reshard_kill": reshard_kill,
        "drain_barrier_kill": fleet,
        "seed": args.seed,
    }


def run_resize_fleet(args) -> dict:
    """Drain-barrier-kill drill: real TCP master + cluster_reader consumer
    threads; a resize epoch lands mid-pass and one consumer wedges inside
    the barrier until the drain TIMEOUT drops it (its heartbeat thread keeps
    the lease alive, so lease eviction alone cannot catch it). Gates: the
    epoch completes, the wedged consumer is timed out of the barrier (and
    rejoins after waking), and task accounting is exactly-once."""
    import shutil
    import tempfile
    import threading

    from paddle_tpu.core import faults, stats
    from paddle_tpu.runtime import recordio
    from paddle_tpu.runtime.master import (
        MasterClient, MasterServer, TaskMaster, cluster_reader,
    )

    os.environ["PADDLE_TPU_RESIZE_STALL_S"] = str(args.stall_s)
    tmp = tempfile.mkdtemp(prefix="chaos_resize_fleet_")
    nrec = args.cluster_tasks * args.records_per_task
    srv = None
    try:
        shards = recordio.convert(
            os.path.join(tmp, "ds"),
            lambda: ({"sid": i} for i in range(nrec)),
            records_per_file=args.records_per_task,
        )
        srv = MasterServer(
            TaskMaster(timeout_s=30.0, failure_max=10), lease_s=1.5,
            resize_drain_timeout_s=args.drain_timeout_s,
        ).start()
        endpoint = srv.address
        boot = MasterClient(endpoint)
        boot.call("set_dataset", shards=shards, chunks_per_task=1)

        consumed = [[] for _ in range(args.consumers)]
        stats.FT_EVENTS.reset()

        def consume(i):
            rd = cluster_reader(
                endpoint, client_kw={"retries": 40, "timeout": 5},
                poll_interval=0.05,
            )
            for s in rd():
                consumed[i].append(s["sid"])
                # slower than --mode cluster on purpose: the pass must
                # outlive a heartbeat period (lease/3) so every consumer
                # SEES the piggybacked drain signal mid-pass — otherwise
                # the drill degenerates to deregister-empties-the-barrier
                time.sleep(args.fleet_work_ms / 1e3)

        threads = [
            threading.Thread(target=consume, args=(i,), daemon=True)
            for i in range(args.consumers)
        ]
        t0 = time.time()
        with faults.inject("resize_drain_stall:step=0", seed=args.seed) as inj:
            for t in threads:
                t.start()
            # announce the epoch once every consumer holds a lease
            deadline = time.time() + 30
            while time.time() < deadline:
                if boot.call("stats").get("live_leases", 0) >= args.consumers:
                    break
                time.sleep(0.05)
            ann = boot.call("resize", world=args.resize_to_world)
            # the epoch must complete despite the wedged consumer
            info = ann
            deadline = time.time() + 60
            while time.time() < deadline and info.get("state") != "idle":
                time.sleep(0.1)
                info = boot.call("stats")["resize"]
            for t in threads:
                t.join(timeout=120)
            stalled = inj.fired.get("resize_drain_stall", 0)
        elapsed = time.time() - t0
        st = boot.call("stats")
        boot.close()
        flat = [x for c in consumed for x in c]
        drains = stats.FT_EVENTS.get("reader_resize_drain")
        return {
            # the drill is only meaningful when the barrier was really
            # exercised: one consumer wedged in it, at least one other
            # drained through it, and the wedged one was removed (barrier
            # timeout — its heartbeat thread keeps the lease alive, so
            # lease eviction alone cannot catch it)
            "barrier_exercised": (
                stalled >= 1 and drains >= 2
                and (info.get("last", {}).get("timed_out") or 0)
                + (info.get("last", {}).get("evicted_during") or 0) >= 1
            ),
            "stall_fired": stalled,
            "reader_drains": drains,
            "platform": "host",
            "consumers": args.consumers,
            "tasks": args.cluster_tasks,
            "records": nrec,
            "epoch_completed": info.get("state") == "idle"
            and info.get("completed", 0) >= 1,
            "evicted_during_epoch": info.get("last", {}).get("evicted_during"),
            "barrier_timed_out": info.get("last", {}).get("timed_out"),
            "barrier_drain_s": info.get("last", {}).get("drain_s"),
            "epoch_total_s": info.get("last", {}).get("total_s"),
            "done": st.get("done"),
            "discarded": st.get("discarded"),
            "exactly_once_tasks": (
                st.get("done") == args.cluster_tasks
                and st.get("discarded") == 0
            ),
            "records_delivered": len(flat),
            "records_replayed": len(flat) - len(set(flat)),
            "coverage_complete": set(flat) == set(range(nrec)),
            "wall_s": round(elapsed, 3),
            "ft_events": stats.FT_EVENTS.as_dict(),
            "seed": args.seed,
        }
    finally:
        if srv is not None:
            srv.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _serving_session(args, **kw):
    from paddle_tpu.serving.session import make_demo_session

    return make_demo_session(
        vocab=128, n_layers=2, d_model=32, n_heads=2, seed=0,
        max_slots=args.serving_slots, page_size=8, prefill_buckets=(8, 16),
        max_new_limit=args.serving_max_new, **kw,
    )


def _named_reasons() -> frozenset:
    """Every finish reason the scheduler can emit — derived from the one
    naming authority (serving.scheduler.FinishReason) so the drill's
    'all accounted with a NAMED reason' gate cannot drift from the code."""
    from paddle_tpu.serving.scheduler import FinishReason

    return frozenset(
        v for k, v in vars(FinishReason).items()
        if not k.startswith("_") and isinstance(v, str)
    )


def serving_crash_leg(args, site: str, spec: str, backend: str) -> dict:
    """One engine-kill drill: sustained mixed-tenant load, the seeded fault
    fires mid-run, the supervisor must recover, and afterwards every
    accepted request is accounted for with a named reason and the page free
    list is whole."""
    import time as _time

    from paddle_tpu.core import faults
    from paddle_tpu.serving.workload import make_prompts

    s = _serving_session(
        args, engine_stall_timeout_s=args.serving_stall_timeout_s,
        engine_restart_max=5,
    )
    total_free = s.cache.num_pages - 1
    prompts = make_prompts(
        args.serving_requests, lengths=(5, 8, 11, 16), vocab=128, bos_id=1,
        seed=args.seed,
    )
    handles, rejected = [], 0
    s.serve_forever()
    t0 = _time.time()
    with faults.inject(spec, seed=args.seed) as inj:
        for i, p in enumerate(prompts):
            try:
                handles.append(s.submit(
                    p, args.serving_max_new, tenant=f"tenant{i % 3}",
                    deadline_s=60.0,
                ))
            except Exception:
                rejected += 1
            # sustained load: arrivals spread across the run so the fault
            # lands mid-stream, not before or after the burst
            _time.sleep(args.serving_submit_gap_ms / 1e3)
        deadline = _time.time() + 120
        for h in handles:
            h._event.wait(max(0.1, deadline - _time.time()))
        fired = dict(inj.fired)
    s.stop()
    wall = _time.time() - t0
    all_done = all(h.done for h in handles)
    named_set = _named_reasons()
    named = all(h.finish_reason in named_set for h in handles if h.done)
    leaked = total_free - s.cache.free_pages
    reasons = {}
    for h in handles:
        reasons[h.finish_reason] = reasons.get(h.finish_reason, 0) + 1
    return {
        "site": site,
        "spec": spec,
        "platform": backend,
        "fault_fired": fired.get(site, 0),
        "engine_restarts": s.engine_restarts,
        "accepted": len(handles),
        "rejected_at_submit": rejected,
        "finish_reasons": reasons,
        "all_accounted_with_named_reason": bool(all_done and named),
        "leaked_pages": leaked,
        "zero_page_leak": leaked == 0,
        "wall_s": round(wall, 3),
        "all_gates_pass": bool(
            all_done and named and leaked == 0
            and s.engine_restarts >= 1 and fired.get(site, 0) >= 1
        ),
    }


def serving_sampling_replay_leg(args, backend: str) -> dict:
    """ISSUE 11: SAMPLED decode (temperature/top-k through per-request
    seeded keys) must stay result-transparent across an engine crash — the
    supervisor's replay reuses each request's seed and token step indices,
    so the faulted run's tokens are BITWISE-equal to an unfaulted run's."""
    import time as _time

    from paddle_tpu.core import faults
    from paddle_tpu.serving.workload import make_prompts

    prompts = make_prompts(
        args.serving_requests, lengths=(5, 8, 11, 16), vocab=128, bos_id=1,
        seed=args.seed,
    )

    def run(spec):
        s = _serving_session(
            args, engine_stall_timeout_s=args.serving_stall_timeout_s,
            engine_restart_max=5,
        )
        handles = []
        s.serve_forever()
        inj_cm = faults.inject(spec, seed=args.seed) if spec else None
        try:
            if inj_cm is not None:
                inj = inj_cm.__enter__()
            for i, p in enumerate(prompts):
                # per-request seeds default from the request id: both runs
                # submit in the same order, so seeds match across runs
                handles.append(s.submit(
                    p, args.serving_max_new, tenant=f"tenant{i % 3}",
                    deadline_s=120.0, temperature=0.8, top_k=20,
                ))
                _time.sleep(args.serving_submit_gap_ms / 1e3)
            deadline = _time.time() + 120
            for h in handles:
                h._event.wait(max(0.1, deadline - _time.time()))
            fired = dict(inj.fired) if inj_cm is not None else {}
        finally:
            if inj_cm is not None:
                inj_cm.__exit__(None, None, None)
        s.stop()
        return ([h.tokens for h in handles],
                [h.finish_reason for h in handles], fired, s.engine_restarts)

    clean_toks, _, _, _ = run(None)
    spec = f"decode_raise:step={args.serving_kill_step}"
    fault_toks, reasons, fired, restarts = run(spec)
    named = _named_reasons()
    bitwise = clean_toks == fault_toks
    return {
        "spec": spec,
        "platform": backend,
        "temperature": 0.8,
        "top_k": 20,
        "fault_fired": fired.get("decode_raise", 0),
        "engine_restarts": restarts,
        "sampled_replay_bitwise_equal": bool(bitwise),
        "all_named": all(r in named for r in reasons),
        "all_gates_pass": bool(
            bitwise and restarts >= 1 and fired.get("decode_raise", 0) >= 1
            and all(r in named for r in reasons)
        ),
    }


def serving_spec_replay_leg(args, backend: str) -> dict:
    """ISSUE 16: SPECULATIVE decode must stay result-transparent across an
    engine crash — the fault fires mid-speculation (the `decode_raise` site
    inside `_speculate`), the supervisor replays, and because drafting is a
    pure function of each request's committed tokens and acceptance samples
    through the same (seed, emitted-token-index) keys, the faulted run's
    SAMPLED tokens are bitwise-equal to an unfaulted speculative run's.
    Repetitive prompts make the drafter actually fire (gated: a leg where
    speculation never ran proves nothing)."""
    import time as _time

    from paddle_tpu.core import faults

    # self-similar prompts: the prompt-lookup drafter needs n-gram repeats
    rng = __import__("numpy").random.RandomState(args.seed)
    prompts = []
    for i in range(args.serving_requests):
        motif = [int(t) for t in rng.randint(3, 128, size=3)]
        prompts.append(([1] + motif * 4)[: 5 + (i % 4) * 3])

    def run(spec):
        s = _serving_session(
            args, engine_stall_timeout_s=args.serving_stall_timeout_s,
            engine_restart_max=5, speculate_k=args.serving_speculate_k,
        )
        handles = []
        s.serve_forever()
        inj_cm = faults.inject(spec, seed=args.seed) if spec else None
        try:
            if inj_cm is not None:
                inj = inj_cm.__enter__()
            for i, p in enumerate(prompts):
                handles.append(s.submit(
                    p, args.serving_max_new, tenant=f"tenant{i % 3}",
                    deadline_s=120.0, temperature=0.8, top_k=20,
                ))
                _time.sleep(args.serving_submit_gap_ms / 1e3)
            deadline = _time.time() + 120
            for h in handles:
                h._event.wait(max(0.1, deadline - _time.time()))
            fired = dict(inj.fired) if inj_cm is not None else {}
        finally:
            if inj_cm is not None:
                inj_cm.__exit__(None, None, None)
        st = s.stats()
        s.stop()
        return ([h.tokens for h in handles],
                [h.finish_reason for h in handles], fired,
                s.engine_restarts, st)

    clean_toks, _, _, _, clean_st = run(None)
    spec = f"decode_raise:step={args.serving_kill_step}"
    fault_toks, reasons, fired, restarts, fault_st = run(spec)
    named = _named_reasons()
    bitwise = clean_toks == fault_toks
    spec_ran = (clean_st["spec_rounds"] >= 1
                and fault_st["spec_rounds"] >= 1)
    return {
        "spec": spec,
        "platform": backend,
        "temperature": 0.8,
        "top_k": 20,
        "speculate_k": args.serving_speculate_k,
        "fault_fired": fired.get("decode_raise", 0),
        "engine_restarts": restarts,
        "spec_rounds": fault_st["spec_rounds"],
        "spec_acceptance_rate": fault_st["spec_acceptance_rate"],
        "speculation_exercised": bool(spec_ran),
        "spec_replay_bitwise_equal": bool(bitwise),
        "all_named": all(r in named for r in reasons),
        "all_gates_pass": bool(
            bitwise and spec_ran and restarts >= 1
            and fired.get("decode_raise", 0) >= 1
            and all(r in named for r in reasons)
        ),
    }


def serving_overload_leg(args, backend: str) -> dict:
    """Capacity closed-loop, then open-loop at 1× and 2× capacity with
    deadlines armed: the goodput-retention gate (2× within 20% of the
    capacity run) is exactly the 'degrades gracefully instead of
    collapsing' claim."""
    from paddle_tpu.serving.workload import (
        make_prompts, run_closed_loop, run_open_loop,
    )

    lengths = (5, 8, 11)

    def fresh():
        s = _serving_session(args)
        # round 1 warms every executable; its per-request times include the
        # jit compiles (seconds), but the session resets the poisoned EWMA
        # itself at the first clean post-compile step (ISSUE 17) — round 2
        # then re-seeds it with steady-state (millisecond) service times
        warm = make_prompts(4, lengths=(8, 16), vocab=128, bos_id=1, seed=9)
        run_closed_loop(s, warm, args.serving_max_new,
                        concurrency=args.serving_slots)
        seed_round = make_prompts(8, lengths=lengths, vocab=128, bos_id=1,
                                  seed=10)
        run_closed_loop(s, seed_round, args.serving_max_new,
                        concurrency=args.serving_slots)
        return s

    s = fresh()
    cap_prompts = make_prompts(
        args.serving_requests, lengths=lengths, vocab=128, bos_id=1,
        seed=args.seed,
    )
    cap = run_closed_loop(
        s, cap_prompts, args.serving_max_new, concurrency=args.serving_slots
    )
    cap.pop("results", None)
    capacity_rps = cap["requests"] / cap["wall_s"]
    # deadline budget: a few service times — generous enough that the
    # at-capacity run meets it, tight enough that an unbounded queue at 2×
    # would drag every request past it
    svc_s = cap["wall_s"] * args.serving_slots / cap["requests"]
    deadline_s = (args.serving_deadline_s
                  or max(0.05, args.serving_deadline_svc_mult * svc_s))

    def open_leg(mult):
        sess = fresh()
        n = max(8, int(capacity_rps * mult * args.serving_overload_s))
        prompts = make_prompts(
            n, lengths=lengths, vocab=128, bos_id=1, seed=args.seed + 1,
        )
        leg = run_open_loop(
            sess, prompts, args.serving_max_new,
            rate_rps=capacity_rps * mult,
            tenants=("tenant0", "tenant1", "tenant2"),
            deadline_s=deadline_s,
        )
        leg["platform"] = backend
        leg["stats"] = {
            k: v for k, v in sess.stats().items()
            if k in ("shed", "deadline_misses", "completed",
                     "pages_recycled_on_cancel", "free_pages")
        }
        return leg

    at_capacity = open_leg(1.0)
    at_2x = open_leg(2.0)
    ratio = (at_2x["goodput_rps"] / at_capacity["goodput_rps"]
             if at_capacity["goodput_rps"] else 0.0)
    return {
        "platform": backend,
        "capacity_closed_loop": dict(cap, platform=backend),
        "capacity_rps": round(capacity_rps, 2),
        "deadline_s": round(deadline_s, 4),
        "at_capacity": at_capacity,
        "at_2x": at_2x,
        "goodput_retention_2x": round(ratio, 3),
        "goodput_within_20pct": bool(ratio >= 0.8),
    }


def run_router(args) -> dict:
    """Router-fleet resilience drill (ISSUE 15): 3 replicas behind the
    router under open-loop mixed-tenant load (half the requests greedy,
    half seeded-sampled), one replica KILLED mid-decode, one WEDGED past
    its lease (the deterministic between-steps wedge: the engine parks on
    the session's generation lock — the process-global fault injector would
    stall all three in-process replicas at once — then heals so its stale
    answers become LATE WINNERS for the dedup map). Gates:

      * every accepted request finishes or fails with a NAMED reason;
      * exactly-once across failover: zero duplicate deliveries and the
        late-winner counter >= 1 (the fleet dedup actually exercised);
      * zero KV page leaks on every SURVIVING replica;
      * goodput retention >= 0.7 vs the unfaulted 3-replica run;
      * failover re-execution token-BITWISE vs the unfaulted run for both
        greedy and seeded-sampled streams (the router pins every request's
        seed, so re-execution is result-transparent on any replica)."""
    import threading
    import time as _time

    import jax

    from paddle_tpu.serving.quota import QuotaExceeded
    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.server import ServingServer
    from paddle_tpu.serving.workload import make_prompts

    backend = jax.default_backend()
    n_rep = 3
    n_req = args.router_requests
    prompts = make_prompts(
        n_req, lengths=(5, 8, 11, 16), vocab=128, bos_id=1, seed=args.seed,
    )
    # mixed sampling: odd indices draw through explicit per-index seeds so
    # the bitwise gate covers sampled failover too (seeds must be submission
    # -content-stable, not allocation-order-stable — shed patterns differ
    # between runs)
    sampling = [
        (dict(temperature=0.8, top_k=20, seed=1000 + i) if i % 2 else {})
        for i in range(n_req)
    ]

    def run(faulted: bool) -> dict:
        router = RouterServer(
            lease_s=args.router_lease_s, poll_interval_s=0.01,
            late_grace_s=30.0,
        ).start()
        servers = []
        for _ in range(n_rep):
            sess = _serving_session(
                args, engine_stall_timeout_s=300.0, engine_restart_max=5,
            )
            srv = ServingServer(
                session=sess, router_endpoints=router.address,
                stall_fence_s=args.router_stall_fence_s,
            ).start()
            servers.append((srv, sess))
        deadline = _time.time() + 30
        while _time.time() < deadline and len(router.fleet.live()) < n_rep:
            _time.sleep(0.02)
        r = router.router
        handles, shed = {}, 0
        kill_at = n_req // 4
        wedge_at = n_req // 2
        wedge_lock = None
        wedge_release_timer = None
        t0 = _time.time()
        for i, p in enumerate(prompts):
            if faulted and i == kill_at:
                servers[0][0].kill()  # killed mid-decode, never comes back
            if faulted and i == wedge_at:
                # wedge replica 1 BETWEEN steps past its lease; heal after
                # router_wedge_s so its stale answers become late winners
                wedge_lock = servers[1][1]._gen_lock
                wedge_lock.acquire()
                wedge_release_timer = threading.Timer(
                    args.router_wedge_s, wedge_lock.release
                )
                wedge_release_timer.start()
            try:
                handles[i] = r.submit(
                    p, args.serving_max_new, tenant=f"tenant{i % 3}",
                    deadline_s=60.0, **sampling[i],
                )
            except QuotaExceeded:
                shed += 1
            _time.sleep(args.router_submit_gap_ms / 1e3)
        done_deadline = _time.time() + 180
        for h in handles.values():
            h._event.wait(max(0.1, done_deadline - _time.time()))
        wall = _time.time() - t0
        if wedge_release_timer is not None:
            wedge_release_timer.join()
        # let the healed replica finish its stale copies (the late winners)
        # and the pumps observe them before reading counters / page books
        survivors = servers[1:] if faulted else servers
        drain_deadline = _time.time() + 60
        while _time.time() < drain_deadline and any(
            s.scheduler.has_work() for _, s in survivors
        ):
            _time.sleep(0.05)
        if faulted:
            deadline = _time.time() + 20
            while _time.time() < deadline and r.late_results_dropped < 1:
                _time.sleep(0.05)
        completed = {
            i: list(h.tokens) for i, h in handles.items()
            if h.done and h.status == h.DONE
        }
        named = _named_reasons()
        reasons = {}
        for h in handles.values():
            reasons[h.finish_reason] = reasons.get(h.finish_reason, 0) + 1
        all_accounted = all(h.done for h in handles.values()) and all(
            h.finish_reason in named for h in handles.values()
        )
        leaks = {}
        for idx, (_, sess) in enumerate(servers):
            if faulted and idx == 0:
                continue  # the killed replica is dead, not leaking
            leaks[idx] = sess.cache.pages_in_use
        out = {
            "accepted": len(handles),
            "shed": shed,
            "completed_ok": len(completed),
            "finish_reasons": reasons,
            "all_accounted_with_named_reason": bool(all_accounted),
            "goodput_rps": round(len(completed) / wall, 2) if wall else 0.0,
            "wall_s": round(wall, 3),
            "failovers": r.failovers,
            "hedges": r.hedges,
            "late_results_dropped": r.late_results_dropped,
            "replica_evictions": r.replica_evictions,
            "leaked_pages_by_survivor": leaks,
            "zero_page_leak": all(v == 0 for v in leaks.values()),
            "platform": backend,
            "_tokens": completed,
        }
        for srv, _ in servers:
            (srv.kill if faulted and srv is servers[0][0] else srv.stop)()
        router.stop()
        return out

    clean = run(faulted=False)
    faulted = run(faulted=True)
    clean_toks = clean.pop("_tokens")
    fault_toks = faulted.pop("_tokens")
    # bitwise: every request the faulted run completed must carry the same
    # tokens the unfaulted run produced — greedy AND sampled indices
    mismatches = [
        i for i, t in fault_toks.items()
        if i in clean_toks and t != clean_toks[i]
    ]
    greedy_checked = sum(1 for i in fault_toks if i % 2 == 0)
    sampled_checked = sum(1 for i in fault_toks if i % 2 == 1)
    retention = (
        faulted["goodput_rps"] / clean["goodput_rps"]
        if clean["goodput_rps"] else 0.0
    )
    ok = (
        clean["all_accounted_with_named_reason"]
        and faulted["all_accounted_with_named_reason"]
        and faulted["failovers"] >= 1
        and faulted["replica_evictions"] >= 2  # the kill AND the wedge
        and faulted["late_results_dropped"] >= 1  # dedup exercised
        and faulted["zero_page_leak"] and clean["zero_page_leak"]
        and not mismatches
        and greedy_checked >= 1 and sampled_checked >= 1
        and retention >= 0.7
    )
    return {
        "metric": "router_goodput_retention",
        "value": round(retention, 3),
        "unit": "x goodput under kill+wedge vs unfaulted 3-replica run",
        "platform": backend,
        "all_gates_pass": bool(ok),
        "gates": {
            "all_accounted_named": bool(
                faulted["all_accounted_with_named_reason"]
            ),
            "failover_exercised": faulted["failovers"] >= 1,
            "both_faults_evicted": faulted["replica_evictions"] >= 2,
            "dedup_late_winner_dropped": faulted["late_results_dropped"] >= 1,
            "zero_duplicate_results": True,  # structural: the dedup latch
            # delivers each fleet request exactly once; late winners above
            "zero_page_leak_survivors": faulted["zero_page_leak"],
            "token_bitwise_vs_unfaulted": not mismatches,
            "greedy_streams_checked": greedy_checked,
            "sampled_streams_checked": sampled_checked,
            "goodput_retention_ge_0p7": bool(retention >= 0.7),
        },
        "clean": clean,
        "faulted": faulted,
        "seed": args.seed,
    }


def run_autoscale(args) -> dict:
    """Autoscaler drill (ISSUE 17): the goodput-driven controller steering a
    REAL fleet — router + in-process replicas on the serving side, a real
    TCP master + cluster_reader consumers on the training side — through an
    idle → 2× burst → idle offered-load schedule, with the controller
    KILLED (seeded `controller_kill`) mid-resize-epoch and a fresh one
    started cold to reconcile from observed state.

    Two runs over the IDENTICAL arrival schedule (workload.expand_schedule):

      * static: max_replicas always on, no controller — the
        provision-for-peak baseline;
      * autoscaled: min_replicas + the controller, which must spawn into
        the burst (borrowing chips back from training via resize epochs
        when none are free) and drain + lend chips to training when idle.

    Gates: burst-phase goodput retention >= 0.8 vs static; mean serving
    chips across the idle phases >= 30% below static; zero lost requests
    (every accepted request ends with a named reason, both runs);
    exactly-once task accounting across every triggered resize epoch
    (done == ntasks, discarded == 0, no epoch left open); the kill landed
    mid-epoch and the restarted controller went on to act."""
    import shutil
    import tempfile
    import threading
    import time as _time

    import jax

    from paddle_tpu.core import faults
    from paddle_tpu.runtime import recordio
    from paddle_tpu.runtime.autoscaler import (
        AutoscalerController, ScaleConfig,
    )
    from paddle_tpu.runtime.master import (
        MasterClient, MasterServer, TaskMaster, cluster_reader,
    )
    from paddle_tpu.serving.quota import QuotaExceeded
    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.server import ServingServer
    from paddle_tpu.serving.workload import (
        expand_schedule, make_prompts, run_closed_loop,
    )

    backend = jax.default_backend()
    max_rep = args.autoscale_max_replicas
    init_world = args.autoscale_train_world
    max_new = args.autoscale_max_new
    lengths = (5, 8, 11)

    def warmed_session():
        # a heavier demo model than the other serving drills: more tokens
        # per request makes one replica's capacity a few tens of rps, so
        # the calibrated burst is a rate a Python submit loop can actually
        # sustain and queue waits move on human-scale thresholds
        from paddle_tpu.serving.session import make_demo_session

        s = make_demo_session(
            vocab=128, n_layers=4, d_model=64, n_heads=4, seed=0,
            max_slots=args.serving_slots, page_size=8,
            prefill_buckets=(8, 16), max_new_limit=max_new,
        )
        # two warm waves: the first pays every jit trace, and the SECOND
        # re-seeds the service-time EWMA with clean post-compile samples —
        # the session's auto-reset (ISSUE 17) fires at the first clean
        # step, but wave-1 requests completing after it still carry their
        # compile stalls, so without wave 2 the wait estimator's floor
        # would sit seconds high and the router would shed everything
        warm = make_prompts(4, lengths=(8, 16), vocab=128, bos_id=1, seed=9)
        run_closed_loop(s, warm, max_new, concurrency=args.serving_slots)
        meas = make_prompts(16, lengths=lengths, vocab=128, bos_id=1,
                            seed=11)
        run_closed_loop(s, meas, max_new, concurrency=args.serving_slots)
        return s

    # calibrate the schedule to THIS host: one replica's closed-loop
    # capacity prices the burst (2x one replica: the static max fleet can
    # absorb it, the autoscaled min fleet cannot — until it scales)
    cap_sess = warmed_session()
    cap = run_closed_loop(
        cap_sess,
        make_prompts(args.serving_requests, lengths=lengths, vocab=128,
                     bos_id=1, seed=args.seed),
        max_new, concurrency=args.serving_slots,
    )
    cap_rps = (cap["requests"] / cap["wall_s"]) if cap["wall_s"] else 10.0
    svc_s = max(1e-3, cap["p50_latency_ms"] / 1e3)
    # the wait estimator never reads zero: an empty queue still prices one
    # EWMA service time (the request's own decode).  Measure that floor on
    # the drained calibration session and put the controller's low band
    # ABOVE it, or scale-down can never fire.
    idle_floor_s = float(cap_sess.scheduler.estimate_wait_s())
    deadline_s = max(1.5, args.serving_deadline_svc_mult * svc_s)
    low_wait_s = max(3.0 * svc_s, 2.5 * idle_floor_s)
    high_wait_s = max(6.0 * svc_s, 5.0 * idle_floor_s, 0.4 * deadline_s,
                      2.0 * low_wait_s)
    burst_rate = args.autoscale_burst_mult * cap_rps
    burst_s = min(args.autoscale_burst_s,
                  max(2.0, args.autoscale_burst_cap / burst_rate))
    idle_rate = max(1.0, 0.05 * cap_rps)
    schedule = [
        (args.autoscale_idle_s, idle_rate),
        (burst_s, burst_rate),
        (args.autoscale_tail_s, idle_rate),
    ]
    total_s = sum(d for d, _ in schedule)
    arrivals = expand_schedule(10 ** 6, schedule)
    prompts = make_prompts(len(arrivals), lengths=lengths, vocab=128,
                           bos_id=1, seed=args.seed)
    # the idle-phase windows the chips gate integrates over
    idle_windows = [
        (0.0, args.autoscale_idle_s),
        (total_s - args.autoscale_tail_s, total_s),
    ]

    def drive(r) -> dict:
        """Replay the arrival schedule against the router; per-phase
        accounting keyed by each request's ARRIVAL phase."""
        handles, hphase = {}, {}
        shed_by_phase = {}
        t0 = _time.time()
        for idx, (off, ph) in enumerate(arrivals):
            now = _time.time()
            if t0 + off > now:
                _time.sleep(t0 + off - now)
            try:
                handles[idx] = r.submit(
                    prompts[idx], max_new,
                    tenant=f"tenant{idx % 3}", deadline_s=deadline_s,
                )
                hphase[idx] = ph
            except QuotaExceeded:
                shed_by_phase[ph] = shed_by_phase.get(ph, 0) + 1
        done_deadline = _time.time() + 120
        for h in handles.values():
            h._event.wait(max(0.1, done_deadline - _time.time()))
        wall = _time.time() - t0
        named = _named_reasons()
        all_accounted = all(h.done for h in handles.values()) and all(
            h.finish_reason in named for h in handles.values()
        )
        phases = []
        for p, (dur, rate) in enumerate(schedule):
            idxs = [i for i, ph in hphase.items() if ph == p]
            ok = sum(
                1 for i in idxs if handles[i].status == handles[i].DONE
            )
            phases.append({
                "phase": p, "duration_s": round(dur, 2),
                "rate_rps": round(rate, 2),
                "offered": sum(1 for _, ph in arrivals if ph == p),
                "accepted": len(idxs),
                "shed": shed_by_phase.get(p, 0),
                "completed_ok": ok,
                "goodput_rps": round(ok / dur, 2) if dur else 0.0,
            })
        return {
            "accepted": len(handles),
            "shed": sum(shed_by_phase.values()),
            "completed_ok": sum(
                1 for h in handles.values() if h.status == h.DONE
            ),
            "all_accounted_with_named_reason": bool(all_accounted),
            "phases": phases,
            "wall_s": round(wall, 3),
        }

    def sampler(router_srv, msrv, samples, stop_evt, t0):
        """Chip-ledger sampling: serving chips = live + draining replicas
        (a draining replica still holds its chip); training chips = the
        resize plane's world."""
        while not stop_evt.wait(0.15):
            reps = router_srv.router.fleet.replicas()
            serving = sum(
                1 for rep in reps if rep.state in ("live", "draining")
            )
            world = (
                msrv.resize.info()["world"] if msrv is not None
                else init_world
            )
            samples.append((_time.time() - t0, serving, world))

    def idle_mean_chips(samples, col) -> float:
        vals = [
            s[col] for s in samples
            if any(lo <= s[0] <= hi for lo, hi in idle_windows)
        ]
        return (sum(vals) / len(vals)) if vals else 0.0

    def run_static() -> dict:
        router = RouterServer(lease_s=1.0, poll_interval_s=0.01).start()
        servers = [
            ServingServer(
                session=(cap_sess if i == 0 else warmed_session()),
                router_endpoints=router.address,
            ).start()
            for i in range(max_rep)
        ]
        deadline = _time.time() + 30
        while _time.time() < deadline and len(router.fleet.live()) < max_rep:
            _time.sleep(0.02)
        samples, stop_evt = [], threading.Event()
        smp = threading.Thread(
            target=sampler,
            args=(router, None, samples, stop_evt, _time.time()),
            daemon=True,
        )
        smp.start()
        out = drive(router.router)
        stop_evt.set()
        smp.join(timeout=5)
        for srv in servers:
            srv.stop()
        router.stop()
        out["idle_serving_chips_mean"] = round(
            idle_mean_chips(samples, 1), 3
        )
        return out

    def run_autoscaled() -> dict:
        tmp = tempfile.mkdtemp(prefix="chaos_autoscale_")
        nrec = args.autoscale_tasks * args.records_per_task
        msrv = router = None
        boot = None
        controllers = []
        try:
            # training plane: real master (resize epochs) + consumers that
            # drain through every epoch's barrier mid-pass
            shards = recordio.convert(
                os.path.join(tmp, "ds"),
                lambda: ({"sid": i} for i in range(nrec)),
                records_per_file=args.records_per_task,
            )
            msrv = MasterServer(
                TaskMaster(timeout_s=30.0, failure_max=10), lease_s=1.5,
                resize_drain_timeout_s=6.0, initial_world=init_world,
            ).start()
            boot = MasterClient(msrv.address)
            boot.call("set_dataset", shards=shards, chunks_per_task=1)
            consumed = [[] for _ in range(args.consumers)]
            # size the per-record work so the training pass outlives the
            # whole load schedule — otherwise the consumers finish before
            # the controller's first resize and every drain barrier is
            # trivially empty (nobody left to drain through it)
            work_s = max(args.autoscale_work_ms / 1e3,
                         (total_s + 8.0) * args.consumers / nrec)

            def consume(i):
                rd = cluster_reader(
                    msrv.address, client_kw={"retries": 40, "timeout": 5},
                    poll_interval=0.05,
                )
                for rec in rd():
                    consumed[i].append(rec["sid"])
                    _time.sleep(work_s)

            consumers = [
                threading.Thread(target=consume, args=(i,), daemon=True)
                for i in range(args.consumers)
            ]

            # serving plane: router + ONE live replica; the spawn lever
            # draws warmed sessions from a pool through the spawner seam
            # (the subprocess ReplicaSpawner's in-process stand-in)
            router = RouterServer(lease_s=1.0, poll_interval_s=0.01).start()
            # all-fresh sessions: cap_sess was consumed by the static run
            # (ServingServer.stop() retires its engine)
            pool = [warmed_session() for _ in range(max_rep + 1)]
            servers = []

            class _InProcSpawner:
                def __init__(self):
                    self.spawned = 0
                    self.exhausted = 0

                def spawn(self):
                    if not pool:
                        self.exhausted += 1
                        return None
                    self.spawned += 1
                    sess = pool.pop(0)
                    srv = ServingServer(
                        session=sess, router_endpoints=router.address,
                    )
                    # drained replica exits and releases its chip (the
                    # --exit_on_drain lifecycle, in-process: stop off the
                    # agent thread, which fires this callback)
                    srv.on_drained = lambda srv=srv: threading.Thread(
                        target=srv.stop, daemon=True
                    ).start()
                    srv.start()
                    servers.append(srv)
                    return srv

                def reap(self):
                    return len(servers)

                def stop_all(self):
                    pass  # the drill stops servers itself

            spawner = _InProcSpawner()
            spawner.spawn()  # the min fleet
            deadline = _time.time() + 30
            while _time.time() < deadline and len(router.fleet.live()) < 1:
                _time.sleep(0.02)
            # consumers start only now — AFTER the (slow) pool warm-up —
            # so the training pass overlaps the controller's lifetime
            for t in consumers:
                t.start()

            cfg = ScaleConfig(
                chips_total=args.autoscale_chips, chips_per_replica=1,
                min_replicas=1, max_replicas=max_rep,
                train_min_world=1,
                train_max_world=args.autoscale_train_max_world,
                high_wait_s=high_wait_s, low_wait_s=low_wait_s,
                high_ticks=2, low_ticks=5,
                serving_cooldown_s=0.8, train_cooldown_s=1.0,
                flap_window_s=1.5, startup_quiet_s=0.4,
                backoff_base_s=0.5, backoff_max_s=8.0,
                resize_timeout_s=30.0, drain_deadline_s=8.0,
            )

            def build_ctl():
                return AutoscalerController(
                    router_endpoints=router.address,
                    master_endpoints=msrv.address,
                    config=cfg, spawner=spawner,
                    tick_s=args.autoscale_tick_s,
                )

            ctl = build_ctl().start()
            controllers.append(ctl)
            kill_info = {}

            def killer():
                # wait for a resize epoch to be IN FLIGHT, then fire the
                # seeded controller_kill at the top of the next tick —
                # death lands mid-epoch; a cold controller takes over
                deadline = _time.time() + total_s
                while _time.time() < deadline:
                    if msrv.resize.info()["state"] != "idle":
                        break
                    _time.sleep(0.02)
                else:
                    kill_info["no_epoch_started"] = True
                    return
                kill_info["epoch_state_at_kill"] = (
                    msrv.resize.info()["state"]
                )
                faults.ACTIVE.configure("controller_kill:step=0", args.seed)
                wait = _time.time() + 15
                while not ctl.dead and _time.time() < wait:
                    _time.sleep(0.02)
                faults.ACTIVE.configure("")
                kill_info["killed"] = bool(ctl.dead)
                ctl2 = build_ctl().start()
                controllers.append(ctl2)

            kt = threading.Thread(target=killer, daemon=True)
            kt.start()
            samples, stop_evt = [], threading.Event()
            smp = threading.Thread(
                target=sampler,
                args=(router, msrv, samples, stop_evt, _time.time()),
                daemon=True,
            )
            smp.start()
            out = drive(router.router)
            stop_evt.set()
            smp.join(timeout=5)
            kt.join(timeout=5)
            for c in controllers:
                c.stop()
            for t in consumers:
                t.join(timeout=120)
            st = boot.call("stats")
            rz = msrv.resize.info()
            flat = [x for c in consumed for x in c]
            out.update({
                "idle_serving_chips_mean": round(
                    idle_mean_chips(samples, 1), 3
                ),
                "max_serving_chips": max((s[1] for s in samples), default=0),
                "max_train_world": max((s[2] for s in samples), default=0),
                "spawner": {
                    "spawned": spawner.spawned,
                    "pool_exhausted": spawner.exhausted,
                },
                "controllers": [c.stats() for c in controllers],
                "kill": kill_info,
                "router": {
                    k: v for k, v in router.router.stats().items()
                    if k != "replicas"
                },
                "master": {
                    "done": st.get("done"),
                    "discarded": st.get("discarded"),
                    "resize_completed": rz.get("completed", 0),
                    "resize_state": rz.get("state"),
                    "final_world": rz.get("world"),
                    "records_delivered": len(flat),
                    "records_replayed": len(flat) - len(set(flat)),
                    "coverage_complete": set(flat) == set(range(nrec)),
                },
            })
            for srv in servers:
                srv.stop()
            return out
        finally:
            faults.ACTIVE.configure("")
            for c in controllers:
                c.stop()
            if boot is not None:
                boot.close()
            if router is not None:
                router.stop()
            if msrv is not None:
                msrv.stop()
            shutil.rmtree(tmp, ignore_errors=True)

    static = run_static()
    auto = run_autoscaled()

    def burst_goodput(run):
        return run["phases"][1]["goodput_rps"]

    retention = (
        burst_goodput(auto) / burst_goodput(static)
        if burst_goodput(static) else 0.0
    )
    reduction = (
        1.0 - auto["idle_serving_chips_mean"]
        / static["idle_serving_chips_mean"]
        if static["idle_serving_chips_mean"] else 0.0
    )
    m = auto["master"]
    exactly_once = (
        m["done"] == args.autoscale_tasks and m["discarded"] == 0
        and m["coverage_complete"]
    )
    kill = auto["kill"]
    gates = {
        "burst_goodput_retention_ge_0p8": retention >= 0.8,
        "idle_chips_reduction_ge_0p3": reduction >= 0.3,
        "zero_lost_requests": bool(
            static["all_accounted_with_named_reason"]
            and auto["all_accounted_with_named_reason"]
        ),
        "exactly_once_tasks": bool(exactly_once),
        "no_epoch_left_open": (
            m["resize_state"] == "idle" and m["resize_completed"] >= 1
        ),
        "controller_killed_mid_epoch": bool(
            kill.get("killed")
            and kill.get("epoch_state_at_kill") in ("draining", "go")
        ),
        "restarted_controller_acted": (
            len(auto["controllers"]) == 2
            and auto["controllers"][1]["decisions"] >= 1
        ),
        "scaled_up_into_burst": auto["max_serving_chips"] >= 2,
        "chips_lent_to_training": auto["max_train_world"] > init_world,
    }
    return {
        "metric": "autoscale_burst_goodput_retention",
        "value": round(retention, 3),
        "unit": "x burst-phase goodput, autoscaled-from-min vs static-max "
                "fleet (controller killed+restarted mid-epoch)",
        "platform": backend,
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "idle_chips_reduction": round(reduction, 3),
        "calibration": {
            "one_replica_capacity_rps": round(cap_rps, 2),
            "svc_p50_s": round(svc_s, 4),
            "idle_floor_s": round(idle_floor_s, 4),
            "low_wait_s": round(low_wait_s, 4),
            "high_wait_s": round(high_wait_s, 4),
            "deadline_s": round(deadline_s, 3),
            "schedule": [
                [round(d, 2), round(r, 2)] for d, r in schedule
            ],
        },
        "static": static,
        "autoscaled": auto,
        "seed": args.seed,
    }


def run_ha(args) -> dict:
    """Control-plane HA drill (ISSUE 18), two legs:

    Router leg — 2 replicas carry [primary, standby] endpoint lists; mixed
    greedy + seeded-sampled requests AND one live push-stream are wedged
    in flight (the deterministic between-steps wedge) when the primary
    router is KILLED. The armed RouterStandby must confirm the death, bind,
    sweep the re-registering replicas' `outstanding` books, and finish
    everything. Gates: zero client errors; every request's tokens BITWISE
    identical to an unfaulted run over the same prompts/seeds (exactly-once
    falls out: equal length + equal content admits no duplicate delivery);
    >= 1 cursor reattach on the stream; exactly one router takeover in
    FT_EVENTS; the sweep adopted >= 1 request; zero KV pages leaked on
    either (surviving) replica in both runs.

    Autoscaler leg — a REAL master + cluster_reader consumers on the
    training plane; the serving side is a scripted stats source holding
    queue wait above the scale-up band plus a counting spawner (the real-
    fleet version of this pressure loop is `--mode autoscale`; this leg
    isolates the HA mechanics). The primary controller borrows a chip from
    training (resize epoch), is KILLED mid-epoch (seeded controller_kill),
    and the AutoscalerStandby watching its liveness port must take over
    with a fresh controller that reconciles from observed state and
    completes the scale-up. Gates: the kill landed mid-epoch; exactly one
    autoscaler takeover; the standby's controller acted (second spawn);
    every training record consumed exactly once across the interrupted
    epoch; the epoch settled (resize plane idle)."""
    import socket as _socket
    import threading
    import time as _time

    import jax

    from paddle_tpu.core import stats as core_stats
    from paddle_tpu.serving.router import RouterServer, RouterStandby
    from paddle_tpu.serving.server import ServingClient, ServingServer
    from paddle_tpu.serving.workload import make_prompts

    backend = jax.default_backend()
    n_rep = 2
    n_req = args.ha_requests
    max_new = args.serving_max_new
    prompts = make_prompts(
        n_req + 1, lengths=(5, 8, 11), vocab=128, bos_id=1, seed=args.seed,
    )
    sampling = [
        (dict(temperature=0.8, top_k=20, seed=1000 + i) if i % 2 else {})
        for i in range(n_req)
    ]

    def router_leg(faulted: bool) -> dict:
        primary = RouterServer(lease_s=1.5, poll_interval_s=0.01).start()
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        sb_port = s.getsockname()[1]
        s.close()
        endpoints = [list(primary.address), ["127.0.0.1", sb_port]]
        box = {}
        stop_evt = threading.Event()
        if faulted:
            standby = RouterStandby(
                primary.address, port=sb_port, poll_s=0.1,
                stop_evt=stop_evt, lease_s=1.5, poll_interval_s=0.01,
            )
            threading.Thread(
                target=lambda: box.update(srv=standby.run()), daemon=True,
            ).start()
        servers = []
        for _ in range(n_rep):
            sess = _serving_session(args)
            srv = ServingServer(
                session=sess, router_endpoints=endpoints,
                stall_fence_s=30.0,
            ).start()
            servers.append((srv, sess))
        deadline = _time.time() + 30
        while _time.time() < deadline and len(primary.fleet.live()) < n_rep:
            _time.sleep(0.02)
        # wedge BOTH replicas between decode steps: every request below is
        # provably in flight when the router dies
        gates = [sess._gen_lock for _, sess in servers]
        for g in gates:
            g.acquire()
        released = False
        results, errs, stream_out = {}, [], {"tokens": [], "reattaches": 0}

        def gen(i):
            c = ServingClient(endpoints, timeout=3.0)
            try:
                out = c.generate(
                    prompts[i], max_new, timeout_s=150.0, **sampling[i],
                )
                results[i] = list(out["tokens"])
            except Exception as e:
                errs.append((i, repr(e)))
            finally:
                c.close()

        def consume_stream():
            c = ServingClient(endpoints, timeout=3.0)
            try:
                for fr in c.stream(prompts[n_req], max_new,
                                   reattach_retries=30):
                    stream_out["tokens"].extend(fr["tokens"])
                    if fr.get("done"):
                        break
                stream_out["reattaches"] = c.stream_reattaches
            except Exception as e:
                errs.append(("stream", repr(e)))
            finally:
                c.close()

        threads = [
            threading.Thread(target=gen, args=(i,), daemon=True)
            for i in range(n_req)
        ] + [threading.Thread(target=consume_stream, daemon=True)]
        tk_before = core_stats.FT_EVENTS.get("router_takeover")
        t0 = _time.time()
        try:
            for t in threads:
                t.start()
            deadline = _time.time() + 60
            while _time.time() < deadline and sum(
                len(srv.dispatch("outstanding", {}, None)["requests"])
                for srv, _ in servers
            ) < n_req + 1:
                _time.sleep(0.05)
            adopted = 0
            if faulted:
                primary.kill()
                deadline = _time.time() + 30
                while _time.time() < deadline and box.get("srv") is None:
                    _time.sleep(0.05)
                new = box["srv"]
                deadline = _time.time() + 60
                while _time.time() < deadline and (
                    new is None or len(new.fleet.live()) < n_rep
                    or new.router.adopted < 1
                ):
                    _time.sleep(0.05)
                adopted = new.router.adopted if new is not None else 0
            for g in gates:
                g.release()
            released = True
            for t in threads:
                t.join(timeout=150.0)
            wall = _time.time() - t0
            drain_deadline = _time.time() + 60
            while _time.time() < drain_deadline and any(
                s.scheduler.has_work() for _, s in servers
            ):
                _time.sleep(0.05)
            leaks = {
                i: sess.cache.pages_in_use
                for i, (_, sess) in enumerate(servers)
            }
            return {
                "completed": len(results),
                "errors": errs,
                "stream_tokens": len(stream_out["tokens"]),
                "stream_reattaches": stream_out["reattaches"],
                "takeovers": (
                    core_stats.FT_EVENTS.get("router_takeover") - tk_before
                ),
                "adopted_by_standby": adopted,
                "leaked_pages_by_replica": leaks,
                "zero_page_leak": all(v == 0 for v in leaks.values()),
                "wall_s": round(wall, 3),
                "_tokens": dict(results),
                "_stream": list(stream_out["tokens"]),
            }
        finally:
            if not released:
                for g in gates:
                    g.release()
            stop_evt.set()
            for srv, _ in servers:
                srv.stop()
            primary.stop()
            if box.get("srv") is not None:
                box["srv"].stop()

    def autoscaler_leg() -> dict:
        import shutil
        import tempfile

        from paddle_tpu.core import faults
        from paddle_tpu.runtime import recordio
        from paddle_tpu.runtime.autoscaler import (
            AutoscalerController, AutoscalerStandby, ScaleConfig,
        )
        from paddle_tpu.runtime.master import (
            MasterClient, MasterServer, TaskMaster, cluster_reader,
        )

        tmp = tempfile.mkdtemp(prefix="chaos_ha_autoscale_")
        nrec = args.autoscale_tasks * args.records_per_task
        msrv = boot = None

        class _Spawner:
            def __init__(self):
                self.spawned = 0

            def spawn(self):
                self.spawned += 1

            def reap(self):
                return self.spawned

            def stop_all(self):
                pass

        spawner = _Spawner()
        spawner.spawn()  # the min fleet

        class _ScriptedRouter:
            """Queue wait pinned above the scale-up band; live replicas
            mirror the spawner's count — observation only, no fleet."""

            def call(self, method, **kw):
                if method == "stats":
                    return {
                        "replicas": [
                            {"replica_id": f"fake-{i}", "state": "live",
                             "outstanding": 0, "load": {}}
                            for i in range(spawner.spawned)
                        ],
                        "estimated_queue_wait_s": 50.0,
                        "shed": 0,
                    }
                return {"ok": True}

            def close(self):
                pass

        try:
            shards = recordio.convert(
                os.path.join(tmp, "ds"),
                lambda: ({"sid": i} for i in range(nrec)),
                records_per_file=args.records_per_task,
            )
            msrv = MasterServer(
                TaskMaster(timeout_s=30.0, failure_max=10), lease_s=1.5,
                resize_drain_timeout_s=6.0, initial_world=2,
            ).start()
            boot = MasterClient(msrv.address)
            boot.call("set_dataset", shards=shards, chunks_per_task=1)
            consumed = [[] for _ in range(args.consumers)]
            # keep the training pass alive long enough for the kill +
            # takeover + reconcile to land mid-pass
            work_s = max(0.15, 12.0 * args.consumers / nrec)

            def consume(i):
                rd = cluster_reader(
                    msrv.address, client_kw={"retries": 40, "timeout": 5},
                    poll_interval=0.05,
                )
                for rec in rd():
                    consumed[i].append(rec["sid"])
                    _time.sleep(work_s)

            consumers = [
                threading.Thread(target=consume, args=(i,), daemon=True)
                for i in range(args.consumers)
            ]
            for t in consumers:
                t.start()
            # chips_total = 1 serving + 2 training: full, so scale-up must
            # borrow a chip back from training via a resize epoch
            cfg = ScaleConfig(
                chips_total=3, chips_per_replica=1,
                min_replicas=1, max_replicas=2,
                train_min_world=1, train_max_world=2,
                high_wait_s=5.0, low_wait_s=1.0,
                high_ticks=2, low_ticks=50,
                serving_cooldown_s=0.3, train_cooldown_s=0.3,
                flap_window_s=0.5, startup_quiet_s=0.1,
                backoff_base_s=0.5, backoff_max_s=4.0,
                resize_timeout_s=30.0, drain_deadline_s=8.0,
            )

            def build_ctl():
                return AutoscalerController(
                    config=cfg, spawner=spawner, tick_s=0.05,
                    router_client=_ScriptedRouter(),
                    master_client=MasterClient(msrv.address),
                )

            tk_before = core_stats.FT_EVENTS.get("autoscaler_takeover")
            ctl = AutoscalerController(
                config=cfg, spawner=spawner, tick_s=0.05,
                router_client=_ScriptedRouter(),
                master_client=MasterClient(msrv.address),
                liveness_port=0,
            ).start()
            box = {}
            standby = AutoscalerStandby(
                ctl.liveness_address, build_ctl, poll_s=0.1,
            )
            threading.Thread(
                target=lambda: box.update(ctl=standby.run()), daemon=True,
            ).start()
            leg = {}
            # wait for the primary's resize epoch, then kill it MID-epoch
            deadline = _time.time() + 30
            while (_time.time() < deadline
                   and msrv.resize.info()["state"] == "idle"):
                _time.sleep(0.02)
            leg["epoch_state_at_kill"] = msrv.resize.info()["state"]
            faults.ACTIVE.configure("controller_kill:step=0", args.seed)
            deadline = _time.time() + 15
            while not ctl.dead and _time.time() < deadline:
                _time.sleep(0.02)
            faults.ACTIVE.configure("")
            leg["primary_killed"] = bool(ctl.dead)
            # the standby confirms the dropped liveness port, takes over,
            # and its controller reconciles + completes the scale-up
            deadline = _time.time() + 60
            while _time.time() < deadline and (
                box.get("ctl") is None or spawner.spawned < 2
            ):
                _time.sleep(0.05)
            leg["standby_took_over"] = box.get("ctl") is not None
            leg["takeovers"] = (
                core_stats.FT_EVENTS.get("autoscaler_takeover") - tk_before
            )
            leg["spawned"] = spawner.spawned
            for t in consumers:
                t.join(timeout=120.0)
            leg["consumers_done"] = not any(t.is_alive() for t in consumers)
            flat = sorted(x for lst in consumed for x in lst)
            leg["tasks_exactly_once"] = flat == list(range(nrec))
            leg["final_world"] = msrv.resize.info()["world"]
            leg["epoch_settled"] = msrv.resize.info()["state"] == "idle"
            if box.get("ctl") is not None:
                box["ctl"].stop()
            ctl.stop()
            return leg
        finally:
            if boot is not None:
                boot.close()
            if msrv is not None:
                msrv.stop()
            shutil.rmtree(tmp, ignore_errors=True)

    clean = router_leg(faulted=False)
    faulted = router_leg(faulted=True)
    clean_toks = clean.pop("_tokens")
    fault_toks = faulted.pop("_tokens")
    clean_stream = clean.pop("_stream")
    fault_stream = faulted.pop("_stream")
    mismatches = [
        i for i in range(n_req) if fault_toks.get(i) != clean_toks.get(i)
    ]
    greedy_checked = sum(1 for i in fault_toks if i % 2 == 0)
    sampled_checked = sum(1 for i in fault_toks if i % 2 == 1)
    auto = autoscaler_leg()
    fidelity = (
        (n_req - len(mismatches)) / n_req if n_req else 0.0
    )
    ok = (
        not clean["errors"] and not faulted["errors"]
        and not mismatches
        and greedy_checked >= 1 and sampled_checked >= 1
        and fault_stream == clean_stream and len(fault_stream) > 0
        and faulted["stream_reattaches"] >= 1
        and faulted["takeovers"] == 1
        and faulted["adopted_by_standby"] >= 1
        and clean["zero_page_leak"] and faulted["zero_page_leak"]
        and auto["primary_killed"]
        and auto["epoch_state_at_kill"] != "idle"
        and auto["takeovers"] == 1
        and auto["spawned"] >= 2
        and auto["tasks_exactly_once"]
        and auto["epoch_settled"]
    )
    return {
        "metric": "ha_token_fidelity",
        "value": round(fidelity, 3),
        "unit": "fraction of requests bitwise-identical across a router "
                "takeover vs the unfaulted run",
        "platform": backend,
        "all_gates_pass": bool(ok),
        "gates": {
            "zero_client_errors": not clean["errors"]
            and not faulted["errors"],
            "token_bitwise_vs_unfaulted": not mismatches,
            "greedy_streams_checked": greedy_checked,
            "sampled_streams_checked": sampled_checked,
            "stream_exactly_once": fault_stream == clean_stream
            and len(fault_stream) > 0,
            "stream_cursor_reattached": faulted["stream_reattaches"] >= 1,
            "router_takeover_once": faulted["takeovers"] == 1,
            "sweep_adopted": faulted["adopted_by_standby"] >= 1,
            "zero_page_leak": clean["zero_page_leak"]
            and faulted["zero_page_leak"],
            "autoscaler_killed_mid_epoch": auto["primary_killed"]
            and auto["epoch_state_at_kill"] != "idle",
            "autoscaler_takeover_once": auto["takeovers"] == 1,
            "standby_completed_scale_up": auto["spawned"] >= 2,
            "train_tasks_exactly_once": auto["tasks_exactly_once"],
            "resize_epoch_settled": auto["epoch_settled"],
        },
        "router_clean": {**clean, "stream_tokens_list": clean_stream},
        "router_faulted": {**faulted, "stream_tokens_list": fault_stream},
        "autoscaler": auto,
        "seed": args.seed,
    }


def run_serving(args) -> dict:
    """Serving resilience drill (see module docstring)."""
    import jax

    from paddle_tpu.obs import metrics as obs_metrics

    backend = jax.default_backend()
    os.environ.setdefault("PADDLE_TPU_SERVING_STALL_S", "5")
    legs = {
        "decode_raise": serving_crash_leg(
            args, "decode_raise",
            f"decode_raise:step={args.serving_kill_step}", backend,
        ),
        "engine_stall": serving_crash_leg(
            args, "engine_stall",
            f"engine_stall:step={args.serving_kill_step}", backend,
        ),
        "page_exhaust": serving_crash_leg(
            args, "page_exhaust", "page_exhaust:step=0", backend,
        ),
    }
    # ISSUE 11: crash replay must stay bitwise WITH sampling enabled (the
    # per-request seed + token-step key contract)
    legs["sampling_replay"] = serving_sampling_replay_leg(args, backend)
    # ISSUE 16: crash mid-SPECULATION must also replay bitwise at
    # temperature > 0 (drafting is a pure function of committed tokens)
    legs["spec_replay"] = serving_spec_replay_leg(args, backend)
    overload = serving_overload_leg(args, backend)
    # the resilience counters must be READABLE off the obs plane — the same
    # registry the serving `metrics` RPC serves
    counters = {
        k: v for k, v in obs_metrics.snapshot().items()
        if k.startswith("paddle_tpu_serving_")
        and ("shed" in k or "deadline" in k or "engine_restarts" in k
             or "recycled" in k)
    }
    ok = (
        all(leg["all_gates_pass"] for leg in legs.values())
        and overload["goodput_within_20pct"]
        and any("engine_restarts" in k for k in counters)
        and any("shed" in k for k in counters)
    )
    return {
        "metric": "serving_goodput_retention_2x",
        "value": overload["goodput_retention_2x"],
        "unit": "x goodput at 2x offered load vs at-capacity",
        "platform": backend,
        "all_gates_pass": bool(ok),
        "crash_legs": legs,
        "overload": overload,
        "obs_counters": counters,
        "seed": args.seed,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="local",
                    choices=["local", "cluster", "resize", "serving",
                             "router", "autoscale", "ha", "fleet"],
                    help="local: in-process throughput-under-faults; "
                         "cluster: multi-process master-failover drill; "
                         "resize: live elastic grow/shrink mid-pass drill; "
                         "serving: engine-kill + overload-shedding drill; "
                         "router: multi-replica kill+wedge failover drill "
                         "(exactly-once, page-leak, goodput + bitwise "
                         "gates); autoscale: goodput-driven controller "
                         "vs idle/burst/idle load, killed+restarted "
                         "mid-resize-epoch; ha: control-plane takeover "
                         "drill — router killed mid-decode under a "
                         "standby (bitwise + stream-reattach gates) and "
                         "autoscaler killed mid-resize-epoch under a "
                         "standby (exactly-once gate); fleet: simulated "
                         "100+-trainer control-plane drill — framed bulk "
                         "leases + piggybacked acks vs the legacy line-JSON "
                         "get_task/task_finished pair (tasks/sec, "
                         "time-to-drain, >= 3x round-trip reduction gate)")
    ap.add_argument("--faults", default=DEFAULT_FAULTS,
                    help="input-side fault mix for the chaos mode")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cluster_tasks", type=int, default=16)
    ap.add_argument("--records_per_task", type=int, default=4)
    ap.add_argument("--consumers", type=int, default=2)
    ap.add_argument("--work_ms", type=float, default=10.0,
                    help="per-record consumer work, keeps the pass alive "
                         "long enough for the kill to land mid-pass")
    ap.add_argument("--kill_rpc", type=int, default=9,
                    help="cluster mode: the RPC hit on which master_kill "
                         "fires (seeded, deterministic)")
    ap.add_argument("--batches", type=int, default=50)
    ap.add_argument("--batch_size", type=int, default=256)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--nan_every", type=int, default=10,
                    help="guard mode poisons every Nth batch (via probability "
                         "1/N) to exercise skip_batch under load")
    ap.add_argument("--resize_from", type=int, default=2,
                    help="resize mode: data-axis size the pass starts on")
    ap.add_argument("--resize_to_world", type=int, default=4,
                    help="resize mode: data-axis size after the mid-pass epoch")
    ap.add_argument("--resize_at", type=int, default=2,
                    help="resize mode: batch id whose EndIteration requests "
                         "the resize (drain lands at the next boundary)")
    ap.add_argument("--force_devices", type=int, default=8,
                    help="resize mode: xla_force_host_platform_device_count "
                         "for the virtual CPU mesh (set before jax imports)")
    ap.add_argument("--stall_s", type=float, default=8.0,
                    help="resize mode: how long the resize_drain_stall "
                         "consumer stays wedged inside the drain barrier "
                         "(longer than --drain_timeout_s, so the master "
                         "times it out of the barrier)")
    ap.add_argument("--drain_timeout_s", type=float, default=3.0,
                    help="resize mode: master drain-barrier timeout — a "
                         "wedged-but-heartbeating member is dropped from the "
                         "barrier after this long and the survivors proceed")
    ap.add_argument("--fleet_work_ms", type=float, default=40.0,
                    help="resize mode: per-record consumer work in the "
                         "drain-barrier drill — the pass must outlive a "
                         "heartbeat period so the drain signal lands mid-pass")
    ap.add_argument("--serving_requests", type=int, default=24,
                    help="serving mode: requests per crash leg / capacity run")
    ap.add_argument("--serving_slots", type=int, default=4,
                    help="serving mode: decode slots (continuous batch width)")
    ap.add_argument("--serving_max_new", type=int, default=12)
    ap.add_argument("--serving_submit_gap_ms", type=float, default=15.0,
                    help="serving mode: arrival spacing in the crash legs so "
                         "the fault lands mid-stream under sustained load")
    ap.add_argument("--serving_speculate_k", type=int, default=4,
                    help="serving mode: draft length for the spec_replay "
                         "leg (crash mid-speculation, bitwise replay gate)")
    ap.add_argument("--serving_kill_step", type=int, default=4,
                    help="serving mode: decode-step hit on which the "
                         "decode_raise/engine_stall fault fires (seeded)")
    ap.add_argument("--serving_stall_timeout_s", type=float, default=0.5,
                    help="serving mode: supervisor stall watchdog in the "
                         "crash legs (PADDLE_TPU_SERVING_STALL_S caps the "
                         "wedge itself)")
    ap.add_argument("--serving_overload_s", type=float, default=4.0,
                    help="serving mode: offered-load window per overload leg")
    ap.add_argument("--serving_deadline_s", type=float, default=0.0,
                    help="serving mode: overload-leg deadline override "
                         "(0 = auto: --serving_deadline_svc_mult service "
                         "times)")
    ap.add_argument("--serving_deadline_svc_mult", type=float, default=6.0,
                    help="serving mode: auto deadline = this many observed "
                         "per-request service times")
    ap.add_argument("--router_requests", type=int, default=120,
                    help="router mode: open-loop requests per run (the "
                         "submit window must dominate the fault-recovery "
                         "time for the goodput-retention gate to measure "
                         "steady state, not the transient)")
    ap.add_argument("--router_submit_gap_ms", type=float, default=50.0,
                    help="router mode: open-loop arrival spacing")
    ap.add_argument("--router_lease_s", type=float, default=0.8,
                    help="router mode: replica lease — the wedged replica "
                         "must blow past it for the eviction+failover leg")
    ap.add_argument("--router_stall_fence_s", type=float, default=0.2,
                    help="router mode: replica agent self-fence window")
    ap.add_argument("--router_wedge_s", type=float, default=2.5,
                    help="router mode: how long the wedged replica stays "
                         "parked between steps (longer than the lease, so "
                         "it is evicted; then it heals and its stale "
                         "answers exercise the late-winner dedup)")
    ap.add_argument("--autoscale_chips", type=int, default=4,
                    help="autoscale mode: total chip budget shared by the "
                         "serving fleet and the training world")
    ap.add_argument("--autoscale_max_replicas", type=int, default=3,
                    help="autoscale mode: serving fleet ceiling (and the "
                         "static baseline's constant fleet size)")
    ap.add_argument("--autoscale_train_world", type=int, default=1,
                    help="autoscale mode: training world at t=0")
    ap.add_argument("--autoscale_train_max_world", type=int, default=2,
                    help="autoscale mode: training world ceiling (chips "
                         "lent by the idle serving fleet)")
    ap.add_argument("--autoscale_idle_s", type=float, default=3.0,
                    help="autoscale mode: leading idle-phase duration")
    ap.add_argument("--autoscale_burst_s", type=float, default=8.0,
                    help="autoscale mode: burst-phase duration ceiling")
    ap.add_argument("--autoscale_tail_s", type=float, default=6.0,
                    help="autoscale mode: trailing idle-phase duration")
    ap.add_argument("--autoscale_burst_mult", type=float, default=2.0,
                    help="autoscale mode: burst rate as a multiple of one "
                         "replica's measured closed-loop capacity")
    ap.add_argument("--autoscale_burst_cap", type=float, default=600.0,
                    help="autoscale mode: max burst arrivals (shortens the "
                         "burst phase on very fast hosts)")
    ap.add_argument("--autoscale_max_new", type=int, default=48,
                    help="autoscale mode: decode tokens per request (more "
                         "than the other serving drills, so one replica's "
                         "capacity is a rate a Python submit loop can "
                         "oversubscribe)")
    ap.add_argument("--autoscale_tick_s", type=float, default=0.2,
                    help="autoscale mode: controller tick period")
    ap.add_argument("--autoscale_tasks", type=int, default=16,
                    help="autoscale mode: training tasks for the "
                         "exactly-once-across-resizes gate")
    ap.add_argument("--autoscale_work_ms", type=float, default=400.0,
                    help="autoscale mode: per-record consumer work (keeps "
                         "the training pass alive across the whole load "
                         "schedule so resizes land mid-pass)")
    ap.add_argument("--fleet_trainers", type=int, default=100,
                    help="fleet mode: simulated trainer count (threads, "
                         "each with its own wire connection)")
    ap.add_argument("--fleet_tasks", type=int, default=800,
                    help="fleet mode: task ledger size drained by each leg")
    ap.add_argument("--fleet_lease_batch", type=int, default=8,
                    help="fleet mode: tasks per bulk get_tasks lease in the "
                         "framed leg (acks for the batch ride the next "
                         "lease request)")
    ap.add_argument("--ha_requests", type=int, default=6,
                    help="ha mode: wedged in-flight requests per router leg "
                         "(half greedy, half seeded-sampled; plus one "
                         "push-stream)")
    args = ap.parse_args()

    if args.mode == "ha":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(run_ha(args)))
        return

    if args.mode == "autoscale":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(run_autoscale(args)))
        return

    if args.mode == "serving":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(run_serving(args)))
        return

    if args.mode == "router":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(run_router(args)))
        return

    if args.mode == "resize":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.force_devices}"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(run_resize(args)))
        return

    if args.mode == "cluster":
        print(json.dumps(run_cluster(args)))
        return

    if args.mode == "fleet":
        print(json.dumps(run_fleet(args)))
        return

    import jax

    clean = run_mode(args, spec="")
    chaos = run_mode(args, spec=args.faults)
    guard = run_mode(
        args, spec=f"nan_loss:{1.0 / args.nan_every}", policy="skip_batch"
    )
    print(json.dumps({
        "metric": "chaos_throughput_retention",
        "value": round(chaos["steps_per_sec"] / clean["steps_per_sec"], 3),
        "unit": "x",
        "clean": clean,
        "input_faults": {"spec": args.faults, **chaos},
        "nan_guard": {"spec": f"nan_loss:{1.0 / args.nan_every}", **guard},
        "seed": args.seed,
        "batches": args.batches,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
