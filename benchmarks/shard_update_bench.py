"""Sharded-update + compressed-collective benchmark (ISSUE 5 acceptance).

Sweeps the data-parallel step's update strategy on a forced-host-device CPU
mesh: {replicated, shard_update} x {none, bf16, int8} compression, at each
requested device count (each count needs its own process — the XLA host
device count is fixed at backend init, so the parent re-execs itself per N).

Per cell it reports:
  * steps_per_sec          (CPU wall clock — a smoke number, not the claim)
  * opt_state_bytes        per-chip resident optimizer-state bytes, measured
                           from sharding metadata (stats.per_chip_tree_bytes)
  * collective_bytes_per_step  the updater's modeled bytes/chip crossing
                           collectives (ring convention; see
                           ParameterUpdater.collective_bytes_per_step)
  * final cost             (convergence smoke for the quantized modes)

and per device count it verifies the acceptance gates:
  * sharded SGD params are BITWISE-equal to replicated after a full pass
    (lr/momentum are powers of two so the scale products are exact — XLA
    freely FMA-contracts them otherwise and arbitrary lr agrees only to
    1-2 ULP; see tests/test_shard_update.py)
  * per-chip opt-state bytes shrink ~N x under shard_update
  * collective bytes/step shrink >= 2x under bf16 compression

Usage:
  JAX_PLATFORMS=cpu python benchmarks/shard_update_bench.py
      [--devices 1,2,4] [--batches N] [--batch_size N] [--dim N] [--hidden N]

Output: one JSON line {"metric": "shard_update_bench", ...} with the grid
plus "gates" booleans.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_trainer(args, n_dev, shard, compression):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.parallel import DataParallel, make_mesh
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(args.dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, args.hidden, act="relu", name="h1")
    h = L.Fc(h, args.hidden, act="relu", name="h2")
    logits = L.Fc(h, args.classes, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")
    dp = DataParallel(make_mesh({"data": n_dev}))
    # power-of-two scales: exact products keep the sharded-vs-replicated
    # comparison bitwise (momentum exercises a real optimizer slot)
    return SGDTrainer(
        cost, SGD(learning_rate=0.125, momentum=0.5), parallel=dp, seed=0,
        shard_update=shard,
        grad_compression=None if compression == "none" else compression,
    )


def run_cell(args, n_dev, shard, compression):
    import numpy as np

    from paddle_tpu.core import stats

    tr = build_trainer(args, n_dev, shard, compression)
    rs = np.random.RandomState(0)
    x = rs.randn(args.batches * args.batch_size, args.dim).astype(np.float32)
    y = rs.randint(0, args.classes, len(x))

    def reader():
        for i in range(0, len(x), args.batch_size):
            yield {"x": x[i:i + args.batch_size], "label": y[i:i + args.batch_size]}

    costs = []
    from paddle_tpu.trainer.events import EndPass

    def handler(e):
        if isinstance(e, EndPass):
            costs.append(e.metrics["avg_cost"])

    tr.train(reader, num_passes=1, event_handler=handler)  # warmup+compile
    t0 = time.time()
    tr.train(reader, num_passes=1, event_handler=handler)
    dt = time.time() - t0
    return {
        "mode": ("sharded" if shard else "replicated"),
        "compression": compression,
        "devices": n_dev,
        "steps_per_sec": round(args.batches / dt, 1),
        "opt_state_bytes": stats.per_chip_tree_bytes(tr.state["opt"]),
        "param_bytes": stats.per_chip_tree_bytes(tr.state["params"]),
        "collective_bytes_per_step": tr.updater.collective_bytes_per_step(),
        "final_cost": round(float(costs[-1]), 6),
    }, {k: np.asarray(v) for k, v in tr.state["params"].items()}


def run_one_device_count(args, n_dev):
    import numpy as np

    cells = []
    params = {}
    grid = [(False, "none"), (True, "none"), (True, "bf16"), (True, "int8")]
    for shard, comp in grid:
        cell, p = run_cell(args, n_dev, shard, comp)
        cells.append(cell)
        params[(cell["mode"], comp)] = p
    rep = params[("replicated", "none")]
    sh = params[("sharded", "none")]
    bitwise = all(
        np.array_equal(
            rep[k].view(np.uint32), sh[k].view(np.uint32)
        )
        for k in rep
    )
    by = {(c["mode"], c["compression"]): c for c in cells}
    rep_c, sh_c = by[("replicated", "none")], by[("sharded", "none")]
    bf_c = by[("sharded", "bf16")]
    gates = {
        "sgd_bitwise_equal": bool(bitwise),
        # ~N x: padding/alignment costs a little, require >= 0.6*N
        "opt_bytes_reduction": round(
            rep_c["opt_state_bytes"] / max(sh_c["opt_state_bytes"], 1), 2
        ),
        "opt_bytes_reduced_enough": bool(
            n_dev == 1
            or rep_c["opt_state_bytes"] / max(sh_c["opt_state_bytes"], 1)
            >= 0.6 * n_dev
        ),
        "bf16_collective_reduction": round(
            rep_c["collective_bytes_per_step"]
            / max(bf_c["collective_bytes_per_step"], 1), 2
        ),
        "bf16_collective_halved": bool(
            n_dev == 1
            or rep_c["collective_bytes_per_step"]
            >= 2 * bf_c["collective_bytes_per_step"]
        ),
    }
    return {"devices": n_dev, "cells": cells, "gates": gates}


def child_main(args):
    result = run_one_device_count(args, args._n_dev)
    print("SHARD_BENCH_JSON " + json.dumps(result))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4")
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--_child_devices", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args._child_devices:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "").replace(
                "--xla_force_host_platform_device_count=8", ""
            )
            + f" --xla_force_host_platform_device_count={args._child_devices}"
        ).strip()
        args._n_dev = args._child_devices
        child_main(args)
        return

    results = []
    for n in [int(d) for d in args.devices.split(",") if d.strip()]:
        cmd = [
            sys.executable, os.path.abspath(__file__),
            f"--_child_devices={n}",
            f"--batches={args.batches}", f"--batch_size={args.batch_size}",
            f"--dim={args.dim}", f"--hidden={args.hidden}",
            f"--classes={args.classes}",
        ]
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=1200,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        line = next(
            (l for l in out.stdout.splitlines() if l.startswith("SHARD_BENCH_JSON ")),
            None,
        )
        if line is None:
            results.append({"devices": n, "error": (out.stderr or out.stdout)[-500:]})
        else:
            results.append(json.loads(line[len("SHARD_BENCH_JSON "):]))

    all_gates = [r["gates"] for r in results if "gates" in r]
    ok = bool(all_gates) and all(
        g["sgd_bitwise_equal"] and g["opt_bytes_reduced_enough"]
        and g["bf16_collective_halved"]
        for g in all_gates
    )
    print(json.dumps({
        "metric": "shard_update_bench",
        "value": 1.0 if ok else 0.0,
        "unit": "acceptance",
        "all_gates_pass": ok,
        "results": results,
    }))


if __name__ == "__main__":
    main()
