"""Sharded-update + compressed-collective benchmark (ISSUE 5 + 14 acceptance).

Sweeps the data-parallel step's update strategy on a forced-host-device CPU
mesh: {replicated, zero1, zero2, zero3} x {none, bf16, int8} compression, at
each requested device count (each count needs its own process — the XLA host
device count is fixed at backend init, so the parent re-execs itself per N).
The zero2 cell runs its window at --k_dispatch (default 16), the fused-update
configuration the grad-leg gate names.

Per cell it reports:
  * steps_per_sec          (CPU wall clock — a smoke number, not the claim)
  * opt_state_bytes        per-chip resident optimizer-state bytes, measured
                           from sharding metadata (stats.per_chip_tree_bytes)
  * param_bytes            per-chip resident parameter bytes (the zero3 claim)
  * collective_bytes_per_step / collective_bytes_detail  the updater's
                           modeled per-leg bytes/chip (ring convention; see
                           ParameterUpdater.collective_bytes_detail)
  * final cost             (convergence smoke for the quantized modes)
  * platform               backend tag so CPU-fallback rounds are excludable

and per device count it verifies the acceptance gates:
  * zero1 AND zero3 SGD params are BITWISE-equal to replicated after a full
    pass (lr/momentum are powers of two so the scale products are exact; see
    tests/test_shard_update.py)
  * per-chip opt-state bytes shrink ~N x under zero1; under zero3 BOTH the
    param bytes and opt-state bytes shrink ~N x
  * zero2's grad(scatter)-leg bytes per step are ~1/K of zero1's at K
  * collective bytes/step shrink >= 2x under bf16 compression
  * zero3's int8 param-gather leg is <= ~1/4 of its f32 leg (3.5x gate —
    int8 payload + one f32 scale per 64-element block)

Usage:
  JAX_PLATFORMS=cpu python benchmarks/shard_update_bench.py
      [--devices 1,2,4] [--batches N] [--batch_size N] [--dim N] [--hidden N]
      [--k_dispatch K]

Output: one JSON line {"metric": "shard_update_bench", ...} with the grid
plus "gates" booleans.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (mode, compression, steps_per_dispatch is --k_dispatch when mode=="zero2")
GRID = [
    ("replicated", "none"),
    ("zero1", "none"),
    ("zero1", "bf16"),
    ("zero1", "int8"),
    ("zero2", "none"),
    ("zero3", "none"),
    ("zero3", "int8"),
]


def build_trainer(args, n_dev, mode, compression):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.parallel import DataParallel, make_mesh
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(args.dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, args.hidden, act="relu", name="h1")
    h = L.Fc(h, args.hidden, act="relu", name="h2")
    logits = L.Fc(h, args.classes, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")
    dp = DataParallel(make_mesh({"data": n_dev}))
    # power-of-two scales: exact products keep the sharded-vs-replicated
    # comparison bitwise (momentum exercises a real optimizer slot)
    return SGDTrainer(
        cost, SGD(learning_rate=0.125, momentum=0.5), parallel=dp, seed=0,
        shard_update=False if mode == "replicated" else mode,
        grad_compression=None if compression == "none" else compression,
    )


def run_cell(args, n_dev, mode, compression):
    import jax
    import numpy as np

    from paddle_tpu.core import stats

    k = args.k_dispatch if mode == "zero2" else 1
    tr = build_trainer(args, n_dev, mode, compression)
    rs = np.random.RandomState(0)
    x = rs.randn(args.batches * args.batch_size, args.dim).astype(np.float32)
    y = rs.randint(0, args.classes, len(x))

    def reader():
        for i in range(0, len(x), args.batch_size):
            yield {"x": x[i:i + args.batch_size], "label": y[i:i + args.batch_size]}

    costs = []
    from paddle_tpu.trainer.events import EndPass

    def handler(e):
        if isinstance(e, EndPass):
            costs.append(e.metrics["avg_cost"])

    # warmup+compile
    tr.train(reader, num_passes=1, event_handler=handler, steps_per_dispatch=k)
    t0 = time.time()
    tr.train(reader, num_passes=1, event_handler=handler, steps_per_dispatch=k)
    dt = time.time() - t0
    params = {
        key: np.asarray(v)
        for key, v in tr.updater.params_to_canonical(tr.state["params"]).items()
    }
    return {
        "mode": mode,
        "compression": compression,
        "devices": n_dev,
        "steps_per_dispatch": k,
        "steps_per_sec": round(args.batches / dt, 1),
        "opt_state_bytes": stats.per_chip_tree_bytes(tr.state["opt"]),
        "param_bytes": stats.per_chip_tree_bytes(tr.state["params"]),
        "collective_bytes_per_step": tr.updater.collective_bytes_per_step(k),
        "collective_bytes_detail": tr.updater.collective_bytes_detail(k),
        "final_cost": round(float(costs[-1]), 6),
        "platform": jax.default_backend(),
    }, params


def zero2_fused_structure_ok(args, n_dev) -> bool:
    """The FALSIFIABLE half of the zero2 claim: the byte model divides by K
    by construction, so only the compiled program can catch a regression to
    a per-step scan — the fused K-dispatch HLO must contain no while loop
    (tests/test_hlo_collectives.py pins the full collective budget too)."""
    import numpy as np

    tr = build_trainer(args, n_dev, "zero2", "none")
    rs = np.random.RandomState(0)
    batch = {
        "x": rs.randn(args.batch_size, args.dim).astype(np.float32),
        "label": rs.randint(0, args.classes, args.batch_size),
    }
    tr.init_state(tr.parallel.shard_batch(batch))
    batches = tr.parallel.shard_batches(
        {k: np.stack([v] * args.k_dispatch) for k, v in batch.items()}
    )
    txt = tr.make_multi_step().lower(tr.state, batches).compile().as_text()
    return " while(" not in txt


def run_one_device_count(args, n_dev):
    import numpy as np

    cells = []
    params = {}
    for mode, comp in GRID:
        cell, p = run_cell(args, n_dev, mode, comp)
        cells.append(cell)
        params[(mode, comp)] = p

    def bitwise_vs_rep(which):
        rep = params[("replicated", "none")]
        other = params[which]
        return all(
            np.array_equal(rep[k].view(np.uint32), other[k].view(np.uint32))
            for k in rep
        )

    by = {(c["mode"], c["compression"]): c for c in cells}
    rep_c = by[("replicated", "none")]
    z1_c, bf_c = by[("zero1", "none")], by[("zero1", "bf16")]
    z2_c = by[("zero2", "none")]
    z3_c, z38_c = by[("zero3", "none")], by[("zero3", "int8")]

    def leg(cell, name):
        return cell["collective_bytes_detail"]["per_leg"][name]["bytes_per_step"]

    k = args.k_dispatch
    gates = {
        "sgd_bitwise_equal": bool(bitwise_vs_rep(("zero1", "none"))),
        "zero3_sgd_bitwise_equal": bool(bitwise_vs_rep(("zero3", "none"))),
        # ~N x: padding/alignment costs a little, require >= 0.6*N
        "opt_bytes_reduction": round(
            rep_c["opt_state_bytes"] / max(z1_c["opt_state_bytes"], 1), 2
        ),
        "opt_bytes_reduced_enough": bool(
            n_dev == 1
            or rep_c["opt_state_bytes"] / max(z1_c["opt_state_bytes"], 1)
            >= 0.6 * n_dev
        ),
        # zero3: params AND opt state both ~N x down per chip
        "zero3_param_bytes_reduction": round(
            rep_c["param_bytes"] / max(z3_c["param_bytes"], 1), 2
        ),
        "zero3_bytes_reduced_enough": bool(
            n_dev == 1
            or (
                rep_c["param_bytes"] / max(z3_c["param_bytes"], 1)
                >= 0.6 * n_dev
                and rep_c["opt_state_bytes"] / max(z3_c["opt_state_bytes"], 1)
                >= 0.6 * n_dev
            )
        ),
        # zero2 at K: the grad(scatter) leg per step is ~1/K of zero1's.
        # NOTE both legs come from the analytic bytes model (which divides
        # by K by construction) — the claim is FALSIFIED structurally, by
        # the fused-program check below and the HLO pins in
        # tests/test_hlo_collectives.py, not by this consistency ratio.
        "zero2_grad_leg_reduction": round(
            leg(z1_c, "scatter") / max(leg(z2_c, "scatter"), 1), 2
        ),
        "zero2_grad_leg_reduced_enough": bool(
            n_dev == 1
            or leg(z2_c, "scatter") * k <= leg(z1_c, "scatter") * 1.05
        ),
        # the structural half: the compiled K-dispatch program really is
        # ONE fused update (no while loop), so the scatter genuinely runs
        # once per dispatch
        "zero2_fused_no_scan": bool(zero2_fused_structure_ok(args, n_dev)),
        "bf16_collective_reduction": round(
            rep_c["collective_bytes_per_step"]
            / max(bf_c["collective_bytes_per_step"], 1), 2
        ),
        "bf16_collective_halved": bool(
            n_dev == 1
            or rep_c["collective_bytes_per_step"]
            >= 2 * bf_c["collective_bytes_per_step"]
        ),
        # int8-in-collective param gather: <= ~1/4 of the f32 leg (itemsize
        # model — the wire realization caveat is documented in
        # parallel/compression.py; the payload STRUCTURE is pinned by
        # test_zero3_int8_gather_crosses_payload_and_scales)
        "int8_gather_reduction": round(
            leg(z3_c, "gather") / max(leg(z38_c, "gather"), 1), 2
        ),
        "int8_gather_reduced_enough": bool(
            n_dev == 1
            or leg(z3_c, "gather") >= 3.5 * leg(z38_c, "gather")
        ),
    }
    return {"devices": n_dev, "cells": cells, "gates": gates}


def child_main(args):
    result = run_one_device_count(args, args._n_dev)
    print("SHARD_BENCH_JSON " + json.dumps(result))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4")
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument(
        "--k_dispatch", type=int, default=16,
        help="steps_per_dispatch for the zero2 cell (the grad-leg gate's K)",
    )
    ap.add_argument("--_child_devices", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args._child_devices:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "").replace(
                "--xla_force_host_platform_device_count=8", ""
            )
            + f" --xla_force_host_platform_device_count={args._child_devices}"
        ).strip()
        args._n_dev = args._child_devices
        child_main(args)
        return

    results = []
    for n in [int(d) for d in args.devices.split(",") if d.strip()]:
        cmd = [
            sys.executable, os.path.abspath(__file__),
            f"--_child_devices={n}",
            f"--batches={args.batches}", f"--batch_size={args.batch_size}",
            f"--dim={args.dim}", f"--hidden={args.hidden}",
            f"--classes={args.classes}", f"--k_dispatch={args.k_dispatch}",
        ]
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=1800,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        line = next(
            (l for l in out.stdout.splitlines() if l.startswith("SHARD_BENCH_JSON ")),
            None,
        )
        if line is None:
            results.append({"devices": n, "error": (out.stderr or out.stdout)[-500:]})
        else:
            results.append(json.loads(line[len("SHARD_BENCH_JSON "):]))

    all_gates = [r["gates"] for r in results if "gates" in r]
    ok = bool(all_gates) and all(
        g["sgd_bitwise_equal"] and g["zero3_sgd_bitwise_equal"]
        and g["opt_bytes_reduced_enough"] and g["zero3_bytes_reduced_enough"]
        and g["zero2_grad_leg_reduced_enough"] and g["zero2_fused_no_scan"]
        and g["bf16_collective_halved"] and g["int8_gather_reduced_enough"]
        for g in all_gates
    )
    print(json.dumps({
        "metric": "shard_update_bench",
        "value": 1.0 if ok else 0.0,
        "unit": "acceptance",
        "all_gates_pass": ok,
        "results": results,
    }))


if __name__ == "__main__":
    main()
