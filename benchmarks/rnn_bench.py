"""LSTM text-classification benchmark — reference benchmark/paddle/rnn/rnn.py
parity (BASELINE.md LSTM rows: 2×lstm + fc, seq len 100, hidden
256/512/1280, bs 64/128/256).

Usage:
  python benchmarks/rnn_bench.py --hidden 256,512 --batch_sizes 64,128
"""

from __future__ import annotations

import argparse
import json
import time


def run_one(batch_size: int, hidden: int, seq_len: int, vocab: int,
            steps: int, warmup: int):
    import jax
    import numpy as np

    from paddle_tpu import models
    from paddle_tpu.nn.graph import Network, reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    ids, label, logits, cost = models.text_lstm(
        vocab_size=vocab, embed_dim=128, hidden_dim=hidden, num_layers=2
    )
    trainer = SGDTrainer(cost, SGD(learning_rate=0.01))
    rs = np.random.RandomState(0)
    batch = {
        ids.name: rs.randint(0, vocab, (batch_size, seq_len)).astype(np.int32),
        ids.name + ".lengths": np.full(batch_size, seq_len, np.int32),
        label.name: rs.randint(0, 2, batch_size),
    }
    batch = jax.device_put(batch)  # keep tunnel H2D out of the timing
    trainer.init_state(batch)
    step = trainer._make_step()
    from paddle_tpu.core.benchmark import time_train_steps

    sec, _ = time_train_steps(step, trainer.state, batch, steps, warmup)
    ms = sec * 1e3
    print(json.dumps({
        "model": "lstm_text_cls", "batch_size": batch_size, "hidden": hidden,
        "seq_len": seq_len, "ms_per_batch": round(ms, 3),
        "tokens_per_sec": round(batch_size * seq_len / (ms / 1e3), 0),
        "backend": jax.default_backend(),
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch_sizes", default="64")
    ap.add_argument("--hidden", default="256")
    ap.add_argument("--seq_len", type=int, default=100)
    ap.add_argument("--vocab", type=int, default=10000)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    args = ap.parse_args()
    for bs in [int(b) for b in args.batch_sizes.split(",")]:
        for h in [int(x) for x in args.hidden.split(",")]:
            run_one(bs, h, args.seq_len, args.vocab, args.steps, args.warmup)


if __name__ == "__main__":
    main()
