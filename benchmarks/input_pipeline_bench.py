"""Input-pipeline overlap benchmark: DevicePrefetcher on vs off.

A synthetic feeder charges a fixed host cost per batch (default 5ms —
sleeping, so it stands in for any numpy/tokenize/pad work that releases the
GIL no better than real code does). The consumer reads the cost every
iteration, the way an evaluator-carrying handler does, so each step's device
time sits on the critical path. Without prefetch the loop pays
feed + step serially; with the prefetcher the worker thread feeds and
device_puts ahead, so steps/sec approaches 1/max(feed, step) — the
host/device overlap discipline, measured without a chip.

Usage:
  JAX_PLATFORMS=cpu python benchmarks/input_pipeline_bench.py [--feed_ms 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_trainer(dim: int, hidden: int, classes: int):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, hidden, act="relu")
    h = L.Fc(h, hidden, act="relu")
    logits = L.Fc(h, classes, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(cost, SGD(learning_rate=0.01), seed=0)


def run_mode(prefetch: bool, args) -> float:
    """steps/sec over the timed (second) pass; first pass compiles."""
    import numpy as np

    from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
    from paddle_tpu.data.pipeline import DevicePrefetcher
    from paddle_tpu.trainer import EndIteration, EndPass

    rs = np.random.RandomState(0)
    raw_batches = [
        [
            (rs.randn(args.dim).astype(np.float32), int(i % args.classes))
            for i in range(args.batch_size)
        ]
        for _ in range(args.batches)
    ]
    base_feeder = DataFeeder(
        {"x": dense_vector(args.dim), "label": integer_value(args.classes)}
    )

    def feeder(samples):
        time.sleep(args.feed_ms / 1e3)  # the synthetic host-prep cost
        return base_feeder(samples)

    reader = lambda: iter(raw_batches)  # noqa: E731
    if prefetch:
        reader = DevicePrefetcher(
            reader, feeder, prefetch_depth=args.prefetch_depth
        )

    trainer = build_trainer(args.dim, args.hidden, args.classes)
    pass_secs = []

    def handler(e):
        if isinstance(e, EndIteration):
            float(e.cost)  # consume per-step output (evaluator-style sync)
        elif isinstance(e, EndPass):
            pass_secs.append(e.metrics["pass_seconds"])

    trainer.train(reader, num_passes=2, feeder=feeder, event_handler=handler)
    return args.batches / pass_secs[-1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--feed_ms", type=float, default=5.0)
    ap.add_argument("--batches", type=int, default=60)
    ap.add_argument("--batch_size", type=int, default=256)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--prefetch_depth", type=int, default=4)
    args = ap.parse_args()

    import jax

    off = run_mode(prefetch=False, args=args)
    on = run_mode(prefetch=True, args=args)
    print(json.dumps({
        "metric": "input_pipeline_prefetch_speedup",
        "value": round(on / off, 3),
        "unit": "x",
        "steps_per_sec_prefetch_off": round(off, 2),
        "steps_per_sec_prefetch_on": round(on, 2),
        "feed_ms": args.feed_ms,
        "prefetch_depth": args.prefetch_depth,
        "batches": args.batches,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
