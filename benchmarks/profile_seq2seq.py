"""Capture a jax.profiler trace of the seq2seq NMT bench step (the second
north-star metric) and emit the HLO-category / source-line time tables.

Usage:  python benchmarks/profile_seq2seq.py [--batch 128] [--len 50]
Outputs: trace under --out (gitignored; only the distilled table is committed
in PROFILE_r04.md) + markdown tables on stdout.

Reference anchor: benchmark/paddle/rnn/rnn.py, benchmark/README.md:115-161.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from profile_resnet import fmt_tables, parse_xplane  # noqa: E402


def build_step(bs: int, seq_len: int, vocab: int, dim: int):
    import jax

    from paddle_tpu.core import dtypes
    from paddle_tpu.models import Seq2SeqModel
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGDTrainer

    dtypes.set_policy(dtypes.bf16_policy())
    reset_name_scope()
    model = Seq2SeqModel(vocab, vocab, embed_dim=dim, hidden_dim=dim)
    trainer = SGDTrainer(model.cost, Adam(learning_rate=1e-3))
    rs = np.random.RandomState(0)
    batch = {
        "source_ids": rs.randint(2, vocab, (bs, seq_len)).astype(np.int32),
        "source_ids.lengths": np.full(bs, seq_len, np.int32),
        "target_ids": rs.randint(2, vocab, (bs, seq_len)).astype(np.int32),
        "target_ids.lengths": np.full(bs, seq_len, np.int32),
        "label_ids": rs.randint(2, vocab, (bs, seq_len)).astype(np.int32),
        "label_ids.lengths": np.full(bs, seq_len, np.int32),
    }
    batch = jax.device_put(batch)
    trainer.init_state(batch)
    step = trainer._make_step()
    return trainer, step, batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--len", type=int, default=50, dest="seq_len")
    ap.add_argument("--vocab", type=int, default=30000)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--out", default="profiles/r04_s2s")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} platform={dev.platform}", flush=True)

    trainer, step, batch = build_step(args.batch, args.seq_len, args.vocab, args.dim)
    state = trainer.state

    t0 = time.perf_counter()
    state, cost, _ = step(state, batch)
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s cost={float(cost):.3f}", flush=True)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, cost, _ = step(state, batch)
    float(cost)
    dt = (time.perf_counter() - t0) / args.steps
    toks = args.batch * args.seq_len / dt
    print(f"steady: {dt * 1000:.2f} ms/step  {toks:.0f} tokens/s", flush=True)

    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        for _ in range(3):
            state, cost, _ = step(state, batch)
        jax.block_until_ready(cost)
        float(cost)

    res, err = parse_xplane(args.out)
    if res is None:
        print("xplane parse failed:", err)
        return
    by_cat, by_src, n_steps = res
    print()
    print(fmt_tables(by_cat, by_src, n_steps, top=20))


if __name__ == "__main__":
    main()
