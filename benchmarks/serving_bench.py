"""Continuous-batching serving benchmark (ISSUE 6 acceptance).

Measures tokens/sec and p50/p99/p999 request latency — plus the
deadline-miss and shed columns (ISSUE 10), zero unless `--deadline_s` arms
per-request deadlines, so overload rounds stay comparable — at 1/4/16/64
concurrent streams against the SAME serving session configuration, where
concurrency=1
is the sequential per-request baseline (one request in flight at a time —
the `run_generation` serving model: nothing overlaps). Same executables,
same platform, same fixed shapes at every concurrency, so the measured
speedup isolates dynamic batching.

The workload is a mixed-length prompt stream spanning two prefill buckets;
after a warmup pass that touches every bucket, the decode-recompile count
must stay at ZERO (the PR-1 RecompileStats assertion — variable-length
sequences of different ages share one compiled decode program through the
paged KV cache).

Acceptance gates (printed in the JSON line):
  * speedup_16 >= 3.0      tokens/sec at 16 streams vs sequential
  * decode_recompiles_after_warmup == 0 over the mixed-length stream

Usage:
  JAX_PLATFORMS=cpu python benchmarks/serving_bench.py
      [--streams 1,4,16,64] [--requests N] [--max_new N]
      [--vocab V --n_layers L --d_model D --n_heads H]

Output: one JSON line {"metric": "serving_bench", ...} with a per-stream-
count entry (each carrying its own "platform" tag, like shard_update_bench).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(args, concurrency: int, prompts):
    """Fresh session per concurrency so KV pool state and stats are clean;
    the persistent compile cache makes the repeat compiles cheap."""
    import jax

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import make_prompts, run_closed_loop

    session = make_demo_session(
        vocab=args.vocab, n_layers=args.n_layers, d_model=args.d_model,
        n_heads=args.n_heads, seed=0,
        max_slots=args.max_slots, page_size=args.page_size,
        prefill_buckets=(16, 32), max_new_limit=args.max_new,
    )
    # warmup: touch EVERY prefill bucket + the decode program (one prompt at
    # each bucket length), then snapshot the recompile counter —
    # steady-state serving must add NOTHING to it
    warm_prompts = make_prompts(
        len(session.buckets), lengths=session.buckets, vocab=args.vocab,
        bos_id=1, seed=7,
    )
    warm = run_closed_loop(
        session, warm_prompts, args.max_new, concurrency=len(warm_prompts)
    )
    sigs_after_warmup = session.decode_shape_signatures()
    # the warmup's compile-heavy per-request times must not leak into the
    # measured run's load-aware admission (they read as second-scale service
    # times and would shed everything against --deadline_s)
    session.scheduler.reset_load_estimate()
    res = run_closed_loop(
        session, prompts, args.max_new, concurrency,
        deadline_s=args.deadline_s or None,
    )
    recompiles = session.decode_shape_signatures() - sigs_after_warmup
    tokens = res.pop("results")
    res.update({
        "platform": jax.devices()[0].platform,
        "decode_recompiles_after_warmup": recompiles,
        "decode_shape_signatures": session.decode_shape_signatures(),
        "warmup_tokens": warm["tokens"],
    })
    return res, tokens


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", default="1,4,16,64")
    ap.add_argument("--requests", type=int, default=48,
                    help="total requests per concurrency level")
    ap.add_argument("--max_new", type=int, default=24)
    ap.add_argument("--deadline_s", type=float, default=0.0,
                    help="arm a per-request total-latency deadline (0 = "
                         "none); the p999 / deadline-miss columns report "
                         "either way so rounds stay comparable")
    ap.add_argument("--max_slots", type=int, default=16)
    ap.add_argument("--page_size", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--n_layers", type=int, default=2)
    ap.add_argument("--d_model", type=int, default=64)
    ap.add_argument("--n_heads", type=int, default=2)
    args = ap.parse_args()

    from paddle_tpu.serving.model import LMConfig
    from paddle_tpu.serving.workload import make_prompts

    cfg = LMConfig(vocab=args.vocab)
    # mixed lengths across BOTH buckets (16 and 32): the zero-recompile gate
    # is only meaningful on a shape-diverse stream
    prompts = make_prompts(
        args.requests, lengths=(5, 11, 16, 23, 32), vocab=args.vocab,
        bos_id=cfg.bos_id, seed=0,
    )

    results = []
    token_sets = {}
    for n in [int(x) for x in args.streams.split(",") if x.strip()]:
        res, tokens = run_one(args, n, prompts)
        results.append(res)
        token_sets[n] = tokens
        print(
            f"[serving_bench] streams={n}: {res['tokens_per_sec']} tok/s "
            f"p50={res['p50_latency_ms']}ms p99={res['p99_latency_ms']}ms "
            f"p999={res['p999_latency_ms']}ms "
            f"deadline_misses={res['deadline_misses']} "
            f"recompiles={res['decode_recompiles_after_warmup']}",
            file=sys.stderr,
        )

    by_n = {r["concurrency"]: r for r in results}
    base = by_n.get(1)
    for r in results:
        if base is not None and base["tokens_per_sec"] > 0:
            r["speedup_vs_sequential"] = round(
                r["tokens_per_sec"] / base["tokens_per_sec"], 2
            )
    # continuous batching must be RESULT-transparent, not just fast: every
    # concurrency level produced identical tokens for every request
    consistent = all(t == token_sets[min(token_sets)] for t in token_sets.values())
    speedup_16 = by_n.get(16, {}).get("speedup_vs_sequential", 0.0)
    gates = {
        "speedup_16_vs_sequential": speedup_16,
        "speedup_16_ge_3x": bool(speedup_16 >= 3.0),
        "zero_decode_recompiles": all(
            r["decode_recompiles_after_warmup"] == 0 for r in results
        ),
        "batching_bitwise_transparent": bool(consistent),
    }
    ok = gates["speedup_16_ge_3x"] and gates["zero_decode_recompiles"] and consistent
    print(json.dumps({
        "metric": "serving_bench",
        "value": speedup_16,
        "unit": "x tokens/sec vs sequential @16 streams",
        "all_gates_pass": bool(ok),
        "gates": gates,
        "results": results,
    }))


if __name__ == "__main__":
    main()
