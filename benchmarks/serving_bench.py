"""Continuous-batching serving benchmark (ISSUE 6 acceptance).

Measures tokens/sec and p50/p99/p999 request latency — plus the
deadline-miss and shed columns (ISSUE 10), zero unless `--deadline_s` arms
per-request deadlines, so overload rounds stay comparable — at 1/4/16/64
concurrent streams against the SAME serving session configuration, where
concurrency=1
is the sequential per-request baseline (one request in flight at a time —
the `run_generation` serving model: nothing overlaps). Same executables,
same platform, same fixed shapes at every concurrency, so the measured
speedup isolates dynamic batching.

The workload is a mixed-length prompt stream spanning two prefill buckets;
after a warmup pass that touches every bucket, the decode-recompile count
must stay at ZERO (the PR-1 RecompileStats assertion — variable-length
sequences of different ages share one compiled decode program through the
paged KV cache).

Acceptance gates (printed in the JSON line):
  * speedup_16 >= 3.0      tokens/sec at 16 streams vs sequential
  * decode_recompiles_after_warmup == 0 over the mixed-length stream
  * mixed-length leg (ISSUE 11): p99 INTER-TOKEN latency with chunked
    prefill <= 0.5x the whole-prompt-prefill baseline at 16 streams when
    long prompts join mid-stream, with identical tokens across the legs

The --tp leg (ISSUE 12) serves identical geometry at TP=1/2/4, one child
process per size with that many FORCED host devices (the shard_update_bench
pattern): tokens must be identical at every TP, per-chip KV-pool bytes
exactly TP× down and param bytes ~TP× down (both from sharding metadata),
zero decode recompiles. Each entry carries its own "platform" tag — CPU
emulates the collectives, so the TP tokens/sec column is a smoke number
there.

The speculative leg (ISSUE 16) runs ONE stream — where batching cannot
help — over high-overlap repeated-motif prompts at --speculate_k 0 vs K:
gates >= 2x single-stream tokens/sec with identical tokens, one compiled
verify signature, zero decode recompiles, and reports the acceptance rate.
The streaming leg pushes the same requests through the router both ways
(poll loop vs push frames) at 1/16/64 streams: gate is push round trips
per delivered token strictly below poll at every count.

The prefix-cache leg (ISSUE 19) serves 4 system prompts x many user turns
that differ only in a short suffix, cache on vs off: gates are prefill
chunk steps AND warm-request TTFT both >= 3x down with the cache on,
tokens bitwise identical on vs off (greedy and seeded sampling, chunked
and whole-prompt prefill), zero page leaks after the index flush, and one
compiled decode signature in every leg (aliasing is a host-side
block-table edit — the executables never see the cache).

The --replicas leg (ISSUE 15) serves identical geometry through the ROUTER
at 1 vs 3 replicas, 64 closed-loop streams: tokens/sec + p99, gate >= 2x
throughput at 3 replicas — armed only on hosts with >= 3 cores (replica
scaling measures hardware parallelism; on a 1-core container the leg still
runs as a correctness + router-overhead drill and records
scaling_gate_meaningful: false).

Usage:
  JAX_PLATFORMS=cpu python benchmarks/serving_bench.py
      [--streams 1,4,16,64] [--requests N] [--max_new N]
      [--tp 1,2,4] [--skip_tp]
      [--vocab V --n_layers L --d_model D --n_heads H]

Output: one JSON line {"metric": "serving_bench", ...} with a per-stream-
count entry (each carrying its own "platform" tag, like shard_update_bench).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(args, concurrency: int, prompts):
    """Fresh session per concurrency so KV pool state and stats are clean;
    the persistent compile cache makes the repeat compiles cheap."""
    import jax

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import make_prompts, run_closed_loop

    session = make_demo_session(
        vocab=args.vocab, n_layers=args.n_layers, d_model=args.d_model,
        n_heads=args.n_heads, seed=0,
        max_slots=args.max_slots, page_size=args.page_size,
        prefill_buckets=(16, 32), max_new_limit=args.max_new,
    )
    # warmup: touch EVERY prefill bucket + the decode program (one prompt at
    # each bucket length), then snapshot the recompile counter —
    # steady-state serving must add NOTHING to it
    warm_prompts = make_prompts(
        len(session.buckets), lengths=session.buckets, vocab=args.vocab,
        bos_id=1, seed=7,
    )
    warm = run_closed_loop(
        session, warm_prompts, args.max_new, concurrency=len(warm_prompts)
    )
    sigs_after_warmup = session.decode_shape_signatures()
    # the warmup's compile-heavy per-request times never leak into the
    # measured run's load-aware admission: the session resets the EWMA
    # itself at the first clean post-compile step (ISSUE 17)
    res = run_closed_loop(
        session, prompts, args.max_new, concurrency,
        deadline_s=args.deadline_s or None,
    )
    recompiles = session.decode_shape_signatures() - sigs_after_warmup
    tokens = res.pop("results")
    res.update({
        "platform": jax.devices()[0].platform,
        "decode_recompiles_after_warmup": recompiles,
        "decode_shape_signatures": session.decode_shape_signatures(),
        "warmup_tokens": warm["tokens"],
    })
    return res, tokens


def run_mixed_length(args):
    """Chunked-prefill no-stall gate (ISSUE 11): 16 short-prompt streams with
    LONG prompts joining mid-stream, measured as p99 inter-token latency.
    Two legs over identical geometry and workload: whole-prompt prefill (the
    long prompt's full forward runs inside one engine step, stalling every
    running stream's next token) vs chunked prefill (the same prompt commits
    `--prefill_chunk` tokens per step, interleaved with decode). The gate is
    chunked p99 ITL <= 0.5x the whole-prompt baseline — the stall is the
    thing being measured, so this only means anything on the SAME platform
    tag. Tokens must also be identical across the legs (chunked prefill is
    result-transparent)."""
    import jax

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import (
        make_mixed_prompts, make_prompts, run_closed_loop,
    )

    long_len = args.mixed_long_len
    buckets = (16, 32, long_len)  # baseline needs a bucket covering the long prompts

    def leg(prefill_chunk):
        # the leg uses its own (bigger) model than the throughput grid: the
        # stall being measured is the long prompt's whole-context forward,
        # which must dominate per-dispatch overhead for the ratio to mean
        # anything — at toy dims the measurement is all dispatch noise
        # page pool sized for the REAL mix (16 short streams + 2 concurrent
        # long prompts), not the worst case of every slot at full context:
        # admission control already queues a long prompt the pool cannot
        # host, and on CPU (no buffer donation) every pool-touching program
        # copies the whole pool, so worst-case sizing would swamp the very
        # stall this leg measures — same pool for BOTH legs, so the ratio
        # isolates chunking
        short_pages = -(-(16 + args.max_new) // args.page_size)
        long_pages = -(-(long_len + args.max_new) // args.page_size)
        num_pages = 20 * short_pages + 2 * args.mixed_burst * long_pages + 1
        # max_slots > stream count: spare slots + a page budget for the burst
        # mean a long prompt admits at the NEXT boundary while all 16 short
        # streams keep decoding — otherwise the burst queues at the FIFO
        # head, admissions behind it stall, and the batch drains before the
        # big prefill even runs (the stall would land on an empty batch and
        # the ITL percentiles would never see it)
        session = make_demo_session(
            vocab=args.vocab, n_layers=args.n_layers,
            d_model=args.mixed_d_model, n_heads=args.mixed_n_heads, seed=0,
            max_slots=20, page_size=args.page_size, num_pages=num_pages,
            prefill_buckets=buckets, max_new_limit=args.max_new,
            max_len=long_len + args.max_new,
            prefill_chunk=prefill_chunk,
        )
        # warmup touches every executable (all buckets + the chunk program +
        # decode) so compile time never pollutes the measured ITL
        warm = make_prompts(
            len(buckets), lengths=buckets, vocab=args.vocab, bos_id=1, seed=7,
        )
        run_closed_loop(session, warm, args.max_new, concurrency=len(warm))
        sigs0 = session.decode_shape_signatures()
        prompts = make_mixed_prompts(
            args.requests, short_lengths=(5, 11, 16), long_len=long_len,
            long_every=12, burst=args.mixed_burst, vocab=args.vocab,
            bos_id=1, seed=1,
        )
        # per-request token budgets STAGGER retirements: with one shared
        # budget every stream retires in the same step, admissions ride the
        # wave boundary, and the whole-prompt stall lands on an empty batch
        # instead of the 16 live streams it is supposed to be measured against
        spread = max(1, args.max_new - 5)
        budgets = [
            args.max_new if len(p) > 16
            else min(args.max_new, 6 + (7 * i) % spread)
            for i, p in enumerate(prompts)
        ]
        # the ITL tail is the measurement: collect BEFORE and hold GC off
        # DURING the run so collector pauses from earlier legs' garbage
        # (the 64-stream grid runs first in a default invocation) don't
        # masquerade as scheduling stalls in either leg's p99
        import gc

        gc.collect()
        gc.disable()
        try:
            res = run_closed_loop(session, prompts, budgets, concurrency=16)
        finally:
            gc.enable()
        tokens = res.pop("results")
        res.update({
            "platform": jax.devices()[0].platform,
            "prefill_chunk": prefill_chunk,
            "long_len": long_len,
            "decode_recompiles_after_warmup":
                session.decode_shape_signatures() - sigs0,
            "prefill_chunks_committed": session.prefill_chunks_committed,
        })
        return res, tokens

    # best-of-N per leg: host noise (GC pauses, CPU contention) lands
    # straight in a single run's p99 tail — the MIN across repeats keeps the
    # deterministic stall component, which is the thing under measurement
    # (alternate the legs so slow host phases hit both)
    whole_runs, chunked_runs = [], []
    for _ in range(args.mixed_repeats):
        whole_runs.append(leg(None))
        chunked_runs.append(leg(args.prefill_chunk))
    whole, whole_tokens = min(
        whole_runs, key=lambda rt: rt[0]["p99_inter_token_ms"]
    )
    chunked, chunked_tokens = min(
        chunked_runs, key=lambda rt: rt[0]["p99_inter_token_ms"]
    )
    ratio = (
        chunked["p99_inter_token_ms"] / whole["p99_inter_token_ms"]
        if whole["p99_inter_token_ms"] > 0 else 0.0
    )
    out = {
        "whole_prompt": whole,
        "chunked": chunked,
        "whole_p99_runs": [r[0]["p99_inter_token_ms"] for r in whole_runs],
        "chunked_p99_runs": [r[0]["p99_inter_token_ms"] for r in chunked_runs],
        "p99_itl_ratio_chunked_vs_whole": round(ratio, 3),
        "chunked_itl_le_half": bool(ratio <= 0.5),
        "chunked_result_transparent": bool(chunked_tokens == whole_tokens),
        "zero_decode_recompiles": bool(
            whole["decode_recompiles_after_warmup"] == 0
            and chunked["decode_recompiles_after_warmup"] == 0
        ),
    }
    print(
        f"[serving_bench] mixed-length: whole p99_itl="
        f"{whole['p99_inter_token_ms']}ms chunked p99_itl="
        f"{chunked['p99_inter_token_ms']}ms ratio={out['p99_itl_ratio_chunked_vs_whole']} "
        f"transparent={out['chunked_result_transparent']}",
        file=sys.stderr,
    )
    return out


def run_speculative(args):
    """The single-stream speculative-decoding leg (ISSUE 16): ONE stream —
    the case continuous batching cannot help, where per-stream latency is
    the whole game — over a high-overlap workload (repeated-motif prompts,
    the extraction/templated-text regime prompt-lookup drafting is built
    for), greedy. Two runs over identical geometry and prompts:
    `--speculate_k 0` (today's one-token decode loop, bit-for-bit the PR-15
    path) vs `--speculate_k K` (draft K from the request's own committed
    tokens, score all K in ONE fixed-shape verify_chunk call). Gates:
      * tokens IDENTICAL across the legs (speculation is result-transparent
        — verification accepts exactly the oracle's tokens)
      * >= 2x single-stream tokens/sec with speculation on
      * verify_shape_signatures == 1 (every round shared one compiled
        [1, K+1] program) and zero decode recompiles in BOTH legs"""
    import jax

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import (
        make_prompts, make_repetitive_prompts, run_closed_loop,
    )

    # the leg runs its own (narrow) vocab: prompt-lookup speculation earns
    # its keep on self-similar text, and a tiny greedy model over a narrow
    # vocab settles into tight repeating continuations — the high-overlap
    # regime the ISSUE names — while a wide-vocab random model wanders for
    # most of a short generation and measures the drafter's worst case
    vocab = args.spec_vocab
    prompts = make_repetitive_prompts(
        args.spec_requests, motif_len=4, repeats=6, vocab=vocab,
        bos_id=1, seed=3,
    )

    def leg(k):
        session = make_demo_session(
            vocab=vocab, n_layers=args.n_layers, d_model=args.d_model,
            n_heads=args.n_heads, seed=0,
            max_slots=4, page_size=args.page_size,
            prefill_buckets=(16, 32), max_new_limit=args.spec_max_new,
            speculate_k=k,
        )
        # warmup touches every prefill bucket + the decode program, and (for
        # the speculative leg) a repetitive prompt long enough to draft so
        # the verify program compiles before the measured window
        warm = make_prompts(
            len(session.buckets), lengths=session.buckets, vocab=vocab,
            bos_id=1, seed=7,
        ) + make_repetitive_prompts(
            1, motif_len=4, repeats=6, vocab=vocab, bos_id=1, seed=11,
        )
        run_closed_loop(session, warm, args.spec_max_new, concurrency=len(warm))
        sigs0 = session.decode_shape_signatures()
        vsigs0 = session.verify_shape_signatures()
        res = run_closed_loop(
            session, prompts, args.spec_max_new, concurrency=1,
        )
        tokens = res.pop("results")
        st = session.stats()
        res.update({
            "platform": jax.devices()[0].platform,
            "speculate_k": k,
            "decode_recompiles_after_warmup":
                session.decode_shape_signatures() - sigs0,
            "verify_recompiles_after_warmup":
                session.verify_shape_signatures() - vsigs0,
            "verify_shape_signatures": st["verify_shape_signatures"],
            "spec_rounds": st["spec_rounds"],
            "spec_acceptance_rate": st["spec_acceptance_rate"],
            "spec_effective_k": st["spec_effective_k"],
        })
        return res, tokens

    # best-of-N per leg, legs alternated: the ratio under measurement is
    # deterministic (steps saved per accepted draft) but each run's wall
    # clock rides host noise — the MAX tokens/sec keeps the structural
    # component, the same discipline as the mixed-length leg's min-p99
    base_runs, spec_runs = [], []
    for _ in range(args.spec_repeats):
        base_runs.append(leg(0))
        spec_runs.append(leg(args.speculate_k))
    base, base_tokens = max(base_runs, key=lambda rt: rt[0]["tokens_per_sec"])
    spec, spec_tokens = max(spec_runs, key=lambda rt: rt[0]["tokens_per_sec"])
    speedup = (
        spec["tokens_per_sec"] / base["tokens_per_sec"]
        if base["tokens_per_sec"] else 0.0
    )
    out = {
        "baseline": base,
        "speculative": spec,
        "single_stream_speedup": round(speedup, 2),
        "spec_tokens_identical": bool(spec_tokens == base_tokens),
        "spec_speedup_ge_2x": bool(speedup >= 2.0),
        "spec_one_verify_signature": bool(
            spec["verify_shape_signatures"] == 1
            and spec["verify_recompiles_after_warmup"] == 0
        ),
        "spec_zero_decode_recompiles": bool(
            base["decode_recompiles_after_warmup"] == 0
            and spec["decode_recompiles_after_warmup"] == 0
        ),
    }
    print(
        f"[serving_bench] speculative k={args.speculate_k}: "
        f"{spec['tokens_per_sec']} tok/s vs {base['tokens_per_sec']} "
        f"(x{out['single_stream_speedup']}) acceptance="
        f"{spec['spec_acceptance_rate']} rounds={spec['spec_rounds']} "
        f"k_eff={spec['spec_effective_k']} "
        f"identical={out['spec_tokens_identical']}",
        file=sys.stderr,
    )
    return out


def run_prefix(args):
    """The shared-prefix KV-cache leg (ISSUE 19): `--prefix_prefixes`
    distinct system prompts, each shared by many user turns that differ only
    in a short random suffix — the many-users-one-assistant regime where a
    million users' prompts are mostly the SAME tokens. Five runs over
    identical geometry and prompts, driven sequentially (one request in
    flight: TTFT is then pure prefill cost, the number the cache attacks):

      A  cache OFF, chunked prefill  (the steps/TTFT baseline)
      B  cache ON,  chunked prefill  (the measured leg)
      C  cache OFF, whole-prompt     (chunk-vs-whole transparency anchor)
      D  cache OFF, chunked, seeded sampling
      E  cache ON,  chunked, seeded sampling

    Gates:
      * prefill chunk steps in B <= 1/Kx of A (warm requests start their one
        chunk at the first uncached token) and warm-request median TTFT down
        by the same >= Kx (K = --prefix_gate_x, default 3)
      * tokens bitwise IDENTICAL: B == A == C (greedy) and E == D (seeded
        sampling) — aliased pages hold exactly the KV the request would have
        computed, under chunked AND whole-prompt prefill
      * zero page leaks: after the run retires every request and the index
        is flushed, every allocatable page is back on the free list
      * decode_shape_signatures == 1 in every leg — the cache is a
        host-side block-table edit, invisible to the compiled programs"""
    import jax
    import numpy as np

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import make_shared_prefix_prompts

    plen = args.prefix_len + args.prefix_suffix
    prompts = make_shared_prefix_prompts(
        args.prefix_requests, n_prefixes=args.prefix_prefixes,
        prefix_len=args.prefix_len, suffix_len=args.prefix_suffix,
        vocab=args.vocab, bos_id=1, seed=5,
    )
    warm_cold = args.prefix_prefixes  # first turn per prefix runs cold

    def leg(prefix_on, temp, chunked=True):
        session = make_demo_session(
            vocab=args.vocab, n_layers=args.n_layers, d_model=args.d_model,
            n_heads=args.n_heads, seed=0,
            max_slots=4, page_size=args.prefix_page_size,
            prefill_buckets=(16, plen), max_new_limit=args.prefix_max_new,
            prefill_chunk=(args.prefix_chunk if chunked else None),
            prefix_cache=prefix_on,
        )
        # warmup compiles the chunk/prefill + decode programs; the flush
        # below guarantees the measured run still starts with a COLD index
        wp = [1] + list(range(3, 3 + plen - 1))
        h = session.submit(wp, args.prefix_max_new)
        session.run_until_idle()
        assert h.done
        if prefix_on:
            session.cache.flush_prefix()
        sigs0 = session.decode_shape_signatures()
        chunks0 = session.stats()["prefill_chunks_committed"]
        ttfts, toks = [], []
        for i, p in enumerate(prompts):
            kw = (
                dict(temperature=temp, top_k=8, seed=1000 + i)
                if temp > 0 else {}
            )
            h = session.submit(p, args.prefix_max_new, **kw)
            session.run_until_idle()
            ttfts.append((h.t_first_token - h.t_submit) * 1e3)
            toks.append(h.tokens)
        st = session.stats()
        leaked = 0
        if prefix_on:
            session.cache.flush_prefix()
        leaked = (session.cache.num_pages - 1) - session.cache.free_pages
        res = {
            "platform": jax.devices()[0].platform,
            "prefix_cache": prefix_on,
            "chunked": chunked,
            "temperature": temp,
            "prefill_chunk_steps": st["prefill_chunks_committed"] - chunks0,
            "ttft_warm_median_ms": round(
                float(np.median(ttfts[warm_cold:])), 3),
            "ttft_cold_median_ms": round(
                float(np.median(ttfts[:warm_cold])), 3),
            "decode_recompiles_after_warmup":
                session.decode_shape_signatures() - sigs0,
            "decode_shape_signatures": session.decode_shape_signatures(),
            "pages_leaked": leaked,
        }
        if prefix_on:
            res.update({
                "prefix_hit_rate": st["prefix_hit_rate"],
                "prefix_pages_shared": st["prefix_pages_shared"],
                "prefix_pages_cow": st["prefix_pages_cow"],
                "prefix_evictions": st["prefix_evictions"],
            })
        return res, toks

    base, base_toks = leg(False, 0.0)            # A
    cached, cached_toks = leg(True, 0.0)         # B
    whole, whole_toks = leg(False, 0.0, chunked=False)  # C
    sbase, sbase_toks = leg(False, 0.7)          # D
    scached, scached_toks = leg(True, 0.7)       # E

    steps_ratio = (
        base["prefill_chunk_steps"] / cached["prefill_chunk_steps"]
        if cached["prefill_chunk_steps"] else 0.0
    )
    ttft_ratio = (
        base["ttft_warm_median_ms"] / cached["ttft_warm_median_ms"]
        if cached["ttft_warm_median_ms"] else 0.0
    )
    out = {
        "baseline": base,
        "cached": cached,
        "whole_prompt": whole,
        "sampled_baseline": sbase,
        "sampled_cached": scached,
        "prefill_steps_ratio": round(steps_ratio, 2),
        "ttft_warm_ratio": round(ttft_ratio, 2),
        "prefix_steps_ge_gate": bool(steps_ratio >= args.prefix_gate_x),
        "prefix_ttft_ge_gate": bool(ttft_ratio >= args.prefix_gate_x),
        "prefix_tokens_identical": bool(
            cached_toks == base_toks and whole_toks == base_toks
        ),
        "prefix_sampled_tokens_identical": bool(scached_toks == sbase_toks),
        "prefix_zero_page_leak": bool(
            cached["pages_leaked"] == 0 and scached["pages_leaked"] == 0
            and base["pages_leaked"] == 0
        ),
        "prefix_one_decode_signature": bool(all(
            r["decode_shape_signatures"] == 1
            and r["decode_recompiles_after_warmup"] == 0
            for r in (base, cached, whole, sbase, scached)
        )),
    }
    print(
        f"[serving_bench] prefix: steps {base['prefill_chunk_steps']} -> "
        f"{cached['prefill_chunk_steps']} (x{out['prefill_steps_ratio']}) "
        f"ttft_warm {base['ttft_warm_median_ms']}ms -> "
        f"{cached['ttft_warm_median_ms']}ms (x{out['ttft_warm_ratio']}) "
        f"hit_rate={cached['prefix_hit_rate']} "
        f"identical={out['prefix_tokens_identical']}/"
        f"{out['prefix_sampled_tokens_identical']} "
        f"leaked={cached['pages_leaked']}",
        file=sys.stderr,
    )
    return out


def run_streaming(args):
    """The push-vs-poll round-trips leg (ISSUE 16): identical requests
    through the ROUTER, delivered two ways — the poll loop every client ran
    before this PR (submit + delta-poll at a fixed interval until done) vs
    push streaming (ONE submit round trip; frames arrive on the same
    connection as the engine emits tokens). The column that matters is
    client round trips per delivered token: polling pays one RPC per
    interval whether or not a token arrived, push pays one RPC per REQUEST.
    Gate: push round-trips-per-token strictly below poll at every stream
    count. Tokens/sec is reported for color but not gated — on a one-box
    CPU run both sides are engine-bound; the wire economics are the
    structural claim.

    ISSUE 20 adds the wire dimension: the push leg runs twice, once over
    the legacy line-JSON wire and once over the framed binary wire
    (compact stream deltas, token payloads packed as int32), and every leg
    reports stream bytes per delivered token off the client's own byte
    counters. Gate: at the LARGEST stream count the binary push spends
    <= half the bytes per token of the JSON push (coalescing under fan-out
    plus the frame encoding carry the 2x)."""
    import threading
    import time

    import jax

    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.server import ServingClient, ServingServer
    from paddle_tpu.serving.workload import make_prompts, run_closed_loop

    session = make_demo_session(
        vocab=args.vocab, n_layers=args.n_layers, d_model=args.d_model,
        n_heads=args.n_heads, seed=0,
        max_slots=args.max_slots, page_size=args.page_size,
        prefill_buckets=(16, 32), max_new_limit=args.stream_max_new,
        speculate_k=args.speculate_k,
    )
    warm = make_prompts(
        len(session.buckets), lengths=session.buckets, vocab=args.vocab,
        bos_id=1, seed=7,
    )
    run_closed_loop(session, warm, args.stream_max_new, concurrency=len(warm))
    router = RouterServer(lease_s=5.0, poll_interval_s=0.005).start()
    server = ServingServer(session=session, router_endpoints=router.address)
    server.start()
    deadline = time.time() + 30
    while time.time() < deadline and not router.fleet.live():
        time.sleep(0.02)

    def drive(n_streams, mode, wire="json"):
        prompts = make_prompts(
            n_streams, lengths=(5, 11, 16, 23, 32), vocab=args.vocab,
            bos_id=1, seed=100 + n_streams,
        )
        rpcs, tokens_out, errors, nbytes = [0], [0], [0], [0]
        lock = threading.Lock()

        def poll_stream(p):
            c = ServingClient(router.address)
            try:
                rid = c.submit(p, args.stream_max_new)
                calls, cur = 1, 0
                while True:
                    resp = c.poll(rid, from_=cur)
                    calls += 1
                    if "err" in resp:
                        raise RuntimeError(resp["err"])
                    if resp.get("done"):
                        toks = resp["tokens"]
                        break
                    cur = int(resp.get("tokens_so_far", cur))
                    time.sleep(0.02)
                with lock:
                    rpcs[0] += calls
                    tokens_out[0] += len(toks)
            except Exception:
                with lock:
                    errors[0] += 1
            finally:
                c.close()

        def push_stream(p):
            c = ServingClient(router.address, wire=wire)
            try:
                n = 0
                for frame in c.stream(p, args.stream_max_new):
                    n = int(frame.get("tokens_so_far", n))
                # one round trip per (re)attach: the submit ack; every frame
                # after it is pushed on the same connection
                with lock:
                    rpcs[0] += 1 + c.stream_reattaches
                    tokens_out[0] += n
                    nbytes[0] += c.stream_bytes_in
            except Exception:
                with lock:
                    errors[0] += 1
            finally:
                c.close()

        fn = poll_stream if mode == "poll" else push_stream
        threads = [
            threading.Thread(target=fn, args=(p,), daemon=True)
            for p in prompts
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        wall = time.monotonic() - t0
        return {
            "mode": mode,
            "wire": wire,
            "streams": n_streams,
            "tokens": tokens_out[0],
            "errors": errors[0],
            "round_trips": rpcs[0],
            "round_trips_per_token": round(
                rpcs[0] / tokens_out[0], 3
            ) if tokens_out[0] else 0.0,
            "stream_bytes": nbytes[0],
            "bytes_per_token": round(
                nbytes[0] / tokens_out[0], 1
            ) if tokens_out[0] else 0.0,
            "tokens_per_sec": round(tokens_out[0] / wall, 1) if wall else 0.0,
        }

    legs = []
    try:
        for n in [int(x) for x in args.stream_counts.split(",") if x.strip()]:
            poll = drive(n, "poll")
            push = drive(n, "push", wire="json")
            push_bin = drive(n, "push", wire="frames")
            legs.append({
                "streams": n,
                "poll": poll,
                "push": push,
                "push_bin": push_bin,
                "push_fewer_round_trips_per_token": bool(
                    push["errors"] == 0 and poll["errors"] == 0
                    and push["round_trips_per_token"]
                    < poll["round_trips_per_token"]
                ),
                "bin_bytes_ratio": round(
                    push["bytes_per_token"] / push_bin["bytes_per_token"], 2
                ) if push_bin["bytes_per_token"] else 0.0,
            })
            print(
                f"[serving_bench] streaming streams={n}: push "
                f"{push['round_trips_per_token']} rt/token vs poll "
                f"{poll['round_trips_per_token']}; bytes/token json "
                f"{push['bytes_per_token']} vs binary "
                f"{push_bin['bytes_per_token']} "
                f"(frames pushed so far: {router.stream_frames}, "
                f"coalesced: {router.stream_coalesced})",
                file=sys.stderr,
            )
    finally:
        server.stop()
        router.stop()
    return {
        "platform": jax.devices()[0].platform,
        "legs": legs,
        "push_round_trips_below_poll_all": bool(legs) and all(
            l["push_fewer_round_trips_per_token"] for l in legs
        ),
        # ISSUE 20 gate: at the largest fan-out the binary push wire moves
        # <= half the bytes per delivered token of the JSON push wire
        "binary_stream_bytes_2x_at_max_fanout": bool(legs) and (
            legs[-1]["bin_bytes_ratio"] >= 2.0
        ),
        "stream_frames_pushed": router.stream_frames,
        "stream_bytes_pushed": router.stream_bytes,
        "stream_frames_coalesced": router.stream_coalesced,
    }


def run_tp_child(args):
    """One tensor-parallel leg in THIS process (forced host device count is
    already set by the parent re-exec): identical geometry at every TP, so
    the tokens/sec + p99 ITL deltas isolate the collectives, and the
    per-chip param/pool bytes come from sharding metadata."""
    import jax

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import make_prompts, run_closed_loop

    tp = args._child_tp
    session = make_demo_session(
        vocab=args.vocab, n_layers=args.n_layers, d_model=args.d_model,
        n_heads=args.tp_n_heads, seed=0,
        max_slots=args.max_slots, page_size=args.page_size,
        prefill_buckets=(16, 32), max_new_limit=args.max_new,
        tp=(tp if tp > 1 else 0),
    )
    prompts = make_prompts(
        args.requests, lengths=(5, 11, 16, 23, 32), vocab=args.vocab,
        bos_id=1, seed=0,
    )
    warm = make_prompts(
        len(session.buckets), lengths=session.buckets, vocab=args.vocab,
        bos_id=1, seed=7,
    )
    run_closed_loop(session, warm, args.max_new, concurrency=len(warm))
    sigs0 = session.decode_shape_signatures()
    res = run_closed_loop(session, prompts, args.max_new, concurrency=16)
    tokens = res.pop("results")
    st = session.stats()
    res.update({
        "tp": tp,
        "platform": jax.devices()[0].platform,
        "devices": jax.device_count(),
        "decode_recompiles_after_warmup":
            session.decode_shape_signatures() - sigs0,
        "param_bytes_per_chip": st["param_bytes_per_chip"],
        "pool_bytes_per_chip": st["pool_bytes_per_chip"],
        "results": tokens,
    })
    print("TP_BENCH_JSON " + json.dumps(res))


def run_tp(args):
    """The --tp leg (ISSUE 12): TP=1/2/4 over identical geometry, each in a
    child process with the XLA host device count FORCED to the TP size (the
    shard_update_bench pattern — the device count is fixed at backend
    init). The persistent compile cache is dropped from the children:
    executing a cache-DESERIALIZED multi-device program segfaults on this
    jax build (see tests/test_precision.py). Gates: tokens identical at
    every TP (tensor parallelism is result-invisible), zero decode
    recompiles, and per-chip pool bytes exactly TP× down."""
    legs = []
    for n in [int(x) for x in args.tp.split(",") if x.strip()]:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PADDLE_TPU_COMPILE_CACHE", None)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "").replace(
                "--xla_force_host_platform_device_count=8", ""
            )
            + f" --xla_force_host_platform_device_count={max(n, 1)}"
        ).strip()
        cmd = [
            sys.executable, os.path.abspath(__file__),
            f"--_child_tp={n}", f"--requests={args.requests}",
            f"--max_new={args.max_new}", f"--max_slots={args.max_slots}",
            f"--page_size={args.page_size}", f"--vocab={args.vocab}",
            f"--n_layers={args.n_layers}", f"--d_model={args.d_model}",
            f"--tp_n_heads={args.tp_n_heads}",
        ]
        try:
            out = subprocess.run(
                cmd, capture_output=True, text=True, timeout=1200, env=env,
            )
        except (subprocess.TimeoutExpired, OSError) as exc:
            # a wedged/unspawnable child is an ERROR LEG, not a bench abort:
            # the streams grid + mixed-length results already computed must
            # still reach the JSON line
            legs.append({"tp": n, "error": repr(exc)[-500:]})
            continue
        line = next(
            (l for l in out.stdout.splitlines()
             if l.startswith("TP_BENCH_JSON ")), None,
        )
        if line is None:
            legs.append({"tp": n, "error": (out.stderr or out.stdout)[-500:]})
        else:
            legs.append(json.loads(line[len("TP_BENCH_JSON "):]))
    ok_legs = [l for l in legs if "error" not in l]
    token_sets = {l["tp"]: l.pop("results") for l in ok_legs}
    base = next((l for l in ok_legs if l["tp"] <= 1), None)
    identical = (
        len(token_sets) == len(legs) and len(set(
            json.dumps(t) for t in token_sets.values()
        )) == 1
    )
    gates = {
        "tp_tokens_identical": bool(identical),
        "tp_zero_decode_recompiles": bool(ok_legs) and all(
            l["decode_recompiles_after_warmup"] == 0 for l in ok_legs
        ),
    }
    for leg in ok_legs:
        if base is None or leg["tp"] <= 1:
            continue
        n = leg["tp"]
        gates[f"tp{n}_pool_bytes_ratio"] = round(
            base["pool_bytes_per_chip"] / max(leg["pool_bytes_per_chip"], 1), 2
        )
        gates[f"tp{n}_pool_bytes_exact"] = bool(
            leg["pool_bytes_per_chip"] * n == base["pool_bytes_per_chip"]
        )
        gates[f"tp{n}_param_bytes_ratio"] = round(
            base["param_bytes_per_chip"] / max(leg["param_bytes_per_chip"], 1),
            2,
        )
        gates[f"tp{n}_param_bytes_reduced_enough"] = bool(
            base["param_bytes_per_chip"]
            >= 0.6 * n * leg["param_bytes_per_chip"]
        )
        print(
            f"[serving_bench] tp={n}: {leg['tokens_per_sec']} tok/s "
            f"p99_itl={leg['p99_inter_token_ms']}ms "
            f"pool_bytes/chip={leg['pool_bytes_per_chip']} "
            f"(ratio {gates[f'tp{n}_pool_bytes_ratio']}x) "
            f"identical={identical}",
            file=sys.stderr,
        )
    return {"legs": legs, "gates": gates}


def run_replicas(args):
    """The --replicas leg (ISSUE 15): identical geometry served by 1 vs N
    replicas behind the router at `--replica_streams` concurrent streams.
    Each stream is a thread keeping one request in flight (submit → result →
    next, pulling from a shared work list), so N replicas get to fill N
    engines' slots concurrently; the gate is >= 2x tokens/sec at 3 replicas
    (engines run jit'd programs that release the GIL, so in-process replicas
    genuinely overlap — on a host with the cores to back them). The gate is
    only ARMED with >= 3 host cores: replica scaling measures hardware
    parallelism, and on a 1-core container 3 engines time-slice one core, so
    aggregate tokens/sec physically cannot scale — the leg still runs there
    as a correctness + router-overhead drill (all requests complete, zero
    failovers, the ratio reported) with `scaling_gate_meaningful: false`
    recorded, the same machine-readable-caveat discipline as the bf16
    speedup gate on the CPU fallback. Sessions are warmed DIRECTLY before
    joining the fleet so compile time never pollutes the measured window;
    every entry carries its own platform tag."""
    import threading
    import time

    import numpy as np

    import jax

    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.server import ServingServer
    from paddle_tpu.serving.workload import make_prompts, run_closed_loop

    def leg(n_replicas):
        sessions = []
        for _ in range(n_replicas):
            s = make_demo_session(
                vocab=args.vocab, n_layers=args.n_layers,
                d_model=args.replicas_d_model, n_heads=4, seed=0,
                max_slots=args.max_slots, page_size=args.page_size,
                prefill_buckets=(16, 32), max_new_limit=args.max_new,
            )
            warm = make_prompts(
                len(s.buckets), lengths=s.buckets, vocab=args.vocab,
                bos_id=1, seed=7,
            )
            run_closed_loop(s, warm, args.max_new, concurrency=len(warm))
            sessions.append(s)
        router = RouterServer(lease_s=5.0, poll_interval_s=0.005).start()
        servers = [
            ServingServer(session=s, router_endpoints=router.address).start()
            for s in sessions
        ]
        deadline = time.time() + 30
        while (time.time() < deadline
               and len(router.fleet.live()) < n_replicas):
            time.sleep(0.02)
        prompts = make_prompts(
            args.replicas_requests, lengths=(5, 11, 16, 23, 32),
            vocab=args.vocab, bos_id=1, seed=0,
        )
        work = list(enumerate(prompts))
        work_lock = threading.Lock()
        lat_ms, tokens_out, errors = [], [0], [0]

        def stream():
            while True:
                with work_lock:
                    if not work:
                        return
                    _idx, p = work.pop(0)
                t1 = time.monotonic()
                try:
                    h = router.router.submit(p, args.max_new)
                    toks = h.result(timeout=180.0)
                except Exception:
                    with work_lock:
                        errors[0] += 1
                    continue
                with work_lock:
                    lat_ms.append((time.monotonic() - t1) * 1e3)
                    tokens_out[0] += len(toks)

        threads = [
            threading.Thread(target=stream, daemon=True)
            for _ in range(args.replica_streams)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        wall = time.monotonic() - t0
        st = router.router.stats()
        for srv in servers:
            srv.stop()
        router.stop()
        lat = np.asarray(lat_ms) if lat_ms else np.asarray([0.0])
        return {
            "replicas": n_replicas,
            "streams": args.replica_streams,
            "requests": args.replicas_requests,
            "completed": len(lat_ms),
            "errors": errors[0],
            "tokens": tokens_out[0],
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(tokens_out[0] / wall, 1) if wall else 0.0,
            "p50_latency_ms": round(float(np.percentile(lat, 50)), 2),
            "p99_latency_ms": round(float(np.percentile(lat, 99)), 2),
            "router_failovers": st["failovers"],
            "platform": jax.devices()[0].platform,
        }

    legs = [
        leg(int(x)) for x in args.replicas.split(",") if x.strip()
    ]
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    by_n = {l["replicas"]: l for l in legs}
    base = by_n.get(1)
    gates = {"host_cores": cores, "scaling_gate_meaningful": cores >= 3}
    for l in legs:
        if base is None or l["replicas"] <= 1:
            continue
        ratio = (
            l["tokens_per_sec"] / base["tokens_per_sec"]
            if base["tokens_per_sec"] else 0.0
        )
        gates[f"replicas{l['replicas']}_speedup_vs_1"] = round(ratio, 2)
        if l["replicas"] == 3:
            # the >= 2x scaling gate needs >= 3 cores to mean anything; on a
            # smaller host record the ratio and leave the gate un-armed
            gates["replicas3_speedup_ge_2x"] = (
                bool(ratio >= 2.0) if cores >= 3 else None
            )
        print(
            f"[serving_bench] replicas={l['replicas']}: "
            f"{l['tokens_per_sec']} tok/s p99={l['p99_latency_ms']}ms "
            f"(x{ratio:.2f} vs 1 replica; {cores} host core(s))",
            file=sys.stderr,
        )
    gates["replicas_all_completed"] = all(
        l["completed"] == l["requests"] and l["errors"] == 0 for l in legs
    )
    gates["replicas_zero_failovers"] = all(
        l["router_failovers"] == 0 for l in legs
    )
    return {"legs": legs, "gates": gates}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", default="1,4,16,64")
    ap.add_argument("--requests", type=int, default=48,
                    help="total requests per concurrency level")
    ap.add_argument("--max_new", type=int, default=24)
    ap.add_argument("--deadline_s", type=float, default=0.0,
                    help="arm a per-request total-latency deadline (0 = "
                         "none); the p999 / deadline-miss columns report "
                         "either way so rounds stay comparable")
    ap.add_argument("--max_slots", type=int, default=16)
    ap.add_argument("--page_size", type=int, default=16)
    ap.add_argument("--prefill_chunk", type=int, default=16,
                    help="chunk size for the mixed-length leg's chunked side")
    ap.add_argument("--mixed_long_len", type=int, default=640,
                    help="long-prompt length joining mid-stream in the "
                         "mixed-length leg")
    ap.add_argument("--mixed_d_model", type=int, default=256)
    ap.add_argument("--mixed_burst", type=int, default=3,
                    help="long prompts arriving together in each burst")
    ap.add_argument("--mixed_repeats", type=int, default=3,
                    help="repeats per mixed-length leg; min-p99 is reported "
                         "(filters host-noise spikes out of the tail)")
    ap.add_argument("--mixed_n_heads", type=int, default=4)
    ap.add_argument("--skip_mixed", action="store_true",
                    help="skip the mixed-length chunked-prefill leg")
    ap.add_argument("--tp", default="1,2,4",
                    help="tensor-parallel leg (ISSUE 12): comma list of TP "
                         "sizes, each run in a child with that many forced "
                         "host devices over identical geometry; empty "
                         "string skips the leg")
    ap.add_argument("--tp_n_heads", type=int, default=4,
                    help="head count for the --tp leg (must divide by every "
                         "TP size; the main grid keeps --n_heads)")
    ap.add_argument("--skip_tp", action="store_true",
                    help="skip the tensor-parallel leg")
    ap.add_argument("--replicas", default="1,3",
                    help="router-fleet leg (ISSUE 15): comma list of replica "
                         "counts served through the router at "
                         "--replica_streams streams; empty string skips")
    ap.add_argument("--replica_streams", type=int, default=64,
                    help="concurrent closed-loop streams through the router")
    ap.add_argument("--replicas_requests", type=int, default=192,
                    help="total requests per replica-count leg")
    ap.add_argument("--replicas_d_model", type=int, default=128,
                    help="model width for the --replicas leg: the engines "
                         "must dominate dispatch overhead for the scaling "
                         "gate to measure replica parallelism")
    ap.add_argument("--skip_replicas", action="store_true",
                    help="skip the router-fleet replica-scaling leg")
    ap.add_argument("--speculate_k", type=int, default=8,
                    help="draft length for the speculative single-stream leg "
                         "and the streaming leg's engine (ISSUE 16)")
    ap.add_argument("--spec_requests", type=int, default=8,
                    help="requests in the single-stream speculative leg")
    ap.add_argument("--spec_max_new", type=int, default=64,
                    help="tokens per request in the speculative leg (long "
                         "enough to amortize prefill out of the ratio, and "
                         "for the greedy continuation to settle into the "
                         "self-similar tail the drafter feeds on)")
    ap.add_argument("--spec_vocab", type=int, default=32,
                    help="vocab for the speculative leg's own model (narrow "
                         "= high-overlap greedy continuations)")
    ap.add_argument("--spec_repeats", type=int, default=2,
                    help="repeats per speculative leg; best tokens/sec is "
                         "compared (filters host noise out of the ratio)")
    ap.add_argument("--skip_spec", action="store_true",
                    help="skip the single-stream speculative-decoding leg")
    ap.add_argument("--prefix_requests", type=int, default=24,
                    help="user turns in the shared-prefix leg (ISSUE 19)")
    ap.add_argument("--prefix_prefixes", type=int, default=4,
                    help="distinct system prompts the turns cycle over")
    ap.add_argument("--prefix_len", type=int, default=56,
                    help="shared system-prompt length in tokens")
    ap.add_argument("--prefix_suffix", type=int, default=8,
                    help="per-user unique suffix length in tokens")
    ap.add_argument("--prefix_chunk", type=int, default=8,
                    help="prefill chunk for the prefix leg (a warm request "
                         "pays ONE chunk: its own suffix)")
    ap.add_argument("--prefix_page_size", type=int, default=8,
                    help="KV page size for the prefix leg (the aliasing "
                         "granularity)")
    ap.add_argument("--prefix_max_new", type=int, default=8)
    ap.add_argument("--prefix_gate_x", type=float, default=3.0,
                    help="required prefill-steps AND warm-TTFT reduction "
                         "factor, cache on vs off")
    ap.add_argument("--skip_prefix", action="store_true",
                    help="skip the shared-prefix KV-cache leg")
    ap.add_argument("--stream_counts", default="1,16,64",
                    help="stream counts for the push-vs-poll round-trips "
                         "leg; empty string skips")
    ap.add_argument("--stream_max_new", type=int, default=24)
    ap.add_argument("--skip_streaming", action="store_true",
                    help="skip the push-vs-poll streaming leg")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--n_layers", type=int, default=2)
    ap.add_argument("--d_model", type=int, default=64)
    ap.add_argument("--n_heads", type=int, default=2)
    ap.add_argument("--_child_tp", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args._child_tp:
        run_tp_child(args)
        return

    from paddle_tpu.serving.model import LMConfig
    from paddle_tpu.serving.workload import make_prompts

    cfg = LMConfig(vocab=args.vocab)
    # mixed lengths across BOTH buckets (16 and 32): the zero-recompile gate
    # is only meaningful on a shape-diverse stream
    prompts = make_prompts(
        args.requests, lengths=(5, 11, 16, 23, 32), vocab=args.vocab,
        bos_id=cfg.bos_id, seed=0,
    )

    results = []
    token_sets = {}
    for n in [int(x) for x in args.streams.split(",") if x.strip()]:
        res, tokens = run_one(args, n, prompts)
        results.append(res)
        token_sets[n] = tokens
        print(
            f"[serving_bench] streams={n}: {res['tokens_per_sec']} tok/s "
            f"p50={res['p50_latency_ms']}ms p99={res['p99_latency_ms']}ms "
            f"p999={res['p999_latency_ms']}ms "
            f"deadline_misses={res['deadline_misses']} "
            f"recompiles={res['decode_recompiles_after_warmup']}",
            file=sys.stderr,
        )

    by_n = {r["concurrency"]: r for r in results}
    base = by_n.get(1)
    for r in results:
        if base is not None and base["tokens_per_sec"] > 0:
            r["speedup_vs_sequential"] = round(
                r["tokens_per_sec"] / base["tokens_per_sec"], 2
            )
    # continuous batching must be RESULT-transparent, not just fast: every
    # concurrency level produced identical tokens for every request
    consistent = all(t == token_sets[min(token_sets)] for t in token_sets.values())
    speedup_16 = by_n.get(16, {}).get("speedup_vs_sequential", 0.0)
    mixed = None if args.skip_mixed else run_mixed_length(args)
    spec = (
        None if (args.skip_spec or args.speculate_k <= 0)
        else run_speculative(args)
    )
    prefix = None if args.skip_prefix else run_prefix(args)
    streaming = (
        None if (args.skip_streaming or not args.stream_counts.strip())
        else run_streaming(args)
    )
    tp = None if (args.skip_tp or not args.tp.strip()) else run_tp(args)
    replicas = (
        None if (args.skip_replicas or not args.replicas.strip())
        else run_replicas(args)
    )
    gates = {
        "speedup_16_vs_sequential": speedup_16,
        "speedup_16_ge_3x": bool(speedup_16 >= 3.0),
        "zero_decode_recompiles": all(
            r["decode_recompiles_after_warmup"] == 0 for r in results
        ),
        "batching_bitwise_transparent": bool(consistent),
    }
    ok = gates["speedup_16_ge_3x"] and gates["zero_decode_recompiles"] and consistent
    if mixed is not None:
        gates["mixed_chunked_itl_le_half_whole"] = mixed["chunked_itl_le_half"]
        gates["mixed_chunked_result_transparent"] = (
            mixed["chunked_result_transparent"]
        )
        gates["mixed_zero_decode_recompiles"] = mixed["zero_decode_recompiles"]
        ok = (ok and mixed["chunked_itl_le_half"]
              and mixed["chunked_result_transparent"]
              and mixed["zero_decode_recompiles"])
    if spec is not None:
        gates["spec_single_stream_speedup"] = spec["single_stream_speedup"]
        gates["spec_speedup_ge_2x"] = spec["spec_speedup_ge_2x"]
        gates["spec_tokens_identical"] = spec["spec_tokens_identical"]
        gates["spec_one_verify_signature"] = spec["spec_one_verify_signature"]
        gates["spec_acceptance_rate"] = (
            spec["speculative"]["spec_acceptance_rate"]
        )
        ok = (ok and spec["spec_speedup_ge_2x"]
              and spec["spec_tokens_identical"]
              and spec["spec_one_verify_signature"]
              and spec["spec_zero_decode_recompiles"])
    if prefix is not None:
        gates["prefix_prefill_steps_ratio"] = prefix["prefill_steps_ratio"]
        gates["prefix_ttft_warm_ratio"] = prefix["ttft_warm_ratio"]
        gates["prefix_steps_ge_gate"] = prefix["prefix_steps_ge_gate"]
        gates["prefix_ttft_ge_gate"] = prefix["prefix_ttft_ge_gate"]
        gates["prefix_tokens_identical"] = prefix["prefix_tokens_identical"]
        gates["prefix_sampled_tokens_identical"] = (
            prefix["prefix_sampled_tokens_identical"]
        )
        gates["prefix_zero_page_leak"] = prefix["prefix_zero_page_leak"]
        gates["prefix_one_decode_signature"] = (
            prefix["prefix_one_decode_signature"]
        )
        gates["prefix_hit_rate"] = prefix["cached"]["prefix_hit_rate"]
        ok = (ok and prefix["prefix_steps_ge_gate"]
              and prefix["prefix_ttft_ge_gate"]
              and prefix["prefix_tokens_identical"]
              and prefix["prefix_sampled_tokens_identical"]
              and prefix["prefix_zero_page_leak"]
              and prefix["prefix_one_decode_signature"])
    if streaming is not None:
        gates["push_round_trips_below_poll_all"] = (
            streaming["push_round_trips_below_poll_all"]
        )
        ok = ok and streaming["push_round_trips_below_poll_all"]
    if tp is not None:
        gates.update(tp["gates"])
        ok = (ok and tp["gates"]["tp_tokens_identical"]
              and tp["gates"]["tp_zero_decode_recompiles"]
              and all(v for k, v in tp["gates"].items()
                      if k.endswith(("_pool_bytes_exact",
                                     "_param_bytes_reduced_enough"))))
    if replicas is not None:
        gates.update(replicas["gates"])
        # the scaling gate only votes when armed (>= 3 host cores); None =
        # structurally unmeasurable on this host, recorded not failed
        ok = (ok and replicas["gates"].get("replicas_all_completed", True)
              and replicas["gates"].get("replicas_zero_failovers", True)
              and replicas["gates"].get("replicas3_speedup_ge_2x") is not False)
    print(json.dumps({
        "metric": "serving_bench",
        "value": speedup_16,
        "unit": "x tokens/sec vs sequential @16 streams",
        "all_gates_pass": bool(ok),
        "gates": gates,
        "results": results,
        "mixed_length": mixed,
        "speculative": spec,
        "prefix_cache": prefix,
        "streaming": streaming,
        "tensor_parallel": tp,
        "router_replicas": replicas,
    }))


if __name__ == "__main__":
    main()
