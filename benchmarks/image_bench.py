"""Image-model training benchmark — reference benchmark/paddle/image parity
(alexnet.py / googlenet.py / vgg.py / smallnet_mnist_cifar.py; the
BASELINE.md ms/batch tables).

Usage:
  python benchmarks/image_bench.py --model alexnet --batch_sizes 64,128
  python benchmarks/image_bench.py --model resnet50 --image 224

Prints one JSON line per (model, batch) with ms/batch on the active backend.
"""

from __future__ import annotations

import argparse
import json
import time


def run_one(model_name: str, batch_size: int, image: int, steps: int, warmup: int):
    import jax
    import numpy as np

    from paddle_tpu import models
    from paddle_tpu.nn.graph import Network, reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    builders = {
        "alexnet": lambda: models.alexnet(image_size=image),
        "googlenet": lambda: models.googlenet(image_size=image),
        "vgg16": lambda: models.vgg16(image_size=image),
        "vgg19": lambda: models.vgg19(image_size=image),
        "resnet50": lambda: models.resnet50(image_size=image),
        "smallnet": lambda: models.lenet(),
    }
    img, label, logits, cost = builders[model_name]()
    trainer = SGDTrainer(cost, SGD(learning_rate=0.01, momentum=0.9))
    rs = np.random.RandomState(0)
    ishape = tuple(img.shape)
    batch = {
        img.name: rs.randn(batch_size, *ishape).astype(np.float32),
        label.name: rs.randint(0, 10, batch_size),
    }
    batch = jax.device_put(batch)  # keep tunnel H2D out of the timing
    trainer.init_state(batch)
    step = trainer._make_step()
    from paddle_tpu.core.benchmark import time_train_steps

    sec, _ = time_train_steps(step, trainer.state, batch, steps, warmup)
    ms = sec * 1e3
    print(json.dumps({
        "model": model_name, "batch_size": batch_size, "image": image,
        "ms_per_batch": round(ms, 3),
        "images_per_sec": round(batch_size / (ms / 1e3), 1),
        "backend": jax.default_backend(),
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="alexnet")
    ap.add_argument("--batch_sizes", default="64")
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    args = ap.parse_args()
    for bs in [int(b) for b in args.batch_sizes.split(",")]:
        run_one(args.model, bs, args.image, args.steps, args.warmup)


if __name__ == "__main__":
    main()
